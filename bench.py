"""Headline benchmark: dist-MNIST training throughput per TPU chip.

North-star metric #1 (BASELINE.md): the reference's only quantitative
claim is the MNIST example at ~10 epochs of 60k samples in 5-10 minutes on
a CPU cluster with Master=1/Worker=1 gloo (`/root/reference/README.md:37`)
— i.e. ~1,333 samples/sec at the midpoint (450 s).  ``vs_baseline`` is
measured throughput per chip divided by that number.

Prints exactly ONE JSON line:
    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Runs on whatever jax.devices() provides (the real TPU chip under the
driver; a CPU mesh locally).
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# ~1,333 samples/s: 10 epochs x 60k samples / 450 s (README.md:37 midpoint)
BASELINE_SAMPLES_PER_SEC = 10 * 60000 / 450.0


def main() -> int:
    import jax
    import jax.numpy as jnp

    from tpujob.workloads import data as datalib
    from tpujob.workloads import distributed as dist
    from tpujob.workloads import mnist, train_lib

    n_chips = max(1, len(jax.devices()))
    pe = dist.process_env()  # the real injected env (one ProcessEnv throughout)
    mesh = dist.make_mesh({"data": -1}, env=pe)

    # -- accuracy parity gate: train on REAL data when available ------------
    # Preference: MNIST IDX files (TPUJOB_MNIST_DIR or ./data) > the offline
    # real UCI handwritten-digits set > synthetic.  The reference gate is
    # FashionMNIST accuracy (examples/mnist/mnist.py:117-132); which dataset
    # actually gated is reported in the JSON line.
    import contextlib
    import io

    data_dir = os.environ.get("TPUJOB_MNIST_DIR") or "data"
    if datalib.resolve_dataset(data_dir, "auto") == "idx":
        gate_argv = ["--data-dir", data_dir, "--dataset", "idx", "--epochs", "1"]
    else:
        try:
            import sklearn  # noqa: F401 - digits needs scikit-learn

            # digits is tiny (~1.7k samples); multiple epochs ~ the
            # reference's 10-epoch training run, still < 2 s
            gate_argv = ["--dataset", "digits", "--epochs", "10"]
        except ImportError:
            gate_argv = ["--dataset", "synthetic", "--train-size", "8192",
                         "--test-size", "2048", "--epochs", "1"]
    acc_args = mnist.build_parser().parse_args(
        gate_argv + ["--dir", "/tmp/tpujob_bench_logs"]
    )
    with contextlib.redirect_stdout(io.StringIO()):  # keep stdout = 1 JSON line
        gate = mnist.run(acc_args, mesh=mesh)
    acc = gate["accuracy"]
    if acc <= 0.8:
        print(f"FAIL: accuracy {acc:.4f} <= 0.8 on {gate['dataset']} "
              "— training is broken", file=sys.stderr)
        return 1

    # -- throughput: big-batch steady-state train steps ---------------------
    batch = 1024 * n_chips
    model = mnist.Net()
    optimizer = train_lib.sgd(0.01, 0.5)
    state = train_lib.init_state(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1,) + datalib.IMAGE_SHAPE)),
        optimizer, mesh,
    )
    # k optimizer steps per dispatch (train_lib.make_multi_step): the
    # tunneled/shared device charges ~100 ms per host round trip, far more
    # than this model's sub-ms step, so a single-step host loop measures
    # dispatch latency, not the TPU.  k=10 amortizes it 10x; exactness vs
    # k sequential single steps is pinned by
    # tests/test_workloads_mnist.py::TestMultiStep.  On CPU (local smoke;
    # the driver's metric is TPU-only) one step takes SECONDS, so shrink
    # the batch and k or the smoke runs for an hour.
    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        batch = 32 * n_chips
    k = 2 if on_cpu else 10
    step = train_lib.make_multi_step(mnist.nll_loss, optimizer, mesh, k=k)
    # multi-host: each process feeds only its local_batch_slice rows, so
    # `batch` stays the GLOBAL batch in the samples/sec arithmetic below
    lo, sz = dist.local_batch_slice(batch, pe)
    x, y = datalib.synthetic_split(batch, seed=0)
    x, y = x[lo : lo + sz], y[lo : lo + sz]
    b = train_lib.put_batch(((x - datalib.MEAN) / datalib.STD, y), mesh)

    from tpujob.workloads.benchlib import measure_windows

    state, loss = step(state, b)  # compile
    jax.block_until_ready(loss)

    def run_one():
        nonlocal state, loss
        state, loss = step(state, b)
        return loss

    # Steady state for >= 5 s in >= 5 windows of ~1 s (method + rationale:
    # tpujob/workloads/benchlib.py).  The stddev is what makes a real
    # regression distinguishable from run-to-run noise — recorded rounds
    # swung 1.78M / 1.60M / 2.04M (-10%/+28%) with no variance reported,
    # so a 20% regression was invisible.  Multi-host runs use a fixed step
    # count per window to keep the collective streams aligned.
    if pe.num_processes > 1:
        # multi-host: ANY wall-clock-bounded loop dispatches unequal
        # collective counts per process (benchlib docstring) — fixed call
        # counts on every platform
        stats = measure_windows(
            run_one, window_s=1.0, min_windows=5, min_total_s=5.0,
            fixed_steps=10 if on_cpu else 50, steps_per_call=k,
        )
    elif on_cpu:
        # local smoke: seconds-per-step silicon — 2 minimal windows prove
        # the contract (one JSON line, all fields), not the throughput
        stats = measure_windows(
            run_one, window_s=0.5, min_windows=2, min_total_s=1.0,
            min_steps_per_window=2, steps_per_call=k,
        )
    else:
        stats = measure_windows(
            run_one, window_s=1.0, min_windows=5, min_total_s=5.0,
            steps_per_call=k,
        )
    steps, wall = stats.steps, stats.wall_s
    mean_ms, std_ms = stats.mean_s * 1e3, stats.std_s * 1e3
    sps_per_chip = steps * batch / wall / n_chips
    print(json.dumps({
        "metric": "mnist_train_samples_per_sec_per_chip",
        "value": round(sps_per_chip, 1),
        "unit": "samples/s/chip",
        "vs_baseline": round(sps_per_chip / BASELINE_SAMPLES_PER_SEC, 2),
        "accuracy": round(float(acc), 4),
        "gate_dataset": gate["dataset"],
        "chips": n_chips,
        "platform": jax.devices()[0].platform,
        "steps": steps,
        "wall_s": round(wall, 3),
        "step_ms_mean": round(mean_ms, 4),
        "step_ms_std": round(std_ms, 4),
        "step_ms_cv_pct": round(100.0 * std_ms / mean_ms, 1),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
