# Operator image: builds tpujob/operator:latest referenced by
# manifests/base/deployment.yaml (reference: /root/reference/Dockerfile:1-16,
# a 2-stage golang -> ubi8 build of the operator binary; here the compiled
# artifact is the native controller kernel, built in a toolchain stage and
# copied into a slim runtime image with the Python operator).
#
#   docker build -t tpujob/operator:latest .

FROM python:3.12-slim AS build-image

RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*

ADD native/ /src/native/
WORKDIR /src
RUN make -C native TARGET=/src/libtpujob_native.so

FROM python:3.12-slim

# Runtime deps: pyyaml parses kubeconfigs + manifests.  --apiserver=kube is
# served by the self-contained REST transport (tpujob/kube/kubetransport.py)
# — no generated client library; the control plane is otherwise stdlib-only.
RUN pip install --no-cache-dir pyyaml

COPY tpujob/ /app/tpujob/
COPY --from=build-image /src/libtpujob_native.so /app/tpujob/runtime/libtpujob_native.so

# bake the build SHA for `--version` (version.go:27-40 analog):
#   docker build --build-arg GIT_SHA=$(git rev-parse --short HEAD) ...
ARG GIT_SHA=unknown
ENV TPUJOB_GIT_SHA=$GIT_SHA

WORKDIR /app
ENV PYTHONPATH=/app PYTHONUNBUFFERED=1

ENTRYPOINT ["python", "-m", "tpujob.server", "--apiserver=kube"]
