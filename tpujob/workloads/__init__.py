"""TPU-native example workloads (the reference's ``examples/`` layer).

These are the containers a TPUJob schedules: they consume the environment
the controller injects (``tpujob/controller/tpu_env.py``) the same way the
reference workloads consume ``MASTER_ADDR``/``WORLD_SIZE``/``RANK``
(``examples/mnist/mnist.py:100-138``, ``examples/smoke-dist/dist_sendrecv.py``)
— but rendezvous through the JAX/PJRT distributed coordinator and run SPMD
over a ``jax.sharding.Mesh`` instead of DistributedDataParallel over
gloo/NCCL.
"""
