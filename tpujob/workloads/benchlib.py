"""Shared steady-state measurement for the benchmarks.

One implementation of the windowed dispatch/drain timing used by both
``bench.py`` (the driver headline metric) and ``bench_models.py`` (the
flagship models), so the two can never silently measure differently.

Method: N async windows, each dispatching steps without syncing and then
draining (``jax.block_until_ready``) INSIDE its own wall time — a window is
an honest end-to-end throughput sample.  Windows, not per-step or
small-chunk syncing: one device sync over the tunneled connection costs
~100 ms, orders of magnitude more than a step, so fine-grained syncing
measures the tunnel, not the TPU.  The across-window stddev is what makes
a real regression distinguishable from the shared device's 10-30%
run-to-run noise.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, List, Optional


@dataclass
class WindowStats:
    steps: int          # total steps across all windows
    wall_s: float       # total measured wall time (drains included)
    mean_s: float       # sample mean of per-window seconds-per-step
    std_s: float        # sample stddev of per-window seconds-per-step
    per_window_s: List[float]  # seconds-per-step of each window

    @property
    def throughput_steps_per_s(self) -> float:
        return self.steps / self.wall_s


def measure_windows(
    run_step: Callable[[], object],
    *,
    window_s: float = 1.0,
    min_windows: int = 5,
    min_total_s: float = 5.0,
    min_steps_per_window: int = 5,
    fixed_steps: Optional[int] = None,
    steps_per_call: int = 1,
) -> WindowStats:
    """Time ``run_step`` (dispatch one async step, return something to
    drain on) in windows of ~``window_s`` seconds.

    ``fixed_steps``: run exactly that many steps per window, and exactly
    ``min_windows`` windows (``min_total_s`` is ignored) — REQUIRED for
    multi-process benchmarks, where ANY wall-clock-bounded loop (step
    count or window count) dispatches unequal collective counts per
    process and desynchronizes the streams (mispaired or hanging
    all-reduces).

    ``steps_per_call``: optimizer steps one ``run_step`` call performs
    (``train_lib.make_multi_step`` dispatch); reported steps and per-step
    times account for it.  ``fixed_steps`` still counts calls.
    """
    import jax

    if fixed_steps is not None and fixed_steps <= 0:
        raise ValueError(f"fixed_steps must be positive, got {fixed_steps}")
    if steps_per_call <= 0:
        raise ValueError(f"steps_per_call must be positive, got {steps_per_call}")

    windows: List[tuple] = []  # (steps, seconds)
    t0 = time.perf_counter()
    while (
        len(windows) < min_windows
        if fixed_steps is not None  # deterministic window count
        else (time.perf_counter() - t0 < min_total_s
              or len(windows) < min_windows)
    ):
        w0 = time.perf_counter()
        w_steps = 0
        drain = None
        while (w_steps < fixed_steps if fixed_steps is not None
               else (time.perf_counter() - w0 < window_s
                     or w_steps < min_steps_per_window)):
            drain = run_step()
            w_steps += 1
        jax.block_until_ready(drain)  # drain inside the window
        windows.append((w_steps, time.perf_counter() - w0))
    wall = time.perf_counter() - t0

    per_step = [s / (w * steps_per_call) for w, s in windows]
    mean = sum(per_step) / len(per_step)
    std = (
        (sum((s - mean) ** 2 for s in per_step) / (len(per_step) - 1)) ** 0.5
        if len(per_step) > 1 else 0.0
    )
    return WindowStats(
        steps=sum(w for w, _ in windows) * steps_per_call,
        wall_s=wall,
        mean_s=mean,
        std_s=std,
        per_window_s=per_step,
    )
