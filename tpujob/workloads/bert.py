"""BERT-large masked-LM pretraining workload (BASELINE.md: "BERT-large
pretrain survives preemption" on preemptible TPU-VM workers).

The reference ships no BERT code — this is the user-container workload for
the driver's preemption config, built TPU-first:

- **DP × TP × SP sharding**: parameters are annotated with rule-based
  PartitionSpecs (``PARTITION_RULES`` below, applied via
  ``tpujob.workloads.parallel.shard_params``) — QKV and
  MLP-in kernels column-split on the ``tensor`` axis, projection and MLP-out
  row-split, embeddings vocab-split — and XLA/GSPMD derives every
  collective.  No hand-written all-reduces.
- **Long context**: when the mesh carries a ``sequence`` axis, attention
  runs as ring attention (``parallel.ring_attention``): K/V blocks rotate
  over ICI while each device attends its local Q shard — O(S/n) activation
  memory.
- **Preemption resilience**: checkpoint every ``--checkpoint-interval``
  steps via ``train_lib.Checkpointer``; on restart (controller restartPolicy
  OnFailure, exit-code-classified retry) training resumes from the latest
  step.

Entrypoint:
    python -m tpujob.workloads.bert --steps 100 --layers 24
"""
from __future__ import annotations

import argparse
import dataclasses
import os
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from tpujob.workloads import data as datalib
from tpujob.workloads import distributed as dist
from tpujob.workloads import parallel, train_lib


# GSPMD partition rules: regex on the '/'-joined param path -> spec.
# Column-parallel (split output dim) for QKV and MLP-in; row-parallel
# (split input dim) for the attention projection and MLP-out; embeddings
# split on vocab.  The Megatron layout, expressed as annotations.
#
# Each kernel's complementary dim additionally shards over "fsdp" (the
# ZeRO-3 layout: params+moments live sharded, XLA all-gathers per layer on
# use and reduce-scatters grads — all derived from these annotations).
# `sanitize_spec` drops axes the mesh doesn't carry, so one table serves
# DP, TP, FSDP, and TP x FSDP meshes unchanged.
PARTITION_RULES = (
    (r"attn/(query|key|value)/kernel", P("fsdp", "tensor")),
    (r"attn/(query|key|value)/bias", P("tensor")),
    (r"attn/out/kernel", P("tensor", "fsdp")),
    (r"mlp_wi/kernel", P("fsdp", "tensor")),
    (r"mlp_wi/bias", P("tensor")),
    (r"mlp_wo/kernel", P("tensor", "fsdp")),
    # Embeddings: vocab split over tensor AND fsdp (the padded vocab is a
    # multiple of 128, so both divide).  Sharding the hidden dim over fsdp
    # instead would make every lookup emit a hidden-over-fsdp activation
    # that must reshard to the batch layout — the "involuntary full
    # rematerialization" the SPMD partitioner warns about on fsdp x tensor
    # meshes.  Vocab-dim sharding keeps the same ZeRO memory win and lets
    # the lookup resolve as masked-gather + psum with batch-sharded output.
    (r"token_embed/embedding", P(("tensor", "fsdp"), None)),
    (r"pos_embed", P("fsdp", None)),
    # MoE: experts split over the expert axis, each expert's FFN optionally
    # Megatron-split over tensor; the router stays replicated (it is tiny
    # and every token needs it)
    (r"moe/wi", P("expert", "fsdp", "tensor")),
    (r"moe/wo", P("expert", "tensor", "fsdp")),
    (r"moe/router", P()),
)


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """Static MoE hyperparameters threaded through the module tree (frozen
    so flax module attributes stay hashable)."""

    experts: int
    k: int = 2
    capacity_factor: float = 1.25
    mesh: Any = None


class MoEMlp(nn.Module):
    """Sparse MoE FFN block (the `ep` strategy — see `parallel.moe_ffn`)."""

    hidden: int
    intermediate: int
    cfg: MoEConfig
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, valid=None):
        e = self.cfg.experts
        router = self.param("router", nn.initializers.normal(0.02),
                            (self.hidden, e), jnp.float32)
        wi = self.param("wi", nn.initializers.lecun_normal(),
                        (e, self.hidden, self.intermediate))
        wo = self.param("wo", nn.initializers.lecun_normal(),
                        (e, self.intermediate, self.hidden))
        y, metrics = parallel.moe_ffn(
            x, router, wi.astype(self.dtype), wo.astype(self.dtype),
            self.cfg.mesh, k=self.cfg.k,
            capacity_factor=self.cfg.capacity_factor, valid=valid,
        )
        self.sow("moe_metrics", "load_balance", metrics["load_balance"])
        self.sow("moe_metrics", "router_z", metrics["router_z"])
        return y


def rope(x: jax.Array, positions: jax.Array, theta: float = 10000.0):
    """Rotary position embedding (GPT-NeoX half-split convention).

    ``x``: [batch, seq, heads, head_dim]; ``positions``: [seq] absolute
    token positions.  Each head-dim pair (i, i + d/2) rotates by
    pos · theta^(-2i/d) — relative offsets then appear as phase
    differences inside q·k, which is why RoPE extrapolates and composes
    with every attention path here: it is applied to q/k BEFORE the
    attention fn, so ring/Ulysses sharding and the flash kernel see
    ordinary tensors.
    """
    d = x.shape[-1]
    half = d // 2
    freqs = positions[:, None].astype(jnp.float32) * (
        theta ** (-jnp.arange(half, dtype=jnp.float32) / half))  # [s, d/2]
    cos = jnp.cos(freqs)[None, :, None, :]
    sin = jnp.sin(freqs)[None, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(
        jnp.float32)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class Attention(nn.Module):
    hidden: int
    heads: int
    dtype: Any = jnp.float32
    attention_fn: Optional[Callable] = None  # None => dense attention
    # autoregressive decode: keep K/V for past positions in a mutable
    # 'cache' collection and attend the single new token against them
    # (gpt.generate_cached); 0 = training mode
    cache_len: int = 0
    # grouped-query attention: project K/V to this many heads (must
    # divide heads); each KV head serves heads/kv_heads query heads.
    # The KV cache and the ring-rotated K/V shrink by the same factor;
    # compute paths see full heads via a broadcast repeat.  None = MHA.
    kv_heads: Optional[int] = None
    # rotary position embedding on q/k (positions come from the decode
    # cursor in cached mode; the cache stores rotated keys)
    use_rope: bool = False
    # causal sliding window (0 = full context); the cached decode masks
    # slots behind the window so it matches windowed training exactly
    window: int = 0

    @nn.compact
    def __call__(self, x):
        d = self.hidden // self.heads
        hkv = self.kv_heads or self.heads
        rep = self.heads // hkv
        q = nn.Dense(self.hidden, dtype=self.dtype, name="query")(x)
        k = nn.Dense(hkv * d, dtype=self.dtype, name="key")(x)
        v = nn.Dense(hkv * d, dtype=self.dtype, name="value")(x)
        b, s, _ = x.shape
        q = q.reshape(b, s, self.heads, d)
        k = k.reshape(b, s, hkv, d)
        v = v.reshape(b, s, hkv, d)
        if self.use_rope and self.cache_len == 0:
            pos = jnp.arange(s)
            q, k = rope(q, pos), rope(k, pos)
        if self.cache_len > 0:
            if s != 1:
                raise ValueError(
                    f"cached decode feeds one position at a time, got {s}")
            # the cache stores KV heads only — the GQA decode-memory win
            shape = (b, self.cache_len, hkv, d)
            ck = self.variable("cache", "cached_key",
                               lambda: jnp.zeros(shape, k.dtype))
            cv = self.variable("cache", "cached_value",
                               lambda: jnp.zeros(shape, v.dtype))
            ci = self.variable("cache", "cache_index",
                               lambda: jnp.zeros((), jnp.int32))
            i = ci.value
            if self.use_rope:
                # rotate at the decode cursor; the cache then holds
                # already-rotated keys (the standard practice — scores
                # need only the query's rotation at read time)
                pos = jnp.reshape(i, (1,))
                q, k = rope(q, pos), rope(k, pos)
            ck.value = jax.lax.dynamic_update_slice_in_dim(ck.value, k, i, 1)
            cv.value = jax.lax.dynamic_update_slice_in_dim(cv.value, v, i, 1)
            ci.value = i + 1
            scale = d ** -0.5
            # grouped einsum: the rep query heads sharing a KV head attend
            # the cache directly — no materialized rep-times K/V repeat,
            # so the decode-memory win actually holds per step
            qg = q.reshape(b, s, hkv, rep, d)
            sc = jnp.einsum("bqhrd,bkhd->bhrqk", qg, ck.value) * scale
            # causal: only filled cache slots (<= i) are visible — and,
            # with a sliding window, only the trailing `window` of them
            slots = jnp.arange(self.cache_len)[None, None, None, None, :]
            vis = slots <= i
            if self.window:
                vis = jnp.logical_and(vis, i - slots < self.window)
            sc = jnp.where(vis, sc, -1e30)
            p = jax.nn.softmax(sc, axis=-1)
            o = jnp.einsum("bhrqk,bkhd->bqhrd", p, cv.value)
            o = o.reshape(b, s, self.heads, d)
        else:
            # every attention path (dense/flash/ring/ulysses) accepts
            # grouped-query K/V and broadcasts heads AFTER its
            # collectives, so the SP paths move the small tensors
            fn = self.attention_fn or parallel.full_attention
            o = fn(q, k, v)  # [b, s, h, d]
        o = o.reshape(b, s, self.hidden)
        return nn.Dense(self.hidden, dtype=self.dtype, name="out")(o)


class Block(nn.Module):
    hidden: int
    heads: int
    intermediate: int
    dtype: Any = jnp.float32
    attention_fn: Optional[Callable] = None
    moe: Optional[MoEConfig] = None
    cache_len: int = 0
    # mesh for activation sharding annotations (dist.constrain_activation);
    # None inside manual regions (the pipeline's stage_fn), where a
    # sharding constraint would be illegal
    mesh: Any = None
    kv_heads: Optional[int] = None
    use_rope: bool = False
    window: int = 0

    @nn.compact
    def __call__(self, x, valid=None):
        a = Attention(self.hidden, self.heads, self.dtype,
                      self.attention_fn, self.cache_len, self.kv_heads,
                      self.use_rope, self.window, name="attn")(x)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_attn")(x + a)
        if self.moe is not None:
            h = MoEMlp(self.hidden, self.intermediate, self.moe,
                       self.dtype, name="moe")(x, valid)
        else:
            h = nn.Dense(self.intermediate, dtype=self.dtype, name="mlp_wi")(x)
            h = nn.gelu(h)
            h = nn.Dense(self.hidden, dtype=self.dtype, name="mlp_wo")(h)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_mlp")(x + h)
        # pin the block boundary to the batch-sharded layout: without the
        # annotation, GSPMD propagation can pull the QKV/MLP kernels' fsdp
        # contracting-dim sharding backward into the residual stream and
        # pay an involuntary replicate-repartition every step
        return dist.constrain_activation(x, self.mesh)


class Bert(nn.Module):
    """BERT encoder with a tied masked-LM head.

    setup-style (not ``@nn.compact``) so the pipeline-parallel path can run
    the ``embed`` and ``head`` stages separately around the pipelined trunk
    (``pipeline_apply``); param paths are identical to the original compact
    form (token_embed, pos_embed, ln_embed, layer_{i}/...).
    """

    vocab: int = 30522
    hidden: int = 1024  # BERT-large
    layers: int = 24
    heads: int = 16
    intermediate: int = 4096
    max_seq: int = 512
    dtype: Any = jnp.float32
    attention_fn: Optional[Callable] = None
    moe: Optional[MoEConfig] = None
    remat: bool = True
    final_ln: bool = False  # GPT-2-style ln_f before the head
    # >0 = KV-cached autoregressive mode with this cache length
    # (gpt.generate_cached sizes it to the actual decode length, not
    # max_seq, so short decodes don't pay max_seq attention per step)
    decode: int = 0
    # mesh for activation sharding annotations at block boundaries
    mesh: Any = None
    # grouped-query attention: KV heads per layer (None = heads)
    kv_heads: Optional[int] = None
    # rotary position embedding on q/k instead of the learned pos_embed
    # (--position rope): relative offsets as phase differences, the
    # modern long-context default; no pos_embed parameter exists then
    use_rope: bool = False
    # causal sliding-window width (0 = full context)
    window: int = 0

    def setup(self):
        # vocab padded to a multiple of 128 so the vocab-sharded embedding
        # divides any tensor-parallel degree (the Megatron padding trick);
        # logits are sliced back to the true vocab before the loss
        vocab_padded = -(-self.vocab // 128) * 128
        self.token_embed = nn.Embed(vocab_padded, self.hidden, dtype=self.dtype)
        if not self.use_rope:
            self.pos_embed = self.param(
                "pos_embed", nn.initializers.normal(0.02),
                (self.max_seq, self.hidden)
            )
        self.ln_embed = nn.LayerNorm(dtype=self.dtype)
        if self.final_ln:
            self.ln_f = nn.LayerNorm(dtype=self.dtype)
        if self.decode and not self.use_rope:
            # decode cursor for the positional embedding (layer caches
            # track their own index; this one belongs to the trunk)
            self.position = self.variable(
                "cache", "position", lambda: jnp.zeros((), jnp.int32))
        block_cls = Block
        if self.remat and not self.decode:
            # rematerialize each block on backward: HBM for FLOPs, the
            # standard long-context trade (jax.checkpoint)
            block_cls = nn.remat(Block)
        cache_len = self.decode
        for i in range(self.layers):
            setattr(self, f"layer_{i}", block_cls(
                self.hidden, self.heads, self.intermediate, self.dtype,
                self.attention_fn, self.moe, cache_len, self.mesh,
                self.kv_heads, self.use_rope, self.window))

    def embed(self, ids):
        x = self.token_embed(ids)
        if self.use_rope:
            pass  # positions enter at the attention q/k rotation
        elif self.decode:
            # one position per call: index pos_embed at the decode cursor
            pos = jax.lax.dynamic_slice_in_dim(
                self.pos_embed, self.position.value, 1, 0)
            self.position.value = self.position.value + 1
            x = x + pos[None].astype(self.dtype)
        else:
            x = x + self.pos_embed[None, : ids.shape[1]].astype(self.dtype)
        return dist.constrain_activation(self.ln_embed(x), self.mesh)

    def head(self, x):
        if self.final_ln:
            x = self.ln_f(x)
        # tied LM head: logits through the embedding transpose
        return self.token_embed.attend(x.astype(jnp.float32))[..., : self.vocab]

    def __call__(self, ids, valid=None):
        # ``valid`` ([batch, seq] 0/1, optional) marks positions that are
        # real tokens; MoE routing skips the rest so a fixed decode buffer
        # stays causal (see ``parallel.moe_ffn``).  Dense models ignore it
        # (the causal attention mask already makes padding inert).
        x = self.embed(ids)
        for i in range(self.layers):
            x = getattr(self, f"layer_{i}")(x, valid)
        return self.head(x)


def pipeline_apply(model: Bert, params, ids, mesh, num_microbatches: int):
    """Forward pass with the trunk run as a GPipe pipeline over the mesh
    ``pipeline`` axis (`parallel.pipeline`); embed/head stay data-parallel
    outside the manual region.  Layer params are restacked from the
    standard per-layer tree each call, so the train state (and checkpoints)
    are layout-identical to the non-pipelined model."""
    x = model.apply(params, ids, method="embed")
    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *(params["params"][f"layer_{i}"] for i in range(model.layers)),
    )
    blk = Block(model.hidden, model.heads, model.intermediate, model.dtype,
                model.attention_fn, model.moe, kv_heads=model.kv_heads,
                use_rope=model.use_rope)
    apply_one = lambda p, xb: blk.apply({"params": p}, xb)
    if model.remat:
        apply_one = jax.checkpoint(apply_one)

    def stage_fn(local_stack, xb):
        return jax.lax.scan(lambda c, p: (apply_one(p, c), None),
                            xb, local_stack)[0]

    x = parallel.pipeline(stage_fn, stacked, x, mesh,
                          num_microbatches=num_microbatches)
    return model.apply(params, x, method="head")


def make_1f1b_value_and_grad(model: Bert, mesh, num_microbatches: int,
                             head_loss, preprocess,
                             batch_shards: Optional[int] = None):
    """(params, batch) -> (loss, grads) via the interleaved 1F1B schedule
    (``pipeline_schedule.pipeline_1f1b``) — fused forward+backward with
    the stash bound ~n microbatches instead of m.

    ``preprocess(batch) -> (input_ids, extra)`` produces the trunk input
    and the per-row arrays the loss needs; ``head_loss(logits_mb,
    extra_mb) -> scalar`` must be scaled so its mean over microbatches
    and batch shards IS the step loss (see the workload ``run()``s).
    Embed and head run outside the pipelined region; their grads come
    from explicit VJPs and merge with the per-stage stack grads (tied
    embeddings accumulate from both sides).
    """
    from tpujob.workloads import pipeline_schedule

    blk = Block(model.hidden, model.heads, model.intermediate, model.dtype,
                model.attention_fn, model.moe, kv_heads=model.kv_heads,
                use_rope=model.use_rope)

    def stage_fn(local_stack, xb):
        # no remat wrapper: the 1F1B backward tick already recomputes its
        # stage forward under jax.vjp, and residuals live only within the
        # tick (checkpointing here would recompute twice)
        return jax.lax.scan(lambda c, p: (blk.apply({"params": p}, c), None),
                            xb, local_stack)[0]

    def vag(params, batch):
        ids_in, extra = preprocess(batch)
        p = params["params"]
        # every non-layer param in one tree: flax setup() registers the
        # trunk params eagerly, so partial trees must carry them all (the
        # unused ones just get zero grads from each vjp)
        outer = {"params": {k: v for k, v in p.items()
                            if not k.startswith("layer_")}}
        x, vjp_embed = jax.vjp(
            lambda pt: model.apply(pt, ids_in, method="embed"), outer)
        stacked = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *(p[f"layer_{i}"] for i in range(model.layers)))
        head_fn = lambda ht, y, ex: head_loss(
            model.apply(ht, y, method="head"), ex)
        loss, dstack, dhead, dx = pipeline_schedule.pipeline_1f1b(
            stage_fn, stacked, x, head_fn, outer, extra, mesh,
            num_microbatches=num_microbatches, batch_shards=batch_shards)
        dembed = vjp_embed(dx.astype(x.dtype))[0]
        gp = {k: jax.tree.map(jnp.zeros_like, v) for k, v in p.items()}
        for src in (dembed["params"], dhead["params"]):
            for k, v in src.items():
                gp[k] = jax.tree.map(jnp.add, gp[k], v)
        for i in range(model.layers):
            gp[f"layer_{i}"] = jax.tree.map(
                lambda g, d, i=i: g + d[i], gp[f"layer_{i}"], dstack)
        return loss, {"params": gp}

    return vag


def _mean_sown(tree, name) -> Any:
    """Mean of every sown leaf whose key path contains ``name`` (one value
    per MoE layer; the mean keeps loss coefficients depth-independent)."""
    vals = [leaf for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
            if any(getattr(p, "key", None) == name for p in path)]
    return sum(vals) / len(vals) if vals else jnp.zeros(())


def mlm_loss(model: Bert, aux_coef: float = 0.01, z_coef: float = 1e-3,
             apply_fn: Optional[Callable] = None, mask_id: int = 103):
    """Masked-LM: mask 15% of positions deterministically per step-seed,
    predict the original ids.  MoE models add the load-balance aux loss and
    router z-loss collected from the ``moe_metrics`` collection.
    ``apply_fn(params, ids) -> logits`` overrides the forward (the
    pipeline-parallel path plugs ``pipeline_apply`` in here).
    ``mask_id``: the [MASK] token — 103 (the WordPiece id) for synthetic
    vocabularies; byte-level corpora use 256 so a literal 0x67 byte is
    never confused with a masked position (see ``run``)."""

    def loss_fn(params, batch):
        ids, mask = batch  # mask: 1.0 where position is masked/predicted
        masked_ids = jnp.where(mask > 0, jnp.int32(mask_id), ids)
        if apply_fn is not None:
            logits, sown = apply_fn(params, masked_ids), {}
        elif model.moe is not None:
            logits, sown = model.apply(params, masked_ids,
                                       mutable=["moe_metrics"])
        else:
            logits, sown = model.apply(params, masked_ids), {}
        logp = jax.nn.log_softmax(logits)
        tok_ll = jnp.take_along_axis(logp, ids[..., None], axis=-1)[..., 0]
        loss = -(tok_ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        if sown:
            loss = (loss + aux_coef * _mean_sown(sown, "load_balance")
                    + z_coef * _mean_sown(sown, "router_z"))
        return loss

    return loss_fn


def mask_batch(ids: np.ndarray, seed: int, rate: float = 0.15):
    rng = np.random.RandomState(seed)
    mask = (rng.rand(*ids.shape) < rate).astype(np.float32)
    return ids, mask


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU-native BERT-large MLM pretrain")
    p.add_argument("--vocab", type=int, default=30522)
    p.add_argument("--hidden", type=int, default=1024)
    p.add_argument("--layers", type=int, default=24)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--kv-heads", type=int, default=0,
                   help="grouped-query attention: project K/V to this "
                        "many heads (must divide --heads; 0 = MHA). The "
                        "KV cache and ring-rotated K/V shrink by "
                        "heads/kv-heads")
    p.add_argument("--position", choices=["learned", "rope"],
                   default="learned",
                   help="positional encoding: learned absolute embedding "
                        "(BERT/GPT-2 style) or rotary on q/k (RoPE - "
                        "relative phases, the long-context default; "
                        "composes with every attention path since it is "
                        "applied before the attention fn)")
    p.add_argument("--intermediate", type=int, default=4096)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=32, help="global batch")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--lr-schedule", choices=["constant", "cosine"],
                   default="constant",
                   help="constant (optionally warmed up) or cosine decay "
                        "to 0 at --steps")
    p.add_argument("--warmup-steps", type=int, default=0,
                   help="linear LR warmup from 0 over this many steps")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="average grads over this many mini-steps per "
                        "optimizer update (effective batch multiplier)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--bf16", action="store_true", default=True)
    p.add_argument("--no-bf16", dest="bf16", action="store_false")
    p.add_argument("--tensor-parallel", type=int, default=1,
                   help="size of the tensor axis")
    p.add_argument("--sequence-parallel", type=int, default=1,
                   help="size of the sequence-parallel axis")
    p.add_argument("--sp-mode", choices=["ring", "ulysses"], default="ring",
                   help="sequence-parallel attention: ring (ppermute K/V, "
                        "composes with TP, O(S/n) memory) or ulysses "
                        "(all-to-all, 2 collectives, full S per device)")
    p.add_argument("--attention", choices=["dense", "flash"], default="dense",
                   help="local attention kernel: dense (XLA) or flash "
                        "(Pallas, VMEM-resident softmax; non-SP path)")
    p.add_argument("--attention-window", type=int, default=0,
                   help="causal sliding-window attention: each query sees "
                        "at most this many trailing positions (0 = full "
                        "context). O(S*window) attention FLOPs - with "
                        "--attention flash whole out-of-window blocks are "
                        "skipped. Causal (gpt) family only; not with "
                        "--sequence-parallel")
    p.add_argument("--moe-experts", type=int, default=0,
                   help="replace each FFN with a sparse MoE of this many "
                        "experts (0 = dense)")
    p.add_argument("--moe-k", type=int, default=2,
                   help="experts routed per token")
    p.add_argument("--moe-capacity-factor", type=float, default=1.25,
                   help="per-expert buffer slack over perfect balance")
    p.add_argument("--expert-parallel", type=int, default=1,
                   help="size of the expert mesh axis (experts sharded "
                        "across it; GSPMD derives the all-to-alls)")
    p.add_argument("--pipeline-parallel", type=int, default=1,
                   help="size of the pipeline mesh axis; the layer stack "
                        "splits into this many GPipe stages (composes with "
                        "data parallelism)")
    p.add_argument("--pipeline-microbatches", type=int, default=0,
                   help="microbatches streamed through the pipeline "
                        "(0 = one per stage; more amortizes the bubble)")
    p.add_argument("--pipeline-schedule", choices=["gpipe", "1f1b"],
                   default="gpipe",
                   help="gpipe: forward schedule + jax.grad transpose "
                        "(activation stash grows with microbatches); "
                        "1f1b: interleaved forward/backward with explicit "
                        "per-stage VJPs — stash bounded by the stage "
                        "count, independent of microbatches")
    p.add_argument("--fsdp", type=int, default=1,
                   help="size of the fsdp mesh axis: ZeRO-3-style sharding "
                        "of params and optimizer moments (batch also splits "
                        "over it; composes with --tensor-parallel, "
                        "--sequence-parallel, and --moe-experts)")
    p.add_argument("--no-remat", dest="remat", action="store_false", default=True)
    p.add_argument("--log-interval", type=int, default=20)
    train_lib.add_profile_flags(p)
    p.add_argument("--checkpoint-interval", type=int, default=0,
                   help="steps between checkpoints; 0 disables")
    p.add_argument("--data-file", default=None,
                   help="train on this file's raw bytes as a byte-level "
                        "corpus instead of synthetic tokens (vocab >= 256; "
                        "the MLM objective needs >= 257 — id 256 is "
                        "[MASK]); batches cycle the chunks "
                        "deterministically per step")
    p.add_argument("--tokenizer", default=None, metavar="bpe:PATH[:V]",
                   help="tokenize --data-file with a trained byte-level "
                        "BPE instead of raw bytes: 'bpe:PATH' loads PATH; "
                        "'bpe:PATH:V' additionally trains a V-id tokenizer "
                        "on the corpus and saves it to PATH when missing. "
                        "Tokens cache to a memory-mapped sidecar next to "
                        "the corpus")
    p.add_argument("--dir", default="logs")
    return p


def tokenizer_from_args(args, reserve: int = 0):
    """Resolve ``--tokenizer`` to a BPETokenizer (or None for raw bytes).

    Spec: ``bpe:PATH`` loads an existing tokenizer; ``bpe:PATH:V`` trains
    a V-id tokenizer on the corpus and saves it to PATH when missing
    (deterministic, so every host trains the identical tokenizer).
    ``reserve``: ids the objective needs past the tokenizer (the MLM
    [MASK]) — validated against --vocab here, before any training runs.
    """
    spec = getattr(args, "tokenizer", None)
    if not spec:
        return None
    if not getattr(args, "data_file", None):
        raise ValueError("--tokenizer needs --data-file (it tokenizes the "
                         "real corpus, not synthetic ids)")
    parts = spec.split(":")
    if parts[0] != "bpe" or len(parts) not in (2, 3) or not parts[1]:
        raise ValueError(
            f"--tokenizer must be 'bpe:PATH' or 'bpe:PATH:VOCAB', "
            f"got {spec!r}")
    from tpujob.workloads import tokenizer as toklib

    path = parts[1]
    if len(parts) == 3:
        target = int(parts[2])
        if args.vocab < target + reserve:
            # fail before spending time training a tokenizer the model
            # cannot hold
            raise ValueError(
                f"--vocab {args.vocab} is too small for a {target}-id "
                f"tokenizer{' plus the [MASK] id' if reserve else ''}: "
                f"need >= {target + reserve}")
        tok = toklib.load_or_train(path, args.data_file, target)
    elif os.path.exists(path):
        tok = toklib.BPETokenizer.load(path)
    else:
        raise ValueError(
            f"--tokenizer {spec!r}: {path} does not exist; use "
            f"'bpe:{path}:VOCAB' to train it on the corpus, or run "
            "python -m tpujob.workloads.tokenizer train")
    if args.vocab < tok.vocab_size + reserve:
        need = tok.vocab_size + reserve
        raise ValueError(
            f"--vocab {args.vocab} is too small for the "
            f"{tok.vocab_size}-id tokenizer"
            f"{' plus the [MASK] id' if reserve else ''}: need >= {need}")
    return tok


def token_batches(args, pe, tokenizer=None):
    """(template local batch ids, provider(step)->ids or None, sample row):
    synthetic fixed batch by default; with --data-file, deterministic
    per-step cycling over the corpus chunks — raw bytes, or BPE tokens
    when ``tokenizer`` is set (both memory-mapped: RAM holds the sliced
    batch, not the corpus).  ``sample`` is global row 0 — IDENTICAL on
    every host (generation prompts must agree across the SPMD decode,
    unlike the per-host local slice)."""
    lo, sz = dist.local_batch_slice(args.batch_size, pe)
    if not getattr(args, "data_file", None):
        ids = datalib.synthetic_token_batch(
            args.batch_size, args.seq_len, args.vocab)
        return ids[lo : lo + sz], None, ids[0:1]
    if tokenizer is not None:
        chunks = datalib.bpe_token_dataset(args.data_file, args.seq_len,
                                           tokenizer)
    else:
        if args.vocab < 256:
            raise ValueError(
                f"--data-file is a byte-level corpus: --vocab {args.vocab} "
                "must be >= 256")
        chunks = datalib.byte_token_dataset(args.data_file, args.seq_len)

    def provider(step: int):
        # gather only this host's rows of the global step batch; the
        # fancy-indexed read materializes just those rows off the memmap
        idx = (np.arange(lo, lo + sz) + step * args.batch_size) % len(chunks)
        return np.asarray(chunks[idx], dtype=np.int32)

    return provider(0), provider, np.asarray(chunks[0:1], dtype=np.int32)


def moe_config_from(args, mesh=None) -> Optional[MoEConfig]:
    """Validate the MoE flag surface and build the config (None = dense).
    The one home for these rules — called both before mesh construction
    (so a 1-device run reports the actionable error, not an opaque
    axis-divisibility one) and from build_model for external-mesh callers."""
    n_experts = getattr(args, "moe_experts", 0)
    ep = getattr(args, "expert_parallel", 1)
    if n_experts <= 0:
        if ep > 1:
            raise ValueError("--expert-parallel needs --moe-experts > 0")
        return None
    if args.moe_k < 1:
        # k=0 would silently zero every MoE FFN (all gates empty)
        raise ValueError(f"--moe-k must be >= 1, got {args.moe_k}")
    if ep > 1 and n_experts % ep != 0:
        raise ValueError(
            f"--moe-experts {n_experts} must divide evenly over "
            f"--expert-parallel {ep}")
    return MoEConfig(experts=n_experts, k=args.moe_k,
                     capacity_factor=args.moe_capacity_factor, mesh=mesh)


def validate_pipeline_flags(args) -> int:
    """Coherence checks for --pipeline-parallel; returns the stage count."""
    pp = getattr(args, "pipeline_parallel", 1)
    micro = getattr(args, "pipeline_microbatches", 0)
    sched = getattr(args, "pipeline_schedule", "gpipe")
    if micro < 0:
        raise ValueError(f"--pipeline-microbatches must be >= 0, got {micro}")
    if micro > 0 and pp <= 1:
        # never drop a requested flag silently
        raise ValueError("--pipeline-microbatches needs --pipeline-parallel > 1")
    if sched != "gpipe" and pp <= 1:
        raise ValueError("--pipeline-schedule needs --pipeline-parallel > 1")
    if sched == "1f1b" and getattr(args, "tensor_parallel", 1) > 1:
        raise ValueError(
            "--pipeline-schedule=1f1b does not compose with "
            "--tensor-parallel in this release; use the gpipe schedule "
            "for TP x PP")
    if pp > 1:
        if args.sequence_parallel > 1:
            raise ValueError(
                "--pipeline-parallel composes with data and tensor "
                "parallelism (the Megatron TP x PP layout) and with "
                "--attention=flash; not with --sequence-parallel in this "
                "release (two nested manual regions over sequence and "
                "pipeline)")
        if getattr(args, "moe_experts", 0) > 0:
            raise ValueError(
                "--pipeline-parallel does not compose with --moe-experts "
                "(the MoE metrics collection cannot cross the pipeline's "
                "manual region)")
        if args.layers % pp != 0:
            raise ValueError(
                f"--layers {args.layers} must divide over "
                f"--pipeline-parallel {pp}")
    return pp


def validate_parallel_flags(args) -> int:
    """All strategy-flag coherence rules in one place; returns the
    pipeline stage count."""
    moe_config_from(args)
    if getattr(args, "position", "learned") == "rope" \
            and (args.hidden // args.heads) % 2 != 0:
        raise ValueError(
            f"--position rope needs an even head dim, got "
            f"{args.hidden // args.heads} (hidden {args.hidden} / heads "
            f"{args.heads})")
    kvh = getattr(args, "kv_heads", 0)
    if kvh:
        if kvh < 0:
            raise ValueError(f"--kv-heads must be >= 1, got {kvh}")
        if args.heads % kvh != 0:
            raise ValueError(
                f"--kv-heads {kvh} must divide --heads {args.heads}")
        tp = getattr(args, "tensor_parallel", 1)
        if tp > 1 and kvh % tp != 0:
            # the K/V projection's output dim is kv_heads*head_dim; a TP
            # split that doesn't divide the KV heads would shard across a
            # head boundary
            raise ValueError(
                f"--kv-heads {kvh} must divide evenly over "
                f"--tensor-parallel {tp}")
    pp = validate_pipeline_flags(args)
    fsdp = getattr(args, "fsdp", 1)
    if fsdp > 1:
        # fsdp composes with sequence parallelism: the SP manual region
        # wraps only the q/k/v activations — params never enter it, so
        # ZeRO-3 keeps its per-layer gather at the jit level unchanged
        # (parity pinned by test_fsdp_composes_with_{ring,ulysses}_sp)
        if getattr(args, "pipeline_parallel", 1) > 1:
            raise ValueError(
                "--fsdp does not compose with --pipeline-parallel (the "
                "stage param stacks enter the pipeline's manual region "
                "and would be re-gathered whole); pair --fsdp with "
                "--tensor-parallel or --moe-experts instead")
    return pp


def make_mesh_for(args, pe):
    # flag coherence before mesh construction, so a wrong-device-count run
    # reports the actionable error, not an opaque axis-divisibility one
    validate_parallel_flags(args)
    axes = {"data": -1}
    if getattr(args, "fsdp", 1) > 1:
        axes["fsdp"] = args.fsdp
    if args.tensor_parallel > 1:
        axes["tensor"] = args.tensor_parallel
    if args.sequence_parallel > 1:
        axes["sequence"] = args.sequence_parallel
    if getattr(args, "expert_parallel", 1) > 1:
        axes["expert"] = args.expert_parallel
    if getattr(args, "pipeline_parallel", 1) > 1:
        axes["pipeline"] = args.pipeline_parallel
    return dist.make_mesh(axes, env=pe)


def build_model(args, mesh, *, causal: bool = False,
                final_ln: bool = False) -> Bert:
    """Construct the transformer from the flag surface.  ``causal=True``
    threads a causal mask through whichever attention path the flags pick
    (dense/flash/ring/ulysses) — the decoder family (gpt.py) is the same
    machine with masked attention and ln_f."""
    attention_fn = None
    use_flash = getattr(args, "attention", "dense") == "flash"
    window = getattr(args, "attention_window", 0)
    sp_active = "sequence" in mesh.axis_names and mesh.shape["sequence"] > 1
    if window:
        if window < 0:
            raise ValueError(
                f"--attention-window must be >= 1, got {window}")
        if not causal:
            raise ValueError(
                "--attention-window (causal sliding window) applies to "
                "the causal family (gpt), not the bidirectional MLM")
        if sp_active:
            raise ValueError(
                "--attention-window does not compose with "
                "--sequence-parallel in this release (the ring/Ulysses "
                "schedules assume full causal visibility)")
    if use_flash:
        from tpujob.workloads import flash
    if sp_active:
        if getattr(args, "sp_mode", "ring") == "ulysses":
            if "tensor" in mesh.axis_names:
                raise ValueError(
                    "--sp-mode=ulysses does not compose with "
                    "--tensor-parallel (the all_to_all consumes the head "
                    "dim); use --sp-mode=ring for SP x TP")
            impl = flash.flash_attention if use_flash else None
            attention_fn = lambda q, k, v: parallel.ulysses_attention(
                q, k, v, mesh, axis="sequence", attention_impl=impl,
                causal=causal,
            )
        else:
            if use_flash:
                # never drop a requested kernel silently: the ring's
                # per-hop block update is its own fused flash-style loop
                raise ValueError(
                    "--attention=flash pairs with --sp-mode=ulysses or no "
                    "sequence parallelism; the ring path already runs a "
                    "fused flash-style block loop")
            attention_fn = lambda q, k, v: parallel.ring_attention(
                q, k, v, mesh, axis="sequence",
                head_axis="tensor" if "tensor" in mesh.axis_names else None,
                causal=causal,
            )
    elif use_flash:
        if "tensor" in mesh.axis_names and mesh.shape["tensor"] > 1:
            # the Mosaic custom call carries no GSPMD partitioning rule: on
            # real TPU a tensor-sharded head dim would be all-gathered and
            # the kernel replicated, silently defeating TP on the hottest
            # op — reject instead (interpret-mode tests would mask it)
            raise ValueError(
                "--attention=flash does not compose with --tensor-parallel "
                "(no GSPMD rule for the Pallas call); use dense attention "
                "with TP, or flash without TP")
        attention_fn = lambda q, k, v: flash.flash_attention(
            q, k, v, causal=causal, window=window)
    elif causal:
        attention_fn = lambda q, k, v: parallel.full_attention(
            q, k, v, causal=True, window=window)
    moe = moe_config_from(args, mesh)
    return Bert(
        vocab=args.vocab, hidden=args.hidden, layers=args.layers,
        heads=args.heads, intermediate=args.intermediate, max_seq=args.seq_len,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        attention_fn=attention_fn, moe=moe, remat=args.remat,
        final_ln=final_ln, mesh=mesh,
        kv_heads=getattr(args, "kv_heads", 0) or None,
        use_rope=getattr(args, "position", "learned") == "rope",
        window=window,
    )


def train(args, mesh, pe, model, make_loss, local_batch, *,
          tag: str = "bert", batch_provider=None,
          make_f1b=None) -> Dict[str, Any]:
    """Shared SPMD training driver for the transformer families (BERT here,
    GPT in ``tpujob.workloads.gpt``): sharded init by PARTITION_RULES,
    pipeline apply_fn wiring, AOT compile, step-exact checkpoint/resume,
    profiler, and honest throughput accounting.

    ``make_loss(apply_fn) -> loss_fn(params, batch)`` builds the model's
    loss (apply_fn is None for the standard forward, or the pipelined
    forward when --pipeline-parallel is set); ``local_batch`` is this
    process's rows of the global batch (a tuple of arrays).
    ``batch_provider(step) -> local batch tuple`` (optional) supplies a
    DIFFERENT batch per step — same shapes as ``local_batch`` (the AOT
    template), deterministic in ``step`` so checkpoint resume replays the
    exact stream (the --data-file real-corpus path).
    """
    writer = train_lib.SummaryWriter(args.dir, enabled=pe.process_id == 0)
    accum = getattr(args, "grad_accum", 1)
    if accum > 1 and args.steps % accum != 0:
        raise ValueError(
            f"--steps {args.steps} must be a multiple of --grad-accum "
            f"{accum} (trailing mini-steps would accumulate gradients "
            "that never apply)")
    if accum > 1 and getattr(args, "warmup_steps", 0) % accum != 0:
        # never drop a requested flag silently: flooring 2//4 warmup
        # updates to 0 would skip the warmup the user asked for
        raise ValueError(
            f"--warmup-steps {args.warmup_steps} must be a multiple of "
            f"--grad-accum {accum} (the schedule advances once per "
            "accumulated update)")
    # the schedule is driven by the INNER optimizer's update count, which
    # advances once per accum mini-steps — convert the flag surface's
    # mini-step units to update units
    lr = train_lib.make_lr_schedule(
        args.lr, getattr(args, "lr_schedule", "constant"),
        getattr(args, "warmup_steps", 0) // accum, args.steps // accum)
    optimizer = train_lib.with_grad_accum(train_lib.adamw(lr), accum)

    rng = jax.random.PRNGKey(args.seed)
    sample = jnp.zeros((1, args.seq_len), jnp.int32)
    # keep only trainable params: init also returns the sown moe_metrics
    # collection for MoE models, which is per-call output, not state
    params = {"params": model.init(rng, sample)["params"]}
    params = parallel.shard_params(params, mesh, PARTITION_RULES)
    # moments initialized from sharded params inherit their layout; bare
    # scalars (adam count, step) must be committed replicated explicitly or
    # they pin to one device and conflict on restore
    repl = dist.replicated(mesh)
    opt_state = jax.tree.map(
        lambda a: jax.device_put(a, repl) if getattr(a, "ndim", None) == 0 else a,
        optimizer.init(params),
    )
    state = {
        "params": params,
        "opt": opt_state,
        "step": jax.device_put(jnp.zeros((), jnp.int32), repl),
    }

    apply_fn = None
    vag = None
    # run() may receive an external mesh (dryrun, tests), so the full flag
    # coherence check must happen here too, not only in make_mesh_for
    pp = validate_parallel_flags(args)
    if pp > 1:
        micro = getattr(args, "pipeline_microbatches", 0) or pp
        if getattr(args, "pipeline_schedule", "gpipe") == "1f1b":
            if make_f1b is None:
                raise ValueError(
                    "--pipeline-schedule=1f1b is not supported for this "
                    "workload (no per-microbatch loss decomposition)")
            from tpujob.workloads import pipeline_schedule
            # ONE shard decision, shared with the schedule (the loss
            # scaling in make_f1b must match what the schedule divides by)
            shards = pipeline_schedule.batch_shard_count(
                mesh, args.batch_size)
            preprocess, head_loss = make_f1b(micro, shards)
            vag = make_1f1b_value_and_grad(model, mesh, micro, head_loss,
                                           preprocess, batch_shards=shards)
        else:
            apply_fn = lambda p, ids: pipeline_apply(model, p, ids, mesh,
                                                     micro)
    loss_fn = make_loss(apply_fn)
    train_step = train_lib.make_train_step(
        loss_fn, optimizer, mesh,
        state_shardings=jax.tree.map(lambda a: a.sharding, state),
        value_and_grad_fn=vag,
    )

    ckpt = None
    start_step = 0
    if args.checkpoint_interval > 0:
        ckpt = train_lib.Checkpointer(args.dir + "/ckpt")
        latest = ckpt.latest_step()
        if latest is not None:
            # the live sharded state is the restore template: orbax reads
            # each host's shards directly (no host round-trip, multi-host ok)
            state = ckpt.restore(latest, state)
            start_step = latest
            print(f"resumed from checkpoint step {latest}")

    batch = train_lib.put_batch(local_batch, mesh)

    if start_step >= args.steps:
        # the pod was restarted after the final checkpoint (the preemption
        # race): report completion instead of training further
        final_loss = float(jax.jit(loss_fn)(state["params"], batch))
        if pe.process_id == 0:
            print(f"already complete: resumed at step {start_step} >= "
                  f"--steps {args.steps}")
        writer.close()
        if ckpt:
            ckpt.close()
        return {"samples_per_sec": 0.0, "tokens_per_sec": 0.0, "wall_s": 0.0,
                "final_loss": final_loss, "state": state}

    # AOT compile instead of warmup steps: no optimizer updates happen
    # outside the counted loop, so a resumed run is step-exact
    compiled = train_step.lower(state, batch).compile()
    profiler = train_lib.profiler_from_args(args, pe)
    t0 = time.perf_counter()
    loss = None
    try:
        for i in range(start_step, args.steps):
            profiler.step(i - start_step, block_on=loss)
            if batch_provider is not None:
                batch = train_lib.put_batch(batch_provider(i), mesh)
            state, loss = compiled(state, batch)
            if i % args.log_interval == 0:
                writer.add_scalar("loss", float(loss), i)
            if ckpt and args.checkpoint_interval and (i + 1) % args.checkpoint_interval == 0:
                ckpt.save(i + 1, state)
        jax.block_until_ready(loss)
        # honest throughput under --profile-dir: exclude trace drain +
        # serialization time, whether the window closed mid-loop
        # (profiler.overhead_s) or in the finally below
        wall = time.perf_counter() - t0 - profiler.overhead_s
    finally:
        profiler.close(block_on=loss)
    steps_run = args.steps - start_step
    sps = steps_run * args.batch_size / wall
    tps = sps * args.seq_len
    final_loss = float(loss)
    writer.close()
    if ckpt:
        ckpt.close()
    if pe.process_id == 0:
        print(f"{tag}(h{args.hidden}xl{args.layers}): {sps:.1f} samples/sec, "
              f"{tps:.0f} tokens/sec, loss={final_loss:.3f}")
    return {"samples_per_sec": sps, "tokens_per_sec": tps, "wall_s": wall,
            "final_loss": final_loss, "state": state}


def run(args, mesh=None) -> Dict[str, Any]:
    pe = dist.initialize()
    # MLM reserves one id past the real token alphabet as [MASK]: the
    # WordPiece 103 for synthetic vocabularies; the first post-alphabet id
    # for real corpora (raw bytes: 256; BPE: tokenizer vocab_size), so a
    # genuine token is never confusable with a masked position
    mask_id = 103
    tok = tokenizer_from_args(args, reserve=1)
    if tok is not None:
        mask_id = tok.vocab_size
    elif getattr(args, "data_file", None):
        if args.vocab < 257:
            raise ValueError(
                f"--data-file with the MLM objective needs --vocab >= 257 "
                f"(256 byte values + the [MASK] token), got {args.vocab}")
        mask_id = 256
    if mesh is None:
        mesh = make_mesh_for(args, pe)
    model = build_model(args, mesh)
    ids0, provider, _ = token_batches(args, pe, tokenizer=tok)
    lo, sz = dist.local_batch_slice(args.batch_size, pe)

    def masked(ids_local, seed):
        # draw the GLOBAL mask (same seed on every host) and slice this
        # host's rows, so masked positions stay i.i.d. across the global
        # batch — masking the local slice directly would repeat one
        # pattern on every host
        _, mask = mask_batch(
            np.zeros((args.batch_size, args.seq_len), np.int32), seed)
        return ids_local, mask[lo : lo + sz]

    bp = None
    if provider is not None:
        bp = lambda step: masked(provider(step), args.seed + step)

    def make_f1b(micro, shards):
        """MLM per-microbatch loss for the 1F1B schedule: normalized by
        the GLOBAL mask count (threaded through ``extra`` as a broadcast
        row so the shard-mean equals the exact global masked mean) and
        scaled by micro*shards so the schedule's mean IS the step loss."""

        def preprocess(batch):
            ids, mask = batch
            masked_ids = jnp.where(mask > 0, jnp.int32(mask_id), ids)
            total = jnp.maximum(mask.sum(), 1.0)
            extra = (ids, mask,
                     jnp.broadcast_to(total, (ids.shape[0],)))
            return masked_ids, extra

        def head_loss(logits, ex):
            ids_mb, mask_mb, tot = ex
            logp = jax.nn.log_softmax(logits)
            tok_ll = jnp.take_along_axis(logp, ids_mb[..., None],
                                         axis=-1)[..., 0]
            return -(tok_ll * mask_mb).sum() / tot[0] * (micro * shards)

        return preprocess, head_loss

    return train(args, mesh, pe, model,
                 lambda af: mlm_loss(model, apply_fn=af, mask_id=mask_id),
                 masked(ids0, args.seed), batch_provider=bp,
                 make_f1b=make_f1b)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    run(args)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
