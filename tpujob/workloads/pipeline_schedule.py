"""True 1F1B pipeline schedule: interleaved forward/backward with explicit
per-stage VJPs.

``parallel.pipeline`` + ``jax.grad`` runs the full forward schedule, then
the full transposed backward — correct and simple, but every microbatch's
boundary activation stays live from its forward tick until its backward
tick, so peak stash memory grows with the microbatch count m.  1F1B's
whole point is to interleave each microbatch's backward between other
microbatches' forwards so a stage stashes at most ~(n - s) activations —
and that interleaving CANNOT be expressed through ``jax.grad`` of a
forward-only schedule (the transpose runs only after the forward
completes).  So this module schedules forward and backward ticks itself:

- ``build_1f1b_tables(n, m)`` simulates the classic 1F1B policy (each
  stage: n-s warmup forwards, then alternate backward/forward, then
  drain) ONCE at trace time, producing static per-tick tables: which
  microbatch each stage forwards/backwards, which stash slot holds each
  in-flight activation, and where arriving ppermute traffic lands.  The
  simulator asserts every dependency (a forward needs its input to have
  arrived; a backward needs its cotangent) and that every op runs exactly
  once, so a scheduling bug fails loudly at trace time, not numerically.
- ``pipeline_1f1b(...)`` executes the timetable as one ``lax.scan`` under
  ``shard_map``: per tick each stage runs idle / forward / forward+loss
  (last stage) / backward via ``lax.switch``, activations ppermute down
  the ring and cotangents ppermute up, and backward ticks recompute their
  stage forward under ``jax.vjp`` (the remat trade — FLOP-neutral with
  the rematerialized GPipe backward).  It returns the mean loss and
  grads for (stage params, head params, pipeline input), i.e. it IS the
  fused forward+backward, not a differentiable forward.

Memory: peak stashed activations per stage is the simulator's measured
``stash_depth`` (~n+1), independent of m — the 1F1B bound, pinned by the
``memory_analysis`` comparison in tests.

The reference has nothing remotely like this (SURVEY.md §2.5: its only
strategy is DDP data parallelism); the design target is the Megatron-LM
1F1B schedule expressed TPU-first (static tables + lax.scan + ppermute,
no host control flow).
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpujob.workloads import distributed as dist
from tpujob.workloads.distributed import shard_map


# action codes for the per-tick lax.switch
IDLE, FWD, FWD_LOSS, BWD = 0, 1, 2, 3


@dataclasses.dataclass(frozen=True)
class Tables:
    """Static 1F1B timetable (everything [T, n] int32 unless noted)."""

    n: int
    m: int
    ticks: int
    stash_depth: int        # activation stash slots per stage
    cot_depth: int          # cotangent stash slots per stage
    action: np.ndarray      # IDLE/FWD/FWD_LOSS/BWD
    op_mb: np.ndarray       # microbatch index of the tick's op (-1 idle)
    op_slot: np.ndarray     # stash slot: fwd reads / bwd reads+frees
    cot_slot: np.ndarray    # bwd: cotangent slot to read
    arr_slot: np.ndarray    # where the arriving activation lands (-1 drop)
    cotarr_slot: np.ndarray  # where the arriving cotangent lands (-1 drop)
    loss_cot_slot: np.ndarray  # last stage fwd tick: slot for the loss cot
    feed_mb: np.ndarray     # stage-0 fwd tick: microbatch to load from x


def build_1f1b_tables(n: int, m: int) -> Tables:
    """Simulate the 1F1B policy and emit the static timetable.

    Policy per stage s (classic): complete min(n - s, m) warmup forwards
    first; afterwards prefer the oldest ready backward, else the next
    forward whose input has arrived; stop when all m backwards are done.
    """
    if m < 1 or n < 1:
        raise ValueError(f"need n >= 1, m >= 1, got n={n} m={m}")
    warmup = [min(n - s, m) for s in range(n)]
    fwd_done = [[None] * m for _ in range(n)]   # tick fwd completed
    bwd_done = [[None] * m for _ in range(n)]
    # activation/cotangent arrival ticks at each stage (stage 0 activations
    # "arrive" at their fwd tick from the local feed; last-stage cotangents
    # at its fwd tick from the local loss vjp)
    act_arrival = [dict() for _ in range(n)]
    cot_arrival = [dict() for _ in range(n)]

    rows: List[dict] = []
    t = 0
    while not all(all(x is not None for x in bwd_done[s]) for s in range(n)):
        row = {"action": [IDLE] * n, "op_mb": [-1] * n}
        for s in range(n):
            fwds = sum(x is not None for x in fwd_done[s])
            bwds = sum(x is not None for x in bwd_done[s])
            # oldest microbatch ready to go backward
            bwd_j = next(
                (j for j in range(m)
                 if bwd_done[s][j] is None and fwd_done[s][j] is not None
                 and cot_arrival[s].get(j, 10**9) <= t),
                None)
            fwd_j = fwds if fwds < m else None
            if fwd_j is not None and s > 0 \
                    and act_arrival[s].get(fwd_j, 10**9) > t:
                fwd_j = None
            if fwds < warmup[s] and fwd_j is not None:
                row["action"][s], row["op_mb"][s] = FWD, fwd_j
            elif bwd_j is not None:
                row["action"][s], row["op_mb"][s] = BWD, bwd_j
            elif fwd_j is not None:
                row["action"][s], row["op_mb"][s] = FWD, fwd_j
        # commit this tick's effects (ppermute lands next tick)
        for s in range(n):
            a, j = row["action"][s], row["op_mb"][s]
            if a == FWD:
                fwd_done[s][j] = t
                if s == 0:
                    act_arrival[0][j] = t  # local feed
                if s + 1 < n:
                    act_arrival[s + 1][j] = t + 1
                if s == n - 1:
                    row["action"][s] = FWD_LOSS
                    cot_arrival[s][j] = t  # local loss vjp
            elif a == BWD:
                bwd_done[s][j] = t
                if s > 0:
                    cot_arrival[s - 1][j] = t + 1
        rows.append(row)
        t += 1
        if t > 4 * (m + n) + 16:
            raise AssertionError("1F1B simulator failed to converge")

    T = len(rows)
    # slot assignment: an activation occupies a slot from its arrival tick
    # until its backward completes; cotangents from arrival until consumed
    def assign_slots(arrival, release):
        slots = [dict() for _ in range(n)]  # mb -> slot per stage
        depth = 1
        for s in range(n):
            free: List[int] = []
            next_new = 0
            events = sorted(
                [(arrival[s][j], 0, j) for j in arrival[s]]
                + [(release[s][j], 1, j) for j in arrival[s]])
            for _, kind, j in events:
                if kind == 0:
                    if free:
                        slots[s][j] = free.pop()
                    else:
                        slots[s][j] = next_new
                        next_new += 1
                        depth = max(depth, next_new)
                else:
                    free.append(slots[s][j])
        return slots, depth

    act_release = [{j: bwd_done[s][j] for j in act_arrival[s]}
                   for s in range(n)]
    cot_release = [{j: bwd_done[s][j] for j in cot_arrival[s]}
                   for s in range(n)]
    act_slots, stash_depth = assign_slots(act_arrival, act_release)
    cot_slots, cot_depth = assign_slots(cot_arrival, cot_release)

    def tbl(fill=-1):
        return np.full((T, n), fill, dtype=np.int32)

    action = tbl(IDLE)
    op_mb, op_slot, cot_slot = tbl(), tbl(), tbl()
    arr_slot, cotarr_slot, loss_cot_slot, feed_mb = tbl(), tbl(), tbl(), tbl()
    for t_, row in enumerate(rows):
        for s in range(n):
            a, j = row["action"][s], row["op_mb"][s]
            action[t_, s] = a
            if a == IDLE:
                continue
            op_mb[t_, s] = j
            op_slot[t_, s] = act_slots[s][j]
            if a in (FWD, FWD_LOSS) and s == 0:
                feed_mb[t_, s] = j
            if a == FWD_LOSS:
                loss_cot_slot[t_, s] = cot_slots[s][j]
            if a == BWD:
                cot_slot[t_, s] = cot_slots[s][j]
    # arrivals: activation sent from s-1's fwd at t-1 lands at (t, s);
    # cotangent from s+1's bwd at t-1 lands at (t, s)
    for t_, row in enumerate(rows[:-1]):
        for s in range(n):
            a, j = row["action"][s], row["op_mb"][s]
            if a in (FWD, FWD_LOSS) and s + 1 < n:
                arr_slot[t_ + 1, s + 1] = act_slots[s + 1][j]
            if a == BWD and s > 0:
                cotarr_slot[t_ + 1, s - 1] = cot_slots[s - 1][j]

    # invariants: every op exactly once, dependencies respected
    for s in range(n):
        assert sorted(j for t_ in range(T)
                      for a, j in [(action[t_, s], op_mb[t_, s])]
                      if a in (FWD, FWD_LOSS)) == list(range(m))
        assert sorted(op_mb[t_, s] for t_ in range(T)
                      if action[t_, s] == BWD) == list(range(m))
        for j in range(m):
            assert act_arrival[s][j] <= fwd_done[s][j]
            assert fwd_done[s][j] < bwd_done[s][j]
            assert cot_arrival[s][j] <= bwd_done[s][j]
            if s > 0:
                assert fwd_done[s - 1][j] < fwd_done[s][j]
            if s + 1 < n:
                assert bwd_done[s + 1][j] < bwd_done[s][j]
    return Tables(
        n=n, m=m, ticks=T, stash_depth=stash_depth, cot_depth=cot_depth,
        action=action, op_mb=op_mb, op_slot=op_slot, cot_slot=cot_slot,
        arr_slot=arr_slot, cotarr_slot=cotarr_slot,
        loss_cot_slot=loss_cot_slot, feed_mb=feed_mb,
    )


def batch_shard_count(mesh, global_batch: int) -> int:
    """How many ways the batch dim splits over the mesh's batch axes —
    THE one decision shared by pipeline_1f1b and any caller that scales
    its per-microbatch loss by the shard count.  Falls back to 1 when the
    batch doesn't divide (e.g. a batch-1 trace)."""
    axes = dist.batch_axes(mesh)
    if not axes:
        return 1
    div = dist.batch_divisor(mesh, *axes)
    return div if global_batch % div == 0 else 1


def _put_slot(buf, val, slot):
    """buf[slot] = val when slot >= 0, else no-op (cheap selects)."""
    upd = jax.lax.dynamic_update_index_in_dim(
        buf, val.astype(buf.dtype), jnp.clip(slot, 0, buf.shape[0] - 1), 0)
    return jnp.where(slot >= 0, upd, buf)


def pipeline_1f1b(
    stage_fn,
    stacked_params: Any,
    x: jax.Array,
    head_fn,
    head_params: Any,
    extra: Any,
    mesh,
    *,
    axis: str = "pipeline",
    num_microbatches: Optional[int] = None,
    batch_shards: Optional[int] = None,
):
    """Fused forward+backward over the 1F1B timetable.

    ``stage_fn(local_stack, x_mb) -> y_mb`` (shape/dtype-preserving);
    ``head_fn(head_params, y_mb, extra_mb) -> scalar`` is the
    per-microbatch loss (its mean over all microbatches and batch shards
    is the returned loss); ``extra`` is a pytree of [batch, ...] arrays
    cut into microbatches alongside ``x`` (labels, masks).
    ``batch_shards``: how many ways the batch dim splits over the mesh's
    batch axes — pass the value your loss scaling was computed against
    (see :func:`batch_shard_count`; callers that scale ``head_fn`` by the
    shard count MUST share one decision, or the loss silently mis-scales
    by the data-axis size); None derives it from ``x`` here.

    Returns ``(loss, d_stacked_params, d_head_params, dx)`` — the exact
    gradients of the mean loss (parity with ``jax.grad`` of the GPipe
    schedule is pinned by tests).  Backward ticks recompute their stage
    forward under ``jax.vjp`` (same FLOP trade as the rematerialized
    GPipe backward); what 1F1B buys is the stash bound: at most
    ``Tables.stash_depth`` (~n) microbatch activations live per stage,
    independent of the microbatch count.
    """
    n = mesh.shape[axis]
    leaves = jax.tree_util.tree_leaves(stacked_params)
    if not leaves:
        raise ValueError("stacked_params is empty")
    if leaves[0].shape[0] % n != 0:
        raise ValueError(
            f"layer stack of {leaves[0].shape[0]} does not divide over "
            f"{axis!r} axis size {n}")
    shards = (batch_shard_count(mesh, x.shape[0]) if batch_shards is None
              else batch_shards)
    if shards > 1 and x.shape[0] % shards != 0:
        raise ValueError(
            f"batch {x.shape[0]} does not divide over {shards} batch "
            "shards")
    batch_axis = dist.batch_axes(mesh) if shards > 1 else None
    b_local = x.shape[0] // shards
    m = num_microbatches or n
    if b_local % m != 0:
        raise ValueError(
            f"per-device batch {b_local} does not divide into "
            f"{m} microbatches")
    tables = build_1f1b_tables(n, m)
    rows = {
        "action": tables.action, "op_mb": tables.op_mb,
        "op_slot": tables.op_slot, "cot_slot": tables.cot_slot,
        "arr_slot": tables.arr_slot, "cotarr_slot": tables.cotarr_slot,
        "loss_cot_slot": tables.loss_cot_slot, "feed_mb": tables.feed_mb,
    }
    rows = {k: jnp.asarray(v) for k, v in rows.items()}
    # jax < 0.5: the legacy shard_map partitioner mispartitions a stack
    # built inside the surrounding jit against a P(axis) in_spec (see
    # parallel.pipeline) — feed the stack replicated, slice each stage's
    # layers inside the manual region, and reassemble dP the same way
    legacy = not dist.shard_map_supports_partial_manual()
    per = leaves[0].shape[0] // n

    def local(p_local, h_params, xb, extra_b):
        idx = jax.lax.axis_index(axis)
        if legacy:
            p_local = jax.tree.map(
                lambda a: jax.lax.dynamic_slice_in_dim(
                    a, idx * per, per, 0), p_local)
        mb = xb.shape[0] // m
        x_mb = xb.reshape((m, mb) + xb.shape[1:])
        extra_mb = jax.tree.map(
            lambda a: a.reshape((m, a.shape[0] // m) + a.shape[1:]), extra_b)
        mb_shape = x_mb.shape[1:]
        zeros_mb = jnp.zeros(mb_shape, x_mb.dtype)

        def head_loss(y, j):
            ex = jax.tree.map(lambda a: a[j], extra_mb)
            loss_j, vjp = jax.vjp(head_fn, h_params, y, ex)
            dh, dy, _ = vjp(jnp.ones((), loss_j.dtype) / m)
            return loss_j / m, dh, dy

        dh0 = jax.tree.map(jnp.zeros_like, h_params)
        dp0 = jax.tree.map(jnp.zeros_like, p_local)

        def tick(carry, row):
            stash, cots, act_in, cot_in, dP, dH, dxs, loss = carry
            pick = lambda k: row[k][idx]
            act = pick("action")
            j = pick("op_mb")
            slot = pick("op_slot")
            # 1) land last tick's ppermute traffic
            stash = _put_slot(stash, act_in, pick("arr_slot"))
            cots = _put_slot(cots, cot_in, pick("cotarr_slot"))
            # 2) stage-0 feed lands in the op slot before use
            feed = pick("feed_mb")
            stash = jnp.where(
                feed >= 0,
                _put_slot(stash, x_mb[jnp.clip(feed, 0, m - 1)], slot),
                stash)
            x_in = stash[jnp.clip(slot, 0, tables.stash_depth - 1)]
            g_in = cots[jnp.clip(pick("cot_slot"), 0, tables.cot_depth - 1)]
            jmb = jnp.clip(j, 0, m - 1)

            def do_idle(_):
                return (zeros_mb, zeros_mb, dp0, dh0,
                        jnp.zeros((), jnp.float32), zeros_mb)

            def do_fwd(_):
                y = stage_fn(p_local, x_in)
                return (y, zeros_mb, dp0, dh0,
                        jnp.zeros((), jnp.float32), zeros_mb)

            def do_fwd_loss(_):
                y = stage_fn(p_local, x_in)
                loss_j, dh, dy = head_loss(y, jmb)
                return (y, zeros_mb, dp0, dh,
                        loss_j.astype(jnp.float32), dy.astype(x_mb.dtype))

            def do_bwd(_):
                y, vjp = jax.vjp(stage_fn, p_local, x_in)
                dp, dx = vjp(g_in.astype(y.dtype))
                return (zeros_mb, dx.astype(x_mb.dtype), dp, dh0,
                        jnp.zeros((), jnp.float32), zeros_mb)

            send_down, send_up, dp_add, dh_add, loss_add, cot_w = \
                jax.lax.switch(act, [do_idle, do_fwd, do_fwd_loss, do_bwd],
                               None)
            # last stage: the loss cotangent enters the cot stash locally
            cots = _put_slot(cots, cot_w, pick("loss_cot_slot"))
            dP = jax.tree.map(jnp.add, dP, dp_add)
            dH = jax.tree.map(jnp.add, dH, dh_add)
            loss = loss + loss_add
            # stage 0's backward output is d(loss)/d(pipeline input)
            is_s0_bwd = jnp.logical_and(idx == 0, act == BWD)
            dxs = jnp.where(
                is_s0_bwd,
                jax.lax.dynamic_update_index_in_dim(dxs, send_up, jmb, 0),
                dxs)
            act_in = jax.lax.ppermute(
                send_down, axis, [(i, i + 1) for i in range(n - 1)])
            cot_in = jax.lax.ppermute(
                send_up, axis, [(i, i - 1) for i in range(1, n)])
            return (stash, cots, act_in, cot_in, dP, dH, dxs, loss), None

        stash0 = jnp.zeros((tables.stash_depth,) + mb_shape, x_mb.dtype)
        cots0 = jnp.zeros((tables.cot_depth,) + mb_shape, x_mb.dtype)
        carry0 = (stash0, cots0, zeros_mb, zeros_mb, dp0, dh0,
                  jnp.zeros((m,) + mb_shape, x_mb.dtype),
                  jnp.zeros((), jnp.float32))
        (_, _, _, _, dP, dH, dxs, loss), _ = jax.lax.scan(
            tick, carry0, rows)
        # reductions: loss/dH live on the last stage, dxs on stage 0 —
        # psum over the pipeline ring (others hold zeros); batch-shard
        # means divide by the shard count (the DDP all-reduce, explicit)
        loss = jax.lax.psum(loss, axis)
        dH = jax.lax.psum(jax.tree.map(
            lambda a: jnp.where(idx == n - 1, a, jnp.zeros_like(a)), dH),
            axis)
        dxs = jax.lax.psum(
            jnp.where(idx == 0, dxs, jnp.zeros_like(dxs)), axis)
        if batch_axis:
            loss = jax.lax.psum(loss, batch_axis) / shards
            dH = jax.tree.map(
                lambda a: jax.lax.psum(a, batch_axis) / shards, dH)
            dP = jax.tree.map(
                lambda a: jax.lax.psum(a, batch_axis) / shards, dP)
            dxs = dxs / shards
        if legacy:
            # replicate the full layer-grad stack: each stage scatters its
            # slice into zeros and the ring psum assembles all stages
            dP = jax.tree.map(
                lambda a: jax.lax.psum(
                    jax.lax.dynamic_update_slice_in_dim(
                        jnp.zeros((n * per,) + a.shape[1:], a.dtype),
                        a, idx * per, 0),
                    axis),
                dP)
        return loss, dP, dH, dxs.reshape(xb.shape)

    xspec = P(batch_axis, *([None] * (x.ndim - 1)))
    exspec = jax.tree.map(
        lambda a: P(batch_axis, *([None] * (a.ndim - 1))), extra)
    manual = {axis} | set(dist.batch_axes(mesh))
    return shard_map(
        local, mesh=mesh,
        in_specs=(P() if legacy else P(axis), P(), xspec, exspec),
        out_specs=(P(), P() if legacy else P(axis), P(), xspec),
        check_vma=False, axis_names=frozenset(manual),
    )(stacked_params, head_params, x, extra)
