"""Pallas flash attention — the hot-op kernel for the TPU compute path.

XLA already fuses elementwise chains into matmuls, but dense attention
still materializes the [seq, seq] score matrix in HBM.  This kernel keeps
the whole softmax in VMEM: the grid walks (batch*heads, q_blocks,
k_blocks), VMEM scratch carries the running (m, l, acc) flash statistics
across the innermost k dimension (TPU grids iterate the last axis
sequentially, so scratch persists), and only the final O(S·d) output is
written back.  MXU-shaped blocks (128 lanes), fp32 accumulation under
bf16 inputs.

Exactness: same running-softmax algebra as ``parallel.ring_attention``'s
block update — results match dense attention to numerical precision, which
the tests assert in interpret mode (CPU).  Composes with Ulysses sequence
parallelism (it slots in as the device-local attention via
``ulysses_attention(attention_impl=...)``); the ring scheme needs no local
kernel swap — its per-hop block update IS a fused flash-style loop
already.

Falls back to ``parallel.full_attention`` when the shapes don't tile
(sequence not divisible by the block size) so callers never have to
special-case.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30  # finite: a fully-masked row must not NaN the running max


def _block_scores(q_ref, k_ref, qi, ki, *, scale, causal, block_q, block_k,
                  window=0):
    """Masked scaled scores S_ij = mask(scale·Q_i K_j^T) for one block pair
    — THE shared definition across the forward and both backward kernels,
    so the backward's recomputed P can never drift from the forward's.
    ``window`` > 0 additionally masks keys more than window-1 positions
    behind the query (causal sliding-window attention)."""
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [block_q, block_k]
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        vis = q_pos >= k_pos
        if window:
            vis = jnp.logical_and(vis, q_pos - k_pos < window)
        s = jnp.where(vis, s, NEG_INF)
    return s


def _causal_live(qi, ki, block_q, block_k, window=0):
    """A K block strictly in the future of every Q row — or, with a
    sliding window, entirely behind every Q row's window — contributes
    nothing; its matmuls are skipped entirely.  With a window the live
    band is O(window/block_k) blocks per Q row, so attention FLOPs are
    O(S·window) instead of O(S²)."""
    live = ki * block_k <= qi * block_q + block_q - 1
    if window:
        live = jnp.logical_and(
            live, ki * block_k + block_k - 1 >= qi * block_q - window + 1)
    return live


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                  *, scale: float, causal: bool, block_q: int, block_k: int,
                  window: int = 0):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # _init above runs unconditionally at ki==0, so a dead ki==0 block
    # (possible under a sliding window) still zeroes the scratch
    live = _causal_live(qi, ki, block_q, block_k, window) if causal else True

    @pl.when(live)
    def _update():
        v = v_ref[0].astype(jnp.float32)
        s = _block_scores(q_ref, k_ref, qi, ki, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, window=window)
        # running softmax: m/l replicated across the 128-lane dim so the
        # scratch keeps MXU/VPU-native tiling
        m_prev = m_ref[:, :1]                      # [block_q, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                     # [block_q, block_k]
        l_new = l_ref[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)
        # logsumexp per row, saved for the backward's P recomputation.
        # Stored 128-lane-replicated: Mosaic requires the last block dim be
        # a multiple of 128, so a flat [rows] layout cannot lower (the
        # official TPU flash kernel stores its residuals the same way).
        lse_ref[0] = m_ref[:] + jnp.log(l_ref[:])


def _fold(x):  # [b, s, h, d] -> [b*h, s, d]
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold(x, b, h):  # [b*h, s, d] -> [b, s, h, d]
    return x.reshape(b, h, x.shape[1], x.shape[2]).transpose(0, 2, 1, 3)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret,
                   window=0):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    grid = (b * h, sq // block_q, sk // block_k)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, window=window,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 128), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu_vmem((block_q, 128), jnp.float32),  # running max m
            pltpu_vmem((block_q, 128), jnp.float32),  # running sum l
            pltpu_vmem((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
        **_grid_params(),
    )(_fold(q), _fold(k), _fold(v))
    return _unfold(out, b, h), lse


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale: float, causal: bool,
               block_q: int, block_k: int, window: int = 0):
    """dQ_i = scale * sum_j (P_ij ∘ (dO_i V_j^T − D_i)) K_j, P recomputed
    in VMEM from the saved logsumexp (FlashAttention-2 eq. for dS)."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = _causal_live(qi, ki, block_q, block_k, window) if causal else True

    @pl.when(live)
    def _update():
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = _block_scores(q_ref, k_ref, qi, ki, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, window=window)
        p = jnp.exp(s - lse_ref[0][:, :1])           # [block_q, block_k]
        dp = jax.lax.dot_general(                    # dO V^T
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dq_acc[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                causal: bool, block_q: int, block_k: int, window: int = 0):
    """dV_j = sum_i P_ij^T dO_i;  dK_j = scale * sum_i dS_ij^T Q_i — one
    K/V block accumulates over the (sequentially iterated) Q blocks."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = _causal_live(qi, ki, block_q, block_k, window) if causal else True

    @pl.when(live)
    def _update():
        q = q_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = _block_scores(q_ref, k_ref, qi, ki, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, window=window)
        p = jnp.exp(s - lse_ref[0][:, :1])           # [block_q, block_k]
        dv_acc[:] += jax.lax.dot_general(            # P^T dO
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(                    # dO V^T
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dk_acc[:] += jax.lax.dot_general(            # dS^T Q
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, scale, block_q, block_k,
                    interpret, window=0):
    """FlashAttention-2 backward: two Pallas passes (dQ; then dK+dV), each
    recomputing its P blocks in VMEM from the forward's logsumexp — no
    [seq, seq] tensor ever reaches HBM, so long-context *training* has the
    same O(S·d) memory as the forward.  ``D_i = rowsum(dO_i ∘ O_i)`` (the
    softmax-Jacobian row term) is a cheap elementwise reduction XLA fuses,
    so it stays outside the kernels."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    dof = _fold(g)
    # D = rowsum(dO * O): [b*h, sq] f32, stored 128-lane-replicated like
    # the lse (Mosaic block layout requirement)
    delta = (dof.astype(jnp.float32) * _fold(out).astype(jnp.float32)).sum(-1)
    delta = jnp.broadcast_to(delta[..., None], delta.shape + (128,))

    q_spec3 = pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))
    k_spec3 = pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0))
    r_spec3 = pl.BlockSpec((1, block_q, 128), lambda bh, qi, ki: (bh, qi, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, window=window),
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[q_spec3, k_spec3, k_spec3, q_spec3, r_spec3, r_spec3],
        out_specs=q_spec3,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[pltpu_vmem((block_q, d), jnp.float32)],
        interpret=interpret,
        **_grid_params(),
    )(qf, kf, vf, dof, lse, delta)

    # dK/dV: K-block outer, Q-block inner (the sequential axis accumulates)
    q_specT = pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0))
    k_specT = pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0))
    r_specT = pl.BlockSpec((1, block_q, 128), lambda bh, ki, qi: (bh, qi, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, window=window),
        grid=(b * h, sk // block_k, sq // block_q),
        in_specs=[q_specT, k_specT, k_specT, q_specT, r_specT, r_specT],
        out_specs=[k_specT, k_specT],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu_vmem((block_k, d), jnp.float32),
            pltpu_vmem((block_k, d), jnp.float32),
        ],
        interpret=interpret,
        **_grid_params(),
    )(qf, kf, vf, dof, lse, delta)
    return (_unfold(dq, b, h), _unfold(dk, b, h), _unfold(dv, b, h))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret, window):
    out, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            interpret, window)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret, window):
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              interpret, window)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, window, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, scale,
                           block_q, block_k, interpret, window)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    window: int = 0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over [batch, seq, heads, head_dim] inputs.

    ``interpret=None`` auto-selects the Pallas interpreter off-TPU (tests,
    CPU meshes) and the compiled Mosaic kernel on TPU.  Shapes that don't
    tile (seq % block != 0) fall back to dense attention.  Differentiable
    via the FlashAttention-2 Pallas backward (``_flash_backward``): P
    blocks are recomputed in VMEM from the saved logsumexp, so training at
    long sequence length keeps the same O(S·d) memory as the forward.
    """
    from tpujob.workloads.parallel import _gqa_repeat, full_attention

    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("window (sliding-window attention) requires "
                         "causal=True")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # grouped-query K/V broadcast up to the query heads before tiling
    # (a KV-head-aware kernel grid is a possible future optimization)
    k, v = _gqa_repeat(q, k, v)
    sq, sk = q.shape[1], k.shape[1]
    # blocks stay MXU-shaped: a sequence that doesn't tile into full
    # 128-row blocks takes the dense path rather than handing Mosaic an
    # unaligned block (sub-128 sequences are cheap densely anyway)
    if sq % block_q or sk % block_k:
        return full_attention(q, k, v, causal=causal, scale=scale,
                              window=window)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, causal, float(scale), block_q, block_k, interpret,
                  int(window))


def pltpu_vmem(shape, dtype):
    """VMEM scratch shape — via the TPU pallas module when present, plain
    interpreter scratch otherwise (keeps CPU-only environments importable)."""
    try:
        from jax.experimental.pallas import tpu as pltpu

        return pltpu.VMEM(shape, dtype)
    except ImportError:  # pragma: no cover - non-TPU pallas builds
        return pl.MemorySpace.ANY(shape, dtype)  # type: ignore[attr-defined]


def _grid_params(last_arbitrary: int = 1):
    """Mosaic compiler params marking the grid's non-accumulating dims
    parallel (only the innermost, scratch-carrying dim is sequential) —
    lets the TPU scheduler parallelize/pipeline freely, as the official
    flash kernel does.  Empty off-TPU (interpret mode ignores them)."""
    try:
        from jax.experimental.pallas import tpu as pltpu

        sem = ("parallel",) * (3 - last_arbitrary) + ("arbitrary",) * last_arbitrary
        # jax >= 0.5 renamed TPUCompilerParams -> CompilerParams
        params_cls = getattr(pltpu, "CompilerParams", None) \
            or pltpu.TPUCompilerParams
        return {"compiler_params": params_cls(dimension_semantics=sem)}
    except ImportError:  # pragma: no cover
        return {}
