"""Pallas flash attention — the hot-op kernel for the TPU compute path.

XLA already fuses elementwise chains into matmuls, but dense attention
still materializes the [seq, seq] score matrix in HBM.  This kernel keeps
the whole softmax in VMEM: the grid walks (batch*heads, q_blocks,
k_blocks), VMEM scratch carries the running (m, l, acc) flash statistics
across the innermost k dimension (TPU grids iterate the last axis
sequentially, so scratch persists), and only the final O(S·d) output is
written back.  MXU-shaped blocks (128 lanes), fp32 accumulation under
bf16 inputs.

Exactness: same running-softmax algebra as ``parallel.ring_attention``'s
block update — results match dense attention to numerical precision, which
the tests assert in interpret mode (CPU).  Composes with Ulysses sequence
parallelism (it slots in as the device-local attention via
``ulysses_attention(attention_impl=...)``); the ring scheme needs no local
kernel swap — its per-hop block update IS a fused flash-style loop
already.

Falls back to ``parallel.full_attention`` when the shapes don't tile
(sequence not divisible by the block size) so callers never have to
special-case.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30  # finite: a fully-masked row must not NaN the running max


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                  *, scale: float, causal: bool, block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: a K block strictly in the future of every Q row contributes
    # nothing — skip its matmuls entirely (the ki==0 block is never fully
    # masked, so _init above always runs)
    live = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _update():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        # running softmax: m/l replicated across the 128-lane dim so the
        # scratch keeps MXU/VPU-native tiling
        m_prev = m_ref[:, :1]                      # [block_q, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                     # [block_q, block_k]
        l_new = l_ref[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    b, sq, h, d = q.shape
    sk = k.shape[1]

    def fold(x):  # [b, s, h, d] -> [b*h, s, d]
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    grid = (b * h, sq // block_q, sk // block_k)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu_vmem((block_q, 128), jnp.float32),  # running max m
            pltpu_vmem((block_q, 128), jnp.float32),  # running sum l
            pltpu_vmem((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
    )(fold(q), fold(k), fold(v))
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    # backward recomputes the dense attention and differentiates it — the
    # memory win applies to the forward/inference path; a Pallas backward
    # kernel is the follow-up (this matches what XLA's dense path does
    # during training anyway, so training sees no regression vs dense)
    from tpujob.workloads.parallel import full_attention

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: full_attention(q, k, v, causal=causal, scale=scale),
        q, k, v,
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over [batch, seq, heads, head_dim] inputs.

    ``interpret=None`` auto-selects the Pallas interpreter off-TPU (tests,
    CPU meshes) and the compiled Mosaic kernel on TPU.  Shapes that don't
    tile (seq % block != 0) fall back to dense attention.  Differentiable
    via a recompute backward (see ``_flash_bwd``).
    """
    from tpujob.workloads.parallel import full_attention

    if scale is None:
        scale = q.shape[-1] ** -0.5
    sq, sk = q.shape[1], k.shape[1]
    # blocks stay MXU-shaped: a sequence that doesn't tile into full
    # 128-row blocks takes the dense path rather than handing Mosaic an
    # unaligned block (sub-128 sequences are cheap densely anyway)
    if sq % block_q or sk % block_k:
        return full_attention(q, k, v, causal=causal, scale=scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, causal, float(scale), block_q, block_k, interpret)


def pltpu_vmem(shape, dtype):
    """VMEM scratch shape — via the TPU pallas module when present, plain
    interpreter scratch otherwise (keeps CPU-only environments importable)."""
    try:
        from jax.experimental.pallas import tpu as pltpu

        return pltpu.VMEM(shape, dtype)
    except ImportError:  # pragma: no cover - non-TPU pallas builds
        return pl.MemorySpace.ANY(shape, dtype)  # type: ignore[attr-defined]
