"""Distributed MNIST example, TPU-native.

Mirror of ``examples/mnist/mnist.py`` arg-for-arg: the same CNN (conv 20@5x5
→ pool → conv 50@5x5 → pool → fc 500 → fc 10 → log_softmax, mnist.py:17-33),
the same flags (batch-size/test-batch-size/epochs/lr/momentum/seed/
log-interval/save-model/dir, mnist.py:79-102), the same train/test log lines
(mnist.py:44-49,64-65) and SummaryWriter scalars ('loss' per log-interval,
'accuracy' per epoch).

TPU-first deltas: the model is flax/linen in NHWC; distribution is SPMD data
parallelism over a ``jax.sharding.Mesh`` (jit inserts the gradient
all-reduce — the compiled form of the DDP wrapper, mnist.py:135-138);
``--backend`` accepts only ``xla``; ``--save-model`` writes an orbax
checkpoint instead of ``torch.save``.

Entrypoint of the MNIST TPUJob examples:
    python -m tpujob.workloads.mnist --epochs 1
"""
from __future__ import annotations

import argparse
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpujob.workloads import data as datalib
from tpujob.workloads import distributed as dist
from tpujob.workloads import train_lib


class Net(nn.Module):
    """The reference CNN (mnist.py:17-33), NHWC."""

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Conv(20, (5, 5), padding="VALID", name="conv1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(50, (5, 5), padding="VALID", name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))  # 4*4*50
        x = nn.relu(nn.Dense(500, name="fc1")(x))
        x = nn.Dense(10, name="fc2")(x)
        return nn.log_softmax(x)


def nll_loss(params: Any, batch) -> jax.Array:
    """F.nll_loss on log-probs (mnist.py:41): mean over the global batch."""
    x, y = batch
    logp = Net().apply(params, x)
    return -jnp.take_along_axis(logp, y[:, None].astype(jnp.int32), axis=1).mean()


def eval_metrics(params: Any, batch):
    """Summed nll + correct-prediction count (mnist.py:53-62)."""
    x, y = batch
    logp = Net().apply(params, x)
    y = y.astype(jnp.int32)
    loss_sum = -jnp.take_along_axis(logp, y[:, None], axis=1).sum()
    correct = (jnp.argmax(logp, axis=1) == y).sum()
    return loss_sum, correct


def train_epoch(args, state, train_step, mesh, train_x, train_y, epoch, writer, pe,
                profiler=None, telemetry=None):
    n = len(train_x) - len(train_x) % args.batch_size
    steps_per_epoch = n // args.batch_size
    # every host iterates the same global batch order (same seed) and feeds
    # only its own rows — the DistributedSampler split, TPU-style
    lo, sz = dist.local_batch_slice(args.batch_size, pe)
    last_loss, prev_loss = None, None
    for batch_idx, (bx, by) in enumerate(
        datalib.batches(train_x, train_y, args.batch_size, seed=args.seed + epoch)
    ):
        if profiler is not None:
            # block_on the previous step's DEVICE output: dispatch is
            # async and the trace must cover actual execution
            profiler.step((epoch - 1) * steps_per_epoch + batch_idx,
                          block_on=prev_loss)
        state, loss = train_step(
            state, train_lib.put_batch((bx[lo : lo + sz], by[lo : lo + sz]), mesh)
        )
        prev_loss = loss
        if telemetry is not None:
            # throughput EMA + the operator-facing progress heartbeat
            # (rate-limited inside the reporter; a no-op locally)
            telemetry.step((epoch - 1) * steps_per_epoch + batch_idx + 1,
                           samples=args.batch_size)
        if batch_idx % args.log_interval == 0:
            loss_v = float(loss)
            print(
                "Train Epoch: {} [{}/{} ({:.0f}%)]\tloss={:.4f}".format(
                    epoch, batch_idx * args.batch_size, n,
                    100.0 * batch_idx / steps_per_epoch, loss_v,
                )
            )
            # 0-based global step, consistent with the profiler's indexing
            writer.add_scalar("loss", loss_v,
                              (epoch - 1) * steps_per_epoch + batch_idx)
            last_loss = loss_v
    return state, last_loss


def test_epoch(args, state, eval_step, mesh, test_x, test_y, epoch, writer, pe) -> float:
    total = len(test_x) - len(test_x) % args.test_batch_size
    lo, sz = dist.local_batch_slice(args.test_batch_size, pe)
    loss_sum, correct = 0.0, 0
    for bx, by in datalib.batches(
        test_x, test_y, args.test_batch_size, shuffle=False
    ):
        ls, c = eval_step(
            state["params"], train_lib.put_batch((bx[lo : lo + sz], by[lo : lo + sz]), mesh)
        )
        loss_sum += float(ls)
        correct += int(c)
    accuracy = correct / max(1, total)
    print("\naccuracy={:.4f}\n".format(accuracy))
    writer.add_scalar("accuracy", accuracy, epoch)
    return accuracy


def build_parser() -> argparse.ArgumentParser:
    # flag-for-flag with mnist.py:79-102
    p = argparse.ArgumentParser(description="TPU-native MNIST Example")
    p.add_argument("--batch-size", type=int, default=64, metavar="N",
                   help="input batch size for training (default: 64)")
    p.add_argument("--test-batch-size", type=int, default=1000, metavar="N",
                   help="input batch size for testing (default: 1000)")
    p.add_argument("--epochs", type=int, default=1, metavar="N",
                   help="number of epochs to train (default: 1)")
    p.add_argument("--lr", type=float, default=0.01, metavar="LR",
                   help="learning rate (default: 0.01)")
    p.add_argument("--momentum", type=float, default=0.5, metavar="M",
                   help="SGD momentum (default: 0.5)")
    p.add_argument("--seed", type=int, default=1, metavar="S",
                   help="random seed (default: 1)")
    p.add_argument("--log-interval", type=int, default=10, metavar="N",
                   help="how many batches to wait before logging training status")
    p.add_argument("--save-model", action="store_true", default=False,
                   help="For Saving the current Model")
    p.add_argument("--dir", default="logs", metavar="L",
                   help="directory where summary logs are stored")
    p.add_argument("--backend", type=str, choices=["xla"], default="xla",
                   help="Distributed backend (XLA collectives over ICI/DCN)")
    p.add_argument("--data-dir", default=None,
                   help="IDX dataset dir (torchvision layout); synthetic if absent")
    p.add_argument("--dataset", choices=["auto", "synthetic", "digits", "idx"],
                   default="auto",
                   help="auto = IDX files when present, else synthetic; "
                        "digits = real offline UCI handwritten digits")
    p.add_argument("--train-size", type=int, default=60000)
    p.add_argument("--test-size", type=int, default=10000)
    train_lib.add_profile_flags(p)
    return p


def run(args, mesh=None) -> Dict[str, Any]:
    pe = dist.initialize()
    if pe.is_distributed:
        print("Using distributed TPU with {} backend".format(args.backend))
    if mesh is None:
        mesh = dist.make_mesh({"data": -1}, env=pe)
    writer = train_lib.SummaryWriter(args.dir, enabled=pe.process_id == 0)

    dataset = datalib.resolve_dataset(args.data_dir, getattr(args, "dataset", "auto"))
    train_x, train_y, test_x, test_y = datalib.mnist_datasets(
        args.data_dir, args.train_size, args.test_size, dataset=dataset
    )
    # clamp so a small test set still yields at least one full batch
    # (drop_remainder would otherwise silently produce accuracy=0), rounded
    # to the mesh's batch-shard divisor so dim 0 stays evenly shardable
    div = dist.batch_divisor(mesh)
    args.test_batch_size = max(div, min(args.test_batch_size, len(test_x)) // div * div)
    args.batch_size = max(div, min(args.batch_size, len(train_x)) // div * div)

    model = Net()
    optimizer = train_lib.sgd(args.lr, args.momentum)
    rng = jax.random.PRNGKey(args.seed)
    state = train_lib.init_state(
        model.init(rng, jnp.zeros((1,) + datalib.IMAGE_SHAPE)), optimizer, mesh
    )
    train_step = train_lib.make_train_step(nll_loss, optimizer, mesh)
    eval_step = train_lib.make_eval_step(eval_metrics, mesh)

    accuracy, last_loss = 0.0, None
    profiler = train_lib.profiler_from_args(args, pe)
    telemetry = train_lib.TrainTelemetry.from_env()
    t0 = time.perf_counter()
    try:
        for epoch in range(1, args.epochs + 1):
            state, last_loss = train_epoch(
                args, state, train_step, mesh, train_x, train_y, epoch, writer, pe,
                profiler=profiler, telemetry=telemetry,
            )
            accuracy = test_epoch(
                args, state, eval_step, mesh, test_x, test_y, epoch, writer, pe
            )
        # honest wall time under --profile-dir: exclude trace drain +
        # serialization even when the window closed mid-loop
        wall = time.perf_counter() - t0 - profiler.overhead_s
    finally:
        profiler.close(block_on=state)
        telemetry.close()

    if args.save_model:
        # collective: every process participates in the orbax save (each
        # contributes its addressable shards; the dir must be a shared FS
        # on multi-host)
        ckpt = train_lib.Checkpointer(args.dir + "/ckpt")
        ckpt.save(int(state["step"]), state)
        ckpt.close()
        telemetry.checkpointed(int(state["step"]))
    writer.close()
    return {
        "accuracy": accuracy,
        "final_loss": last_loss,
        "wall_s": wall,
        "samples": (len(train_x) - len(train_x) % args.batch_size) * args.epochs,
        "dataset": dataset,
        "state": state,
    }


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)
    result = run(args)
    return 0 if result["accuracy"] > 0.0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
