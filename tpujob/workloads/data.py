"""Dataset plumbing for the example workloads.

The reference's MNIST example streams FashionMNIST through torchvision with
a per-rank DataLoader (``examples/mnist/mnist.py:117-132``).  This module
supplies the TPU equivalent: numpy arrays fed host-sharded into the global
batch (each host loads only its ``local_batch_slice`` rows).

Zero-egress environments can't download FashionMNIST, so the default is a
deterministic synthetic set with the same shape/num-classes and a learnable
class structure (class-conditional templates + noise); real IDX files are
used when present at ``data_dir`` (the torchvision on-disk format).
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Iterator, Optional, Tuple

import numpy as np

# FashionMNIST normalization constants used by the reference
# (examples/mnist/mnist.py:123-124)
MEAN, STD = 0.1307, 0.3081

IMAGE_SHAPE = (28, 28, 1)  # NHWC, channels-last is the TPU-friendly layout
NUM_CLASSES = 10


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">HBB", f.read(4))
        dtype = {8: np.uint8, 9: np.int8, 11: np.int16, 12: np.int32, 13: np.float32}[
            magic[1]
        ]
        dims = struct.unpack(">" + "I" * magic[2], f.read(4 * magic[2]))
        return np.frombuffer(f.read(), dtype=dtype).reshape(dims)


def _find_idx(data_dir: str, stem: str) -> Optional[str]:
    for sub in ("", "FashionMNIST/raw", "MNIST/raw"):
        for ext in ("", ".gz"):
            p = os.path.join(data_dir, sub, stem + ext)
            if os.path.exists(p):
                return p
    return None


def synthetic_split(
    n: int, seed: int, noise: float = 0.2
) -> Tuple[np.ndarray, np.ndarray]:
    """Class-conditional synthetic images: each class is a fixed random
    28x28 template; samples are template + gaussian noise.  Linearly
    separable enough that the reference CNN reaches high accuracy in one
    epoch, so accuracy assertions stay meaningful."""
    rng = np.random.RandomState(1234)  # templates shared across splits
    templates = rng.rand(NUM_CLASSES, 28, 28).astype(np.float32)
    rng2 = np.random.RandomState(seed)
    labels = rng2.randint(0, NUM_CLASSES, size=n).astype(np.int32)
    images = templates[labels] + noise * rng2.randn(n, 28, 28).astype(np.float32)
    images = np.clip(images, 0.0, 1.0)
    return images[..., None], labels


def _load_idx_splits(data_dir: str):
    ti = _find_idx(data_dir, "train-images-idx3-ubyte")
    tl = _find_idx(data_dir, "train-labels-idx1-ubyte")
    vi = _find_idx(data_dir, "t10k-images-idx3-ubyte")
    vl = _find_idx(data_dir, "t10k-labels-idx1-ubyte")
    if not (ti and tl and vi and vl):
        return None
    tx = _read_idx(ti).astype(np.float32)[..., None] / 255.0
    vx = _read_idx(vi).astype(np.float32)[..., None] / 255.0
    ty = _read_idx(tl).astype(np.int32)
    vy = _read_idx(vl).astype(np.int32)
    return (tx - MEAN) / STD, ty, (vx - MEAN) / STD, vy


def digits_datasets(
    train_size: int = 1500,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """The sklearn/UCI handwritten-digits set: REAL handwritten digit images
    available offline (1,797 samples).  8x8 greyscale, upscaled 3x and
    padded to 28x28 so the reference CNN runs unchanged.  The offline
    stand-in for the FashionMNIST accuracy-parity gate
    (``examples/mnist/mnist.py:117-132``) in zero-egress environments.
    """
    from sklearn.datasets import load_digits

    d = load_digits()
    x = d.images.astype(np.float32) / 16.0
    x = np.repeat(np.repeat(x, 3, axis=1), 3, axis=2)  # 8x8 -> 24x24
    x = np.pad(x, ((0, 0), (2, 2), (2, 2)))[..., None]  # -> 28x28 NHWC
    y = d.target.astype(np.int32)
    idx = np.arange(len(x))
    np.random.RandomState(0).shuffle(idx)
    x, y = (x - MEAN) / STD, y
    train_size = min(train_size, len(x) - 64)  # keep a real test split
    tr, te = idx[:train_size], idx[train_size:]
    return x[tr], y[tr], x[te], y[te]


_IDX_STEMS = (
    "train-images-idx3-ubyte",
    "train-labels-idx1-ubyte",
    "t10k-images-idx3-ubyte",
    "t10k-labels-idx1-ubyte",
)


def resolve_dataset(data_dir: Optional[str], dataset: str = "auto") -> str:
    """Which dataset ``mnist_datasets`` will serve: explicit choice, or
    ``auto`` = a COMPLETE IDX set under ``data_dir`` (all four files — a
    partial download must fall back, not crash), else synthetic."""
    if dataset in ("idx", "digits", "synthetic"):
        return dataset
    if data_dir and all(_find_idx(data_dir, stem) for stem in _IDX_STEMS):
        return "idx"
    return "synthetic"


def mnist_datasets(
    data_dir: Optional[str] = None,
    train_size: int = 60000,
    test_size: int = 10000,
    dataset: str = "auto",
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """(train_x, train_y, test_x, test_y), normalized, NHWC float32.

    ``dataset``: ``auto`` (IDX files under ``data_dir`` when present, else
    synthetic), or explicitly ``idx`` / ``digits`` (real, offline) /
    ``synthetic``.
    """
    resolved = resolve_dataset(data_dir, dataset)
    if resolved == "idx":
        splits = _load_idx_splits(data_dir) if data_dir else None
        if splits is None:
            raise FileNotFoundError(
                f"dataset 'idx' requested but no IDX files under {data_dir!r}"
            )
        return splits
    if resolved == "digits":
        return digits_datasets(train_size)
    tx, ty = synthetic_split(train_size, seed=0)
    vx, vy = synthetic_split(test_size, seed=1)
    return (tx - MEAN) / STD, ty, (vx - MEAN) / STD, vy


def batches(
    x: np.ndarray,
    y: np.ndarray,
    batch_size: int,
    *,
    seed: int = 0,
    shuffle: bool = True,
    drop_remainder: bool = True,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Epoch iterator (the DataLoader equivalent).  Drops the ragged tail by
    default — static shapes keep every step on the same compiled program."""
    n = len(x)
    idx = np.arange(n)
    if shuffle:
        np.random.RandomState(seed).shuffle(idx)
    end = n - n % batch_size if drop_remainder else n
    for start in range(0, end, batch_size):
        sel = idx[start : start + batch_size]
        yield x[sel], y[sel]


def synthetic_imagenet_batch(batch: int, image_size: int = 224, seed: int = 0):
    """A deterministic ImageNet-shaped batch for ResNet-50 benchmarking."""
    rng = np.random.RandomState(seed)
    x = rng.randn(batch, image_size, image_size, 3).astype(np.float32)
    y = rng.randint(0, 1000, size=batch).astype(np.int32)
    return x, y


def synthetic_token_batch(batch: int, seq_len: int, vocab: int = 30522, seed: int = 0):
    """A deterministic token batch for BERT benchmarking/pretraining."""
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, size=(batch, seq_len)).astype(np.int32)
    return ids


def byte_token_dataset(path: str, seq_len: int,
                       limit_chunks: Optional[int] = None) -> np.ndarray:
    """Real-text LM data with zero dependencies: the file's raw bytes,
    chunked to [n, seq_len] token ids (vocab 256).

    The byte-level analog of the reference example's real-dataset path
    (its MNIST streams FashionMNIST, ``examples/mnist/mnist.py:117-132``)
    for the LM workloads — any text or binary file is a corpus, with no
    tokenizer download (zero-egress-safe).

    The returned array is a **memory-mapped view** (uint8): a multi-GB
    corpus costs no host RAM until rows are actually sliced; fancy-indexed
    row reads (``chunks[idx]``) materialize only those rows.  Callers
    convert the sliced batch to int32 (``.astype``) at feed time.
    """
    size = os.path.getsize(path)
    n = size // seq_len
    if limit_chunks is not None:
        n = min(n, limit_chunks)
    if n == 0:
        raise ValueError(
            f"{path!r} holds {size} bytes — shorter than one "
            f"seq_len={seq_len} chunk")
    raw = np.memmap(path, dtype=np.uint8, mode="r", shape=(n * seq_len,))
    return raw.reshape(n, seq_len)


def bpe_token_dataset(path: str, seq_len: int, tokenizer,
                      cache_dir: Optional[str] = None) -> np.ndarray:
    """BPE-tokenized corpus as memory-mapped [n, seq_len] chunks.

    The corpus is encoded ONCE into a sidecar token file next to the
    corpus and memory-mapped thereafter (uint16 when the vocab fits, else
    uint32) — the RAM cost per host is the sliced batch, not the corpus.
    The sidecar name carries a digest of the tokenizer's merges AND the
    corpus size/mtime, so editing the corpus or retraining the tokenizer
    invalidates the cache instead of silently serving stale tokens.
    """
    import hashlib

    v = tokenizer.vocab_size
    dtype = np.uint16 if v <= np.iinfo(np.uint16).max else np.uint32
    st = os.stat(path)
    key = hashlib.sha1(
        repr((tokenizer.merges, st.st_size, st.st_mtime_ns)).encode()
    ).hexdigest()[:12]
    base = os.path.join(cache_dir, os.path.basename(path)) if cache_dir else path
    sidecar = f"{base}.bpe{v}-{key}.tokens"
    if not os.path.exists(sidecar):
        with open(path, "rb") as f:
            ids = tokenizer.encode(f.read())
        # per-process tmp name + atomic replace: concurrent hosts building
        # the same cache race benignly (last replace wins, same content)
        tmp = f"{sidecar}.{os.getpid()}.tmp"
        ids.astype(dtype).tofile(tmp)
        os.replace(tmp, sidecar)
    count = os.path.getsize(sidecar) // np.dtype(dtype).itemsize
    n = count // seq_len
    if n == 0:
        raise ValueError(
            f"{path!r} encodes to {count} BPE tokens — shorter than one "
            f"seq_len={seq_len} chunk")
    toks = np.memmap(sidecar, dtype=dtype, mode="r", shape=(n * seq_len,))
    return toks.reshape(n, seq_len)
