"""Shared SPMD training machinery for the example workloads.

The reference's training loop is torch-imperative: forward, ``loss.backward()``,
allreduce via the DDP hook, ``optimizer.step()``
(``examples/mnist/mnist.py:35-49``).  The TPU-native loop is one jitted
functional step: ``jax.value_and_grad`` under ``jit`` over a Mesh, with the
gradient all-reduce inserted by XLA from the sharding annotations (params
replicated, batch sharded on the data axis) — there is no explicit
collective to write for DP.

Also here: checkpoint/save-restore (orbax — the ``torch.save`` equivalent,
mnist.py:146-147, upgraded to resumable distributed checkpointing the
reference lacks, SURVEY.md §5) and a SummaryWriter-compatible scalar logger
(the tensorboardX shim; JSONL on disk, no display deps).
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from tpujob.workloads import distributed as dist


class SummaryWriter:
    """tensorboardX-shaped scalar writer (mnist.py:6,49,65 ``add_scalar``).

    Writes one JSONL file per run; only process 0 writes, matching the
    usual multi-host convention.
    """

    def __init__(self, logdir: str, enabled: Optional[bool] = None):
        self.logdir = logdir
        if enabled is None:
            enabled = dist.process_env().process_id == 0
        self.enabled = enabled
        self._f = None
        if enabled:
            os.makedirs(logdir, exist_ok=True)
            self._f = open(os.path.join(logdir, "scalars.jsonl"), "a")

    def add_scalar(self, tag: str, value, step: int) -> None:
        if self._f:
            self._f.write(
                json.dumps({"tag": tag, "value": float(value), "step": int(step),
                            "wall_time": time.time()}) + "\n"
            )
            self._f.flush()

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None


class Profiler:
    """``--profile-dir`` hook: wraps N steady-state steps in
    ``jax.profiler.start_trace``/``stop_trace``.

    The TPU-first observability story the reference lacks: its only
    profiling is workload-side tensorboardX scalars plus cAdvisor container
    dashboards (``docs/monitoring/README.md:17-46``,
    ``examples/mnist/mnist.py:6,108``).  A JAX trace captures the XLA/TPU
    timeline (MXU utilization, HBM transfers, collective overlap) viewable
    in TensorBoard's profile plugin or Perfetto.

    Skips the first ``start_step`` steps (compilation/warmup would drown
    the steady state); only process 0 traces by default.  Call ``step(i)``
    at each loop iteration top and ``close()`` after the loop.  In
    epoch-style loops a window larger than one epoch keeps tracing until
    the step count is reached in the next epoch, so whatever runs between
    (eval, checkpointing) appears in the trace — by design, that IS the
    steady state of such a loop.
    """

    def __init__(
        self,
        profile_dir: Optional[str],
        start_step: int = 2,
        num_steps: int = 3,
        enabled: Optional[bool] = None,
    ):
        if enabled is None:
            enabled = dist.process_env().process_id == 0
        self.profile_dir = profile_dir
        self.start_step = start_step
        self.stop_step = start_step + max(1, num_steps)
        self.enabled = bool(profile_dir) and enabled
        self._active = False
        # wall time spent draining + serializing the trace: callers
        # subtract it from their timed region so profiled runs report
        # honest throughput even when the window closes mid-loop
        self.overhead_s = 0.0

    def step(self, step: int, block_on=None) -> None:
        """Call at each loop iteration top; ``block_on`` is the previous
        step's output — JAX dispatch is async, so the trace must wait for
        the traced steps to actually execute on device before stopping, or
        it captures host-side dispatch with an empty device timeline."""
        if not self.enabled:
            return
        if not self._active and self.start_step <= step < self.stop_step:
            jax.profiler.start_trace(self.profile_dir)
            self._active = True
        elif self._active and step >= self.stop_step:
            self._finish(block_on)

    def close(self, block_on=None) -> None:
        """Stop an in-flight trace (short runs that never reach stop_step,
        or an exception inside the window — call from finally: the profiler
        is process-global, and a leaked trace poisons the next run)."""
        if self._active:
            self._finish(block_on)

    def _finish(self, block_on=None) -> None:
        self._active = False
        self.enabled = False  # one trace window per run
        try:
            # the drain waits for counted training compute — NOT overhead
            # (classifying it as overhead would inflate profiled steps/sec)
            if block_on is not None:
                jax.block_until_ready(block_on)
        finally:
            t0 = time.perf_counter()
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # never let trace teardown kill training
                import logging

                logging.getLogger("tpujob.workloads").warning(
                    "profiler stop_trace failed: %s", e)
            self.overhead_s += time.perf_counter() - t0


class TrainTelemetry:
    """Step-loop telemetry: smoothed samples/sec plus the progress
    heartbeat (``tpujob.dev/progress``) the operator's telemetry plane and
    Stalled-job watchdog consume.

    Call :meth:`step` at each loop iteration (after the train step
    dispatched) and :meth:`checkpointed` after each durable save.  Only the
    coordinator publishes by default — the controller reads one heartbeat
    per job, and process 0 is the one whose silence means the job is stuck
    (a straggling non-coordinator host stalls the collective, which stalls
    process 0's step clock right along with it).  With no reporter (local
    runs, tests) this is throughput bookkeeping only.
    """

    def __init__(self, reporter: Optional["dist.ProgressReporter"] = None,
                 enabled: Optional[bool] = None, ema: float = 0.3,
                 clock: Callable[[], float] = time.monotonic):
        if enabled is None:
            enabled = dist.process_env().process_id == 0
        self.reporter = reporter if enabled else None
        self._ema = ema
        self._clock = clock
        self.samples_per_sec: Optional[float] = None
        self.step_count = 0
        self.checkpoint_step: Optional[int] = None
        self.resize_generation = 0
        self._last_t: Optional[float] = None

    @classmethod
    def from_env(cls, interval_s: float = 10.0) -> "TrainTelemetry":
        """The conventional in-cluster construction: coordinator publishes
        through the pod-identity env (no-op reporter everywhere else)."""
        pe = dist.process_env()
        publish = (dist.progress_publisher_from_env()
                   if pe.process_id == 0 else None)
        return cls(reporter=dist.ProgressReporter(publish,
                                                  interval_s=interval_s),
                   enabled=pe.process_id == 0)

    def step(self, step: int, samples: int = 0,
             resize_generation: Optional[int] = None) -> None:
        """One loop iteration: fold ``samples`` into the throughput EMA and
        heartbeat (rate-limited inside the reporter)."""
        now = self._clock()
        if self._last_t is not None and samples > 0:
            dt = now - self._last_t
            if dt > 0:
                inst = samples / dt
                self.samples_per_sec = (
                    inst if self.samples_per_sec is None
                    else self._ema * inst + (1 - self._ema) * self.samples_per_sec)
        self._last_t = now
        self.step_count = step
        if resize_generation is not None:
            self.resize_generation = resize_generation
        if self.reporter is not None:
            self.reporter.report(step, self.samples_per_sec,
                                 self.checkpoint_step, self.resize_generation)

    def checkpointed(self, step: int) -> None:
        """A durable checkpoint landed: publish immediately (the watchdog's
        checkpoint-age metric keys off this)."""
        self.checkpoint_step = step
        if self.reporter is not None:
            self.reporter.report(self.step_count, self.samples_per_sec,
                                 step, self.resize_generation, force=True)

    def close(self) -> None:
        """Final forced heartbeat so the controller sees the last step."""
        if self.reporter is not None and self.step_count:
            self.reporter.report(self.step_count, self.samples_per_sec,
                                 self.checkpoint_step,
                                 self.resize_generation, force=True)


def add_profile_flags(parser) -> None:
    """The shared --profile-* surface for every workload CLI."""
    parser.add_argument("--profile-dir", default=None,
                        help="write a jax.profiler trace of steady-state "
                             "steps here (TensorBoard profile plugin format)")
    parser.add_argument("--profile-start-step", type=int, default=2,
                        help="first step of the trace window (skips compile)")
    parser.add_argument("--profile-steps", type=int, default=3,
                        help="number of steps to trace")


def profiler_from_args(args, pe) -> Profiler:
    return Profiler(
        getattr(args, "profile_dir", None),
        start_step=getattr(args, "profile_start_step", 2),
        num_steps=getattr(args, "profile_steps", 3),
        enabled=pe.process_id == 0,
    )


# ---------------------------------------------------------------------------
# Train state + step
# ---------------------------------------------------------------------------


def sgd(lr: float, momentum: float) -> optax.GradientTransformation:
    """The reference's optimizer (optim.SGD(lr, momentum), mnist.py:141)."""
    return optax.sgd(lr, momentum=momentum)


def _decay_mask(params):
    """Decay matrices/embeddings only — biases, LayerNorm scales, and
    scalars are excluded (the standard AdamW practice: decaying a
    LayerNorm scale toward 0 fights the normalization)."""
    return jax.tree.map(lambda p: getattr(p, "ndim", 0) >= 2, params)


def adamw(lr, weight_decay: float = 0.01) -> optax.GradientTransformation:
    """Transformer-default optimizer (BERT pretraining).  ``lr`` may be a
    float or an optax schedule.  Weight decay applies to >=2D params only
    (see ``_decay_mask``)."""
    return optax.adamw(lr, weight_decay=weight_decay, mask=_decay_mask)


def make_lr_schedule(lr: float, kind: str, warmup_steps: int, total_steps: int):
    """Learning-rate schedule from the flag surface: linear warmup to
    ``lr`` over ``warmup_steps``, then constant or cosine decay to 0 at
    ``total_steps``.  Returns a plain float when there is nothing to
    schedule (XLA then folds the constant)."""
    if kind == "cosine":
        if warmup_steps <= 0:
            # no warmup: decay from peak — warmup_cosine with a forced
            # 1-step warmup would run the FIRST update at LR 0 (a no-op)
            return optax.cosine_decay_schedule(lr, max(total_steps, 1))
        return optax.warmup_cosine_decay_schedule(
            0.0, lr, warmup_steps, max(total_steps, warmup_steps + 1))
    if kind != "constant":
        raise ValueError(f"unknown lr schedule {kind!r}")
    if warmup_steps > 0:
        return optax.join_schedules(
            [optax.linear_schedule(0.0, lr, warmup_steps),
             optax.constant_schedule(lr)],
            [warmup_steps])
    return lr


def with_grad_accum(optimizer: optax.GradientTransformation, every: int):
    """Gradient accumulation: average grads over ``every`` consecutive
    mini-steps, apply one optimizer update (optax.MultiSteps).  The
    train-step shape is unchanged — ``state['step']`` counts mini-steps;
    the effective batch is every x the fed batch."""
    if every <= 0:
        raise ValueError(f"grad accumulation must be >= 1, got {every}")
    if every == 1:
        return optimizer
    return optax.MultiSteps(optimizer, every_k_schedule=every)


def init_state(
    params: Any,
    optimizer: optax.GradientTransformation,
    mesh=None,
    extra: Any = None,
) -> Dict[str, Any]:
    """{'params','opt','step'[,'extra']} pytree, replicated over the mesh.

    ``extra`` carries non-gradient mutable collections (e.g. BatchNorm
    running stats) threaded through the train step.
    """
    state = {"params": params, "opt": optimizer.init(params), "step": jnp.zeros((), jnp.int32)}
    if extra is not None:
        state["extra"] = extra
    if mesh is not None:
        state = jax.device_put(state, dist.replicated(mesh))
    return state


def make_train_step(
    loss_fn: Callable[..., Any],
    optimizer: optax.GradientTransformation,
    mesh,
    donate: bool = True,
    has_extra: bool = False,
    state_shardings: Any = None,
    value_and_grad_fn: Optional[Callable] = None,
):
    """Build the jitted DP train step.

    ``loss_fn(params, batch) -> scalar mean loss`` (or, with ``has_extra``,
    ``loss_fn(params, extra, batch) -> (loss, new_extra)`` for mutable
    collections like BatchNorm stats).  Shardings: state replicated, or
    pinned to ``state_shardings`` (a pytree of NamedShardings) when the
    caller committed rule-based tensor-parallel layouts; batch is split on
    the data axis.  XLA inserts the gradient psum from the annotations
    (this is DDP's allreduce, compiled).

    ``value_and_grad_fn(params, batch) -> (loss, grads)`` replaces
    ``jax.value_and_grad(loss_fn)`` when the gradient computation is
    itself a schedule (the 1F1B pipeline interleaves each microbatch's
    backward between other microbatches' forwards, which a transpose of
    the forward cannot express).
    """
    if value_and_grad_fn is not None and has_extra:
        raise ValueError(
            "value_and_grad_fn does not support has_extra (it returns "
            "(loss, grads) with no mutable-collection slot)")
    repl = dist.replicated(mesh)
    bsh = dist.batch_sharding(mesh)
    step = _step_body(loss_fn, optimizer, has_extra, value_and_grad_fn)

    if state_shardings is not None:
        # Tensor-parallel case: the caller committed params (and the
        # optimizer moments initialized from them) to rule-derived layouts;
        # pin outputs to the same layouts so the step is layout-stable
        # (an AOT-compiled executable must see identical shardings each call)
        return jax.jit(
            step,
            in_shardings=(state_shardings, bsh),
            out_shardings=(state_shardings, repl),
            donate_argnums=(0,) if donate else (),
        )
    # a single sharding is a valid pytree prefix for the whole state dict
    return jax.jit(
        step,
        in_shardings=(repl, bsh),
        out_shardings=(repl, repl),
        donate_argnums=(0,) if donate else (),
    )


def _step_body(loss_fn, optimizer, has_extra, value_and_grad_fn=None):
    """The pure train step shared by the single- and multi-step builders."""

    def step(state, batch):
        if value_and_grad_fn is not None:
            loss, grads = value_and_grad_fn(state["params"], batch)
        elif has_extra:
            (loss, extra), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state["params"], state["extra"], batch
            )
        else:
            loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch)
        updates, opt = optimizer.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        out = {"params": params, "opt": opt, "step": state["step"] + 1}
        if has_extra:
            out["extra"] = extra
        return out, loss

    return step


def make_multi_step(
    loss_fn: Callable[..., Any],
    optimizer: optax.GradientTransformation,
    mesh,
    k: int,
    donate: bool = True,
    has_extra: bool = False,
    stacked: bool = False,
    state_shardings: Any = None,
):
    """Like :func:`make_train_step`, but one dispatch runs ``k`` optimizer
    updates under ``lax.scan`` and returns the per-step losses ``[k]``.

    On a dispatch-latency-bound link (the usual state of a tunneled or
    contended TPU: one host→device round trip costs more than a small
    model's step takes to compute) the host loop pays that latency every
    step; scanning k steps in-graph pays it once per k.  With
    ``stacked=True`` the batch leaves carry a leading ``[k]`` dim of
    per-step microbatches (the real-training shape, sharded on dim 1 by
    the caller); otherwise one batch is reused for every step (the
    steady-state benchmark shape).
    """
    if k < 1:
        raise ValueError(f"multi-step k must be >= 1, got {k}")
    repl = dist.replicated(mesh)
    step = _step_body(loss_fn, optimizer, has_extra)

    if stacked:
        def multi(state, batches):
            lead = jax.tree_util.tree_leaves(batches)[0].shape[0]
            if lead != k:
                raise ValueError(
                    f"stacked batch carries {lead} microbatches but the "
                    f"multi-step was built with k={k}")
            return jax.lax.scan(step, state, batches)
    else:
        def multi(state, batch):
            return jax.lax.scan(lambda s, _: step(s, batch), state, None,
                                length=k)

    # the batch arrives with whatever sharding the caller committed
    # (put_batch); state replicated unless pinned to rule-derived layouts
    # (TP/FSDP), mirroring make_train_step
    ssh = state_shardings if state_shardings is not None else repl
    return jax.jit(
        multi,
        in_shardings=(ssh, None),
        out_shardings=(ssh, repl),
        donate_argnums=(0,) if donate else (),
    )


def make_eval_step(
    metric_fn: Callable[[Any, Tuple[jax.Array, ...]], Any],
    mesh,
):
    """Jitted eval step: replicated params, sharded batch, replicated metrics."""
    repl = dist.replicated(mesh)
    bsh = dist.batch_sharding(mesh)
    return jax.jit(metric_fn, in_shardings=(repl, bsh), out_shardings=repl)


def put_batch(batch, mesh):
    """Assemble the global batch, dim-0 sharded on the batch axes.

    Each process passes only its own rows (its ``local_batch_slice`` of the
    global batch).  Single-process: the local rows are the global batch and
    a plain device_put suffices.  Multi-host: only this host's devices are
    addressable, so the global array is assembled with
    ``make_array_from_process_local_data`` — no cross-host transfer.
    """
    sh = dist.batch_sharding(mesh)
    if jax.process_count() == 1:
        return jax.tree.map(lambda a: jax.device_put(a, sh), batch)
    return jax.tree.map(
        lambda a: jax.make_array_from_process_local_data(sh, a), batch
    )


# ---------------------------------------------------------------------------
# Checkpointing (orbax)
# ---------------------------------------------------------------------------


class Checkpointer:
    """Step-numbered save/restore for the train state.

    The resume story the reference leaves to the workload (SURVEY.md §5
    "Checkpoint/resume: none in the operator"): with OnFailure restarts the
    re-scheduled pod calls ``latest_step`` + ``restore`` and continues.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, state) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: int, like):
        import orbax.checkpoint as ocp

        return self._mgr.restore(step, args=ocp.args.StandardRestore(like))

    def restore_latest(self, like) -> Tuple[Optional[int], Any]:
        """``(step, state)`` from the newest checkpoint, or ``(None, like)``
        when none exists yet (cold start).

        The elastic-resize restore contract: after a world-size change the
        runtime re-rendezvouses (``distributed.reinitialize``) and device
        arrays do not survive — the surviving processes restore from here
        and continue at the checkpointed step.  Because the controller runs
        a checkpoint barrier before draining (and the workload pauses
        stepping between its ack and the republish), a clean shrink resumes
        EXACTLY where it acked — the latest step, not a cold start."""
        step = self._mgr.latest_step()
        if step is None:
            return None, like
        return step, self.restore(step, like)

    def close(self) -> None:
        self._mgr.close()
