"""Shared SPMD training machinery for the example workloads.

The reference's training loop is torch-imperative: forward, ``loss.backward()``,
allreduce via the DDP hook, ``optimizer.step()``
(``examples/mnist/mnist.py:35-49``).  The TPU-native loop is one jitted
functional step: ``jax.value_and_grad`` under ``jit`` over a Mesh, with the
gradient all-reduce inserted by XLA from the sharding annotations (params
replicated, batch sharded on the data axis) — there is no explicit
collective to write for DP.

Also here: checkpoint/save-restore (orbax — the ``torch.save`` equivalent,
mnist.py:146-147, upgraded to resumable distributed checkpointing the
reference lacks, SURVEY.md §5) and a SummaryWriter-compatible scalar logger
(the tensorboardX shim; JSONL on disk, no display deps).
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from tpujob.workloads import distributed as dist


class SummaryWriter:
    """tensorboardX-shaped scalar writer (mnist.py:6,49,65 ``add_scalar``).

    Writes one JSONL file per run; only process 0 writes, matching the
    usual multi-host convention.
    """

    def __init__(self, logdir: str, enabled: Optional[bool] = None):
        self.logdir = logdir
        if enabled is None:
            enabled = dist.process_env().process_id == 0
        self.enabled = enabled
        self._f = None
        if enabled:
            os.makedirs(logdir, exist_ok=True)
            self._f = open(os.path.join(logdir, "scalars.jsonl"), "a")

    def add_scalar(self, tag: str, value, step: int) -> None:
        if self._f:
            self._f.write(
                json.dumps({"tag": tag, "value": float(value), "step": int(step),
                            "wall_time": time.time()}) + "\n"
            )
            self._f.flush()

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None


# ---------------------------------------------------------------------------
# Train state + step
# ---------------------------------------------------------------------------


def sgd(lr: float, momentum: float) -> optax.GradientTransformation:
    """The reference's optimizer (optim.SGD(lr, momentum), mnist.py:141)."""
    return optax.sgd(lr, momentum=momentum)


def init_state(
    model_init: Callable[..., Any],
    optimizer: optax.GradientTransformation,
    rng: jax.Array,
    sample_input: jax.Array,
    mesh=None,
) -> Dict[str, Any]:
    """{'params','opt','step'} pytree, replicated over the mesh when given."""
    params = model_init(rng, sample_input)
    state = {"params": params, "opt": optimizer.init(params), "step": jnp.zeros((), jnp.int32)}
    if mesh is not None:
        state = jax.device_put(state, dist.replicated(mesh))
    return state


def make_train_step(
    loss_fn: Callable[[Any, Tuple[jax.Array, ...]], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh,
    donate: bool = True,
):
    """Build the jitted DP train step.

    ``loss_fn(params, batch) -> scalar mean loss``.  Shardings: state
    replicated, batch split on the data axis; XLA inserts the psum for the
    replicated-output gradients (this is DDP's allreduce, compiled).
    """
    repl = dist.replicated(mesh)
    bsh = dist.batch_sharding(mesh)

    def step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch)
        updates, opt = optimizer.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {"params": params, "opt": opt, "step": state["step"] + 1}, loss

    return jax.jit(
        step,
        in_shardings=(repl, bsh),
        out_shardings=(repl, repl),
        donate_argnums=(0,) if donate else (),
    )


def make_eval_step(
    metric_fn: Callable[[Any, Tuple[jax.Array, ...]], Any],
    mesh,
):
    """Jitted eval step: replicated params, sharded batch, replicated metrics."""
    repl = dist.replicated(mesh)
    bsh = dist.batch_sharding(mesh)
    return jax.jit(metric_fn, in_shardings=(repl, bsh), out_shardings=repl)


def put_batch(batch, mesh):
    """Assemble the global batch, dim-0 sharded on the batch axes.

    Each process passes only its own rows (its ``local_batch_slice`` of the
    global batch).  Single-process: the local rows are the global batch and
    a plain device_put suffices.  Multi-host: only this host's devices are
    addressable, so the global array is assembled with
    ``make_array_from_process_local_data`` — no cross-host transfer.
    """
    sh = dist.batch_sharding(mesh)
    if jax.process_count() == 1:
        return jax.tree.map(lambda a: jax.device_put(a, sh), batch)
    return jax.tree.map(
        lambda a: jax.make_array_from_process_local_data(sh, a), batch
    )


# ---------------------------------------------------------------------------
# Checkpointing (orbax)
# ---------------------------------------------------------------------------


class Checkpointer:
    """Step-numbered save/restore for the train state.

    The resume story the reference leaves to the workload (SURVEY.md §5
    "Checkpoint/resume: none in the operator"): with OnFailure restarts the
    re-scheduled pod calls ``latest_step`` + ``restore`` and continues.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, state) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: int, like):
        import orbax.checkpoint as ocp

        return self._mgr.restore(step, args=ocp.args.StandardRestore(like))

    def close(self) -> None:
        self._mgr.close()
