"""GPT-style decoder-only causal-LM pretraining workload.

The autoregressive model family on the same TPU-first machine as BERT:
the encoder blocks of ``tpujob.workloads.bert`` with a causal mask threaded
through whichever attention path the flags pick (dense XLA, Pallas flash,
ring or Ulysses sequence parallelism — all four implement ``causal=True``),
a GPT-2-style ``ln_f`` before the tied LM head, and next-token
cross-entropy.  The full parallelism matrix applies unchanged: DP,
FSDP/ZeRO-3, tensor, sequence, GPipe pipeline, and sparse-MoE expert
parallelism, all via the shared flag surface and ``bert.PARTITION_RULES``.

The reference ships no GPT workload (its examples are MNIST and a
send/recv smoke, SURVEY.md §2.3); this is model-family breadth beyond it,
sized GPT-2-medium by default.

Entrypoint:
    python -m tpujob.workloads.gpt --steps 100 --layers 24
"""
from __future__ import annotations

import argparse
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from tpujob.workloads import bert as bertlib
from tpujob.workloads import distributed as dist


def lm_loss(model, aux_coef: float = 0.01, z_coef: float = 1e-3,
            apply_fn: Optional[Callable] = None):
    """Next-token cross-entropy (shift-by-one), plus the MoE aux losses
    when the FFNs are sparse — same collection plumbing as bert.mlm_loss."""

    def loss_fn(params, batch):
        (ids,) = batch  # [b, s]
        if apply_fn is not None:
            logits, sown = apply_fn(params, ids), {}
        elif model.moe is not None:
            logits, sown = model.apply(params, ids, mutable=["moe_metrics"])
        else:
            logits, sown = model.apply(params, ids), {}
        logp = jax.nn.log_softmax(logits[:, :-1])
        tok_ll = jnp.take_along_axis(logp, ids[:, 1:, None], axis=-1)[..., 0]
        loss = -tok_ll.mean()
        if sown:
            loss = (loss
                    + aux_coef * bertlib._mean_sown(sown, "load_balance")
                    + z_coef * bertlib._mean_sown(sown, "router_z"))
        return loss

    return loss_fn


def sample_next(logit: jax.Array, key, *, temperature: float = 0.0,
                top_k: int = 0, top_p: float = 0.0) -> jax.Array:
    """One sampling decision over [batch, vocab] logits — the shared
    policy for both decode paths.

    ``temperature`` 0 = greedy argmax (top_k/top_p ignored); otherwise
    softmax sampling, optionally truncated to the ``top_k`` highest
    logits and/or the smallest prefix of the sorted distribution whose
    probability mass reaches ``top_p`` (nucleus sampling — the first
    token crossing the threshold stays in).  All static-shape masking,
    so the decode still compiles to one executable.
    """
    if temperature <= 0.0:
        return jnp.argmax(logit, axis=-1)
    logit = logit / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logit, top_k)[0][..., -1:]
        logit = jnp.where(logit < kth, -jnp.inf, logit)
    if top_p > 0.0:
        sorted_logit = jnp.sort(logit, axis=-1)[..., ::-1]
        csum = jnp.cumsum(jax.nn.softmax(sorted_logit, axis=-1), axis=-1)
        # keep every token whose PRECEDING mass is < top_p (the token
        # that crosses the threshold is included, per the original paper)
        keep = jnp.concatenate(
            [jnp.ones_like(csum[..., :1], bool), csum[..., :-1] < top_p],
            axis=-1)
        cutoff = jnp.min(jnp.where(keep, sorted_logit, jnp.inf), axis=-1,
                         keepdims=True)
        logit = jnp.where(logit < cutoff, -jnp.inf, logit)
    return jax.random.categorical(key, logit)


def generate(
    model,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    rng: Optional[jax.Array] = None,
):
    """Autoregressive decode, TPU-style: static shapes, one compile, a
    ``lax.scan`` over positions.

    Correctness-first design: each step runs the full forward over a
    fixed-length buffer — the causal mask makes positions past the cursor
    inert, so the suffix padding cannot influence sampled tokens.  For MoE
    models the attention mask alone is not enough (padding positions would
    compete for expert-capacity slots and could evict a realized token's
    assignment), so a validity mask additionally stops routing past the
    cursor (``parallel.moe_ffn`` ``valid``).  (A KV cache would make each
    step O(1) in recompute; this is O(n) but compiles to one executable
    with no dynamic shapes.)
    ``temperature`` 0 = greedy argmax; > 0 samples from the softmax with
    ``rng``.  Returns [batch, prompt_len + max_new_tokens] token ids.
    """
    b, p = prompt.shape
    total = p + max_new_tokens
    if total > model.max_seq:
        raise ValueError(
            f"prompt {p} + max_new_tokens {max_new_tokens} exceeds the "
            f"model's max_seq {model.max_seq}")
    if temperature > 0 and rng is None:
        raise ValueError("temperature > 0 sampling needs an rng key")
    if rng is None:
        rng = jax.random.PRNGKey(0)  # unused on the greedy path
    buf = jnp.zeros((b, total), jnp.int32).at[:, :p].set(prompt)

    @jax.jit
    def decode(params, buf, rng):
        def step(carry, i):
            buf, rng = carry
            if model.moe is not None:
                # positions [0, p+i) are realized; the rest must not route
                valid = (jnp.arange(total)[None, :] < p + i).astype(
                    jnp.float32) * jnp.ones((b, 1))
                logits = model.apply(params, buf, valid)  # [b, total, V]
            else:
                logits = model.apply(params, buf)  # [b, total, V]
            # token i is written at position p+i, predicted from p+i-1
            logit = jax.lax.dynamic_slice_in_dim(
                logits, p + i - 1, 1, axis=1)[:, 0]
            rng, key = jax.random.split(rng)
            nxt = sample_next(logit, key, temperature=temperature,
                              top_k=top_k, top_p=top_p)
            buf = jax.lax.dynamic_update_slice_in_dim(
                buf, nxt[:, None].astype(jnp.int32), p + i, axis=1)
            return (buf, rng), None

        (buf, _), _ = jax.lax.scan(
            step, (buf, rng), jnp.arange(max_new_tokens))
        return buf

    return decode(params, buf, rng)


def generate_cached(
    model,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    rng: Optional[jax.Array] = None,
):
    """KV-cached autoregressive decode: O(1) recompute per token.

    Clones the trained model into decode mode (`Bert.decode`) — each layer
    keeps past K/V in a mutable ``cache`` collection and the forward sees
    ONE token per step, vs :func:`generate`'s full re-forward.  Same param
    tree, so the trained params drop in; attention falls back to the
    dense cached path regardless of the training-time attention_fn (all
    attention variants here are exact, so numerics match — pinned by
    ``test_generate_cached_matches_full_reforward``).  One ``lax.scan``
    covers prefill and generation uniformly: prompt positions feed the
    known token, later positions feed the sampled one.
    """
    b, p = prompt.shape
    total = p + max_new_tokens
    if total > model.max_seq:
        raise ValueError(
            f"prompt {p} + max_new_tokens {max_new_tokens} exceeds the "
            f"model's max_seq {model.max_seq}")
    if temperature > 0 and rng is None:
        raise ValueError("temperature > 0 sampling needs an rng key")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if model.moe is not None:
        # MoE capacity is per-sequence-length: a 1-token step never drops
        # tokens while a full forward may, so cached decode would not be
        # the same function — use the exact re-forward path instead
        return generate(model, params, prompt, max_new_tokens,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        rng=rng)
    dm = model.clone(decode=total, attention_fn=None, remat=False)
    # only the cache SHAPES are wanted: eval_shape avoids materializing
    # (and then discarding) a full parameter tree
    cache_shapes = jax.eval_shape(
        dm.init, jax.random.PRNGKey(0), jnp.zeros((b, 1), jnp.int32))["cache"]
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)
    buf = jnp.zeros((b, total), jnp.int32).at[:, :p].set(prompt)

    @jax.jit
    def decode(params, cache, buf, rng):
        def step(carry, i):
            cache, buf, rng = carry
            tok = jax.lax.dynamic_slice_in_dim(buf, i, 1, axis=1)  # [b, 1]
            logits, mut = dm.apply(
                {**params, "cache": cache}, tok, mutable=["cache"])
            cache = mut["cache"]
            logit = logits[:, 0]
            rng, key = jax.random.split(rng)
            sampled = sample_next(logit, key, temperature=temperature,
                                  top_k=top_k, top_p=top_p)
            # within the prompt the next token is already known
            known = jax.lax.dynamic_slice_in_dim(buf, i + 1, 1, axis=1)[:, 0]
            nxt = jnp.where(i + 1 < p, known, sampled).astype(jnp.int32)
            buf = jax.lax.dynamic_update_slice_in_dim(
                buf, nxt[:, None], i + 1, axis=1)
            return (cache, buf, rng), None

        (_, buf, _), _ = jax.lax.scan(
            step, (cache, buf, rng), jnp.arange(total - 1))
        return buf

    return decode(params, cache, buf, rng)


def build_parser() -> argparse.ArgumentParser:
    """The BERT flag surface with decoder defaults (GPT-2-medium shapes,
    GPT-2 vocab)."""
    p = bertlib.build_parser()
    p.description = "TPU-native GPT (decoder-only) causal-LM pretrain"
    p.set_defaults(vocab=50257, seq_len=1024)
    p.add_argument("--generate", type=int, default=0, metavar="N",
                   help="after training, decode N tokens from a "
                        "training-batch prefix and print the ids")
    p.add_argument("--generate-temperature", type=float, default=0.0,
                   help="0 = greedy; > 0 samples from the softmax")
    p.add_argument("--generate-top-k", type=int, default=0,
                   help="sample only from the k highest logits (0 = off)")
    p.add_argument("--generate-top-p", type=float, default=0.0,
                   help="nucleus sampling: smallest prefix of the sorted "
                        "distribution reaching this mass (0 = off)")
    return p


def build_model(args, mesh):
    return bertlib.build_model(args, mesh, causal=True, final_ln=True)


make_mesh_for = bertlib.make_mesh_for


def run(args, mesh=None) -> Dict[str, Any]:
    pe = dist.initialize()
    n_gen = getattr(args, "generate", 0)
    if n_gen >= args.seq_len:
        # fail BEFORE training, not after the whole run completed
        raise ValueError(
            f"--generate {n_gen} must leave room for a prompt within "
            f"--seq-len {args.seq_len} (need generate <= seq-len - 1)")
    if getattr(args, "generate_temperature", 0.0) <= 0.0 and (
            getattr(args, "generate_top_k", 0)
            or getattr(args, "generate_top_p", 0.0)):
        # never drop a requested behavior silently: greedy decode ignores
        # the truncation flags
        raise ValueError(
            "--generate-top-k/--generate-top-p need "
            "--generate-temperature > 0 (greedy decode samples nothing)")
    if mesh is None:
        mesh = make_mesh_for(args, pe)
    model = build_model(args, mesh)
    tok = bertlib.tokenizer_from_args(args)
    ids0, provider, sample = bertlib.token_batches(args, pe, tokenizer=tok)
    bp = None if provider is None else (lambda step: (provider(step),))

    def make_f1b(micro, shards):
        """Causal-LM per-microbatch loss for the 1F1B schedule: the
        shift-by-one token mean — per-microbatch token counts are equal,
        so the schedule's mean of means IS the global mean (no scaling)."""

        def preprocess(batch):
            (ids,) = batch
            return ids, (ids,)

        def head_loss(logits, ex):
            (ids_mb,) = ex
            logp = jax.nn.log_softmax(logits[:, :-1])
            tok_ll = jnp.take_along_axis(
                logp, ids_mb[:, 1:, None], axis=-1)[..., 0]
            return -tok_ll.mean()

        return preprocess, head_loss

    result = bertlib.train(args, mesh, pe, model,
                           lambda af: lm_loss(model, apply_fn=af),
                           (ids0,), tag="gpt", batch_provider=bp,
                           make_f1b=make_f1b)
    if n_gen > 0:
        # every process enters the SPMD decode with the SAME prompt
        # (global row 0, not this host's local slice); only the print is
        # rank-gated
        prompt = jnp.asarray(sample[:, : min(8, args.seq_len - n_gen)])
        temp = getattr(args, "generate_temperature", 0.0)
        out = generate_cached(
            model, result["state"]["params"], prompt, n_gen,
            temperature=temp,
            top_k=getattr(args, "generate_top_k", 0),
            top_p=getattr(args, "generate_top_p", 0.0),
            rng=jax.random.PRNGKey(args.seed) if temp > 0 else None)
        if pe.process_id == 0:
            print(f"generated ids: {jax.device_get(out)[0].tolist()}")
    return result


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    run(args)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
