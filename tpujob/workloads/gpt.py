"""GPT-style decoder-only causal-LM pretraining workload.

The autoregressive model family on the same TPU-first machine as BERT:
the encoder blocks of ``tpujob.workloads.bert`` with a causal mask threaded
through whichever attention path the flags pick (dense XLA, Pallas flash,
ring or Ulysses sequence parallelism — all four implement ``causal=True``),
a GPT-2-style ``ln_f`` before the tied LM head, and next-token
cross-entropy.  The full parallelism matrix applies unchanged: DP,
FSDP/ZeRO-3, tensor, sequence, GPipe pipeline, and sparse-MoE expert
parallelism, all via the shared flag surface and ``bert.PARTITION_RULES``.

The reference ships no GPT workload (its examples are MNIST and a
send/recv smoke, SURVEY.md §2.3); this is model-family breadth beyond it,
sized GPT-2-medium by default.

Entrypoint:
    python -m tpujob.workloads.gpt --steps 100 --layers 24
"""
from __future__ import annotations

import argparse
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from tpujob.workloads import bert as bertlib
from tpujob.workloads import data as datalib
from tpujob.workloads import distributed as dist


def lm_loss(model, aux_coef: float = 0.01, z_coef: float = 1e-3,
            apply_fn: Optional[Callable] = None):
    """Next-token cross-entropy (shift-by-one), plus the MoE aux losses
    when the FFNs are sparse — same collection plumbing as bert.mlm_loss."""

    def loss_fn(params, batch):
        (ids,) = batch  # [b, s]
        if apply_fn is not None:
            logits, sown = apply_fn(params, ids), {}
        elif model.moe is not None:
            logits, sown = model.apply(params, ids, mutable=["moe_metrics"])
        else:
            logits, sown = model.apply(params, ids), {}
        logp = jax.nn.log_softmax(logits[:, :-1])
        tok_ll = jnp.take_along_axis(logp, ids[:, 1:, None], axis=-1)[..., 0]
        loss = -tok_ll.mean()
        if sown:
            loss = (loss
                    + aux_coef * bertlib._mean_sown(sown, "load_balance")
                    + z_coef * bertlib._mean_sown(sown, "router_z"))
        return loss

    return loss_fn


def build_parser() -> argparse.ArgumentParser:
    """The BERT flag surface with decoder defaults (GPT-2-medium shapes,
    GPT-2 vocab)."""
    p = bertlib.build_parser()
    p.description = "TPU-native GPT (decoder-only) causal-LM pretrain"
    p.set_defaults(vocab=50257, seq_len=1024)
    return p


def build_model(args, mesh):
    return bertlib.build_model(args, mesh, causal=True, final_ln=True)


make_mesh_for = bertlib.make_mesh_for


def run(args, mesh=None) -> Dict[str, Any]:
    pe = dist.initialize()
    if mesh is None:
        mesh = make_mesh_for(args, pe)
    model = build_model(args, mesh)
    lo, sz = dist.local_batch_slice(args.batch_size, pe)
    ids = datalib.synthetic_token_batch(args.batch_size, args.seq_len, args.vocab)
    return bertlib.train(args, mesh, pe, model,
                         lambda af: lm_loss(model, apply_fn=af),
                         (ids[lo : lo + sz],), tag="gpt")


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    run(args)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
