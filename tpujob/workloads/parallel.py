"""Parallelism strategies beyond data parallelism.

The reference implements exactly one strategy — multi-process DP via DDP in
the workload (SURVEY.md §2.5, ``examples/mnist/mnist.py:135-138``).  This
module carries the TPU-first extensions that make the framework usable at
slice scale:

- **Tensor parallelism**: rule-based parameter partition specs; XLA/GSPMD
  inserts the per-layer collectives from the annotations (no hand-written
  all-reduces).
- **Pipeline parallelism**: GPipe microbatch schedule over the ``pipeline``
  mesh axis (``pipeline``) — shard_map + ppermute ring shifts under one
  ``lax.scan``; one ``jax.grad`` through it is the pipeline backward.
- **Expert parallelism**: sparse MoE FFN (``moe_ffn``) with top-k routing,
  static capacity, and expert weights sharded over the ``expert`` axis;
  GSPMD derives the dispatch/combine all-to-alls.
- **Sequence/context parallelism**, two interchangeable implementations
  (the long-context story):

  * ring attention — K/V blocks rotate around the ICI ring via
    ``ppermute`` while each device keeps a flash-attention-style running
    softmax over its Q shard: O(S/n) memory per device, compute overlapped
    with neighbour exchange, composes with a tensor-parallel head split.
  * Ulysses (all-to-all) — one ``all_to_all`` re-shards sequence → heads,
    dense attention runs locally over the full sequence, a second
    ``all_to_all`` restores the layout: 2 collectives instead of n hops,
    best at moderate S with heads ≥ the axis size.

All collective layout follows the mesh built by
``tpujob.workloads.distributed.make_mesh`` (data slowest / tensor+sequence
on ICI neighbours).
"""
from __future__ import annotations

import math
import re
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpujob.workloads import distributed as dist
from tpujob.workloads.distributed import shard_map


# ---------------------------------------------------------------------------
# Rule-based tensor-parallel parameter partitioning
# ---------------------------------------------------------------------------


def partition_spec_tree(params: Any, rules: Sequence[Tuple[str, P]]) -> Any:
    """Map each param leaf to a PartitionSpec by first regex match on its
    '/'-joined path; unmatched leaves replicate (P()).

    This is the GSPMD idiom: annotate parameters once, let the compiler
    derive every collective — the TPU-native replacement for hand-placed
    NCCL calls.
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def spec_for(path) -> P:
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path
        )
        for pat, spec in rules:
            if re.search(pat, name):
                return spec
        return P()

    specs = [spec_for(path) for path, _ in flat]
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, specs)


def sanitize_spec(spec: P, mesh) -> P:
    """Drop mesh axes the rule names but this mesh doesn't carry (a TP rule
    on a pure-DP mesh degrades to replication, not an error)."""
    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in mesh.axis_names)
            return kept if kept else None
        return entry if entry in mesh.axis_names else None

    return P(*(keep(e) for e in spec))


def shard_params(params: Any, mesh, rules: Sequence[Tuple[str, P]]) -> Any:
    """device_put params with their rule-derived shardings."""
    specs = partition_spec_tree(params, rules)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, sanitize_spec(s, mesh))),
        params, specs,
    )


# ---------------------------------------------------------------------------
# Ring attention (sequence/context parallelism)
# ---------------------------------------------------------------------------


def _block_attention(q, k, v, bias, m_prev, l_prev, o_prev, scale):
    """One flash-style block update: softmax statistics (m, l) and output
    accumulator o folded over an incoming K/V block."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    # renormalize previous accumulator to the new max
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l_prev * alpha + p.sum(axis=-1)
    o_new = o_prev * alpha[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v)
    return m_new, l_new, o_new


def _sp_batch_axis(mesh, batch_size: int) -> Optional[Tuple[str, ...]]:
    """Mesh axes for the batch dim inside a manual (shard_map) region or
    sharding constraint: keep it split over the mesh's batch-parallel axes
    (`dist.batch_axes`), but skip when the static batch doesn't divide
    them — e.g. batch-1 traces during model.init."""
    axes = dist.batch_axes(mesh)
    if axes and batch_size % dist.batch_divisor(mesh, *axes) == 0:
        return axes
    return None


_UNROLL_MAX_HOPS = 16


def _ring_hops(n: int, body, carry):
    """Run ``body(i, carry)`` for the n ring hops.  Unrolled for small n:
    XLA then sees every hop (cost analysis counts real FLOPs, and each
    hop's ppermute can overlap the previous hop's compute instead of
    hitting a loop barrier); ``fori_loop`` beyond that bounds compile
    time."""
    if n <= _UNROLL_MAX_HOPS:
        for i in range(n):
            carry = body(i, carry)
        return carry
    return jax.lax.fori_loop(0, n, body, carry)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    axis: str = "sequence",
    head_axis: Optional[str] = None,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention over a sequence sharded on ``axis``.

    Inputs are [batch, seq, heads, head_dim] with seq sharded over the mesh
    ``axis``; output has the same sharding.  Each of the n ring steps
    computes attention of the local Q block against the K/V block currently
    resident, then rotates K/V one hop with ``ppermute`` (neighbour-only ICI
    traffic — this is why the sequence axis must sit on ICI, see
    ``distributed.AXIS_ORDER``).  Softmax is exact via running (m, l)
    statistics, so results match full attention to numerical precision.

    ``head_axis`` additionally splits the heads dim over a tensor-parallel
    mesh axis (ring-over-sequence composes with Megatron-style TP: each
    device holds its head shard of its sequence block).

    ``causal=True`` uses the zigzag block assignment when the sequence
    tiles into 2n chunks (see ``_zigzag_ring_attention``): causal work is
    then perfectly balanced across the ring and fully-masked future blocks
    are never computed — (2n+1)/4n of the non-causal FLOPs (56% at n=4)
    instead of paying every einsum and masking after.  Shapes that don't
    tile fall back to the contiguous layout, which still skips dead
    blocks' compute via ``lax.cond`` (runtime win, but the last device
    remains the n-hop critical path).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = mesh.shape[axis]
    if causal and n > 1 and q.shape[1] == k.shape[1] \
            and q.shape[1] % (2 * n) == 0:
        return _zigzag_ring_attention(q, k, v, mesh, axis, head_axis, scale)

    def local(qb, kb, vb):
        idx = jax.lax.axis_index(axis)
        b, sq, h, d = qb.shape
        m0 = jnp.full((b, h, sq), -jnp.inf, q.dtype)
        l0 = jnp.zeros((b, h, sq), q.dtype)
        o0 = jnp.zeros((b, h, sq, d), q.dtype)

        def step(i, carry):
            m, l, o, kc, vc = carry
            # kc/vc arrived from neighbour idx+1 at each hop, so after i
            # hops the resident block is (idx + i) % n
            src_block = (idx + i) % n

            def attend(mlo):
                m, l, o = mlo
                bias = None
                if causal:
                    sk = kc.shape[1]
                    q_pos = idx * sq + jnp.arange(sq)
                    k_pos = src_block * sk + jnp.arange(sk)
                    mask = q_pos[:, None] >= k_pos[None, :]
                    # finite mask value: a fully-masked block (all-future K)
                    # must not poison the running max (exp(-inf+inf)=nan)
                    bias = jnp.where(mask, 0.0, -1e30)[None, None]
                # GQA: the ring rotates the small KV-head tensors; heads
                # broadcast only here, at compute
                kr, vr = _gqa_repeat(qb, kc, vc)
                return _block_attention(qb, kr, vr, bias, m, l, o, scale)

            if causal:
                # a K/V block strictly in this Q shard's future contributes
                # nothing — skip its einsums entirely (the block must still
                # ride the ring for the devices behind us, but ~half the
                # hops do no compute; a fully-masked bias would pay them)
                m, l, o = jax.lax.cond(
                    src_block <= idx, attend, lambda mlo: mlo, (m, l, o))
            else:
                m, l, o = attend((m, l, o))
            # rotate K/V to the next device (receive from idx+1)
            perm = [(j, (j - 1) % n) for j in range(n)]
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return m, l, o, kc, vc

        m, l, o, _, _ = _ring_hops(n, step, (m0, l0, o0, kb, vb))
        out = o / l[..., None]
        return out.transpose(0, 2, 1, 3)  # [b, sq, h, d]

    spec = P(_sp_batch_axis(mesh, q.shape[0]), axis, head_axis, None)
    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def _zigzag_ring_attention(q, k, v, mesh, axis, head_axis, scale):
    """Causal ring attention with the zigzag (folded) block assignment.

    The global sequence is cut into 2n chunks; device j holds the PAIR
    (chunk j, chunk 2n-1-j) — one early, one late.  That folding balances
    causal work exactly: for every arriving K/V pair from device s != j,
    precisely two of the four (Q chunk x K chunk) combinations are live,
    and both are *fully* visible (no mask needed):

      - late Q (2n-1-j) x early K (s): always live, since 2n-1-j >= n > s;
      - the third live pair flips with the ring direction: early Q x early
        K when s < j, late Q x late K when s > j — selected with
        ``jnp.where`` on the chunk inputs and accumulator, so the compiled
        program has ONE einsum pair of static shape, not a branch.

    The local hop (s = j) runs the two triangular diagonals plus the
    always-live cross pair.  Total: 3 + 2(n-1) chunk-attentions versus
    4n for the non-skipping contiguous schedule — (2n+1)/4n of the
    FLOPs (56.25% at n=4, -> 50% as n grows), *balanced*, so the wall
    clock drops with the FLOPs instead of bottlenecking on the last
    device the way contiguous dead-block skipping does.  This is the
    standard zigzag/striped causal ring layout (e.g. the zigzag variant
    of ring flash attention); the permutation in and out of zigzag order
    is two O(S·d) shuffles, negligible against the O(S²·d/n) attention.

    Inputs/outputs are in natural sequence order, sharded on ``axis``
    like :func:`ring_attention` — the zigzag layout is internal.
    """
    import numpy as np

    n = mesh.shape[axis]
    b, S, h, d = q.shape
    c = S // (2 * n)
    # device j's shard of the zigzag layout = chunks (j, 2n-1-j)
    perm = np.concatenate([
        np.r_[np.arange(j * c, (j + 1) * c),
              np.arange((2 * n - 1 - j) * c, (2 * n - j) * c)]
        for j in range(n)
    ])
    inv = np.argsort(perm)

    def local(qb, kb, vb):
        idx = jax.lax.axis_index(axis)
        qA, qB = qb[:, :c], qb[:, c:]  # early chunk j, late chunk 2n-1-j
        bl, _, hl, dl = qb.shape  # local sizes (batch/heads may be sharded)

        def acc0():
            m = jnp.full((bl, hl, c), -jnp.inf, q.dtype)
            l = jnp.zeros((bl, hl, c), q.dtype)
            o = jnp.zeros((bl, hl, c, dl), q.dtype)
            return m, l, o

        # triangular (within-chunk diagonal) bias; finite mask value as in
        # the contiguous path
        tri = jnp.where(
            jnp.arange(c)[:, None] >= jnp.arange(c)[None, :], 0.0, -1e30
        )[None, None]

        def attend(qc, kc, vc, bias, m, l, o):
            # GQA: heads broadcast at compute only — the ring rotates the
            # small KV-head tensors
            kr, vr = _gqa_repeat(qc, kc, vc)
            return _block_attention(qc, kr, vr, bias, m, l, o, scale)

        # hop 0: the resident pair is our own (s = j)
        kA, kB = kb[:, :c], kb[:, c:]
        vA, vB = vb[:, :c], vb[:, c:]
        mA, lA, oA = attend(qA, kA, vA, tri, *acc0())
        mB, lB, oB = attend(qB, kA, vA, None, *acc0())
        mB, lB, oB = attend(qB, kB, vB, tri, mB, lB, oB)

        ring_perm = [(j, (j - 1) % n) for j in range(n)]

        def hop(i, carry):
            mA, lA, oA, mB, lB, oB, kc, vc = carry
            kc = jax.lax.ppermute(kc, axis, ring_perm)
            vc = jax.lax.ppermute(vc, axis, ring_perm)
            s = (idx + i) % n  # owner of the newly resident pair
            kA, kB = kc[:, :c], kc[:, c:]
            vA, vB = vc[:, :c], vc[:, c:]
            # late Q x early K: live and fully visible for every s != idx
            mB, lB, oB = attend(qB, kA, vA, None, mB, lB, oB)
            # the direction-dependent pair: early x early when the sender
            # is behind us, late x late when ahead — same shapes either
            # way, so select inputs and accumulator instead of branching
            early = s < idx
            q2 = jnp.where(early, qA, qB)
            k2 = jnp.where(early, kA, kB)
            v2 = jnp.where(early, vA, vB)
            m2p = jnp.where(early, mA, mB)
            l2p = jnp.where(early, lA, lB)
            o2p = jnp.where(early, oA, oB)
            m2, l2, o2 = attend(q2, k2, v2, None, m2p, l2p, o2p)
            mA = jnp.where(early, m2, mA)
            lA = jnp.where(early, l2, lA)
            oA = jnp.where(early, o2, oA)
            mB = jnp.where(early, mB, m2)
            lB = jnp.where(early, lB, l2)
            oB = jnp.where(early, oB, o2)
            return mA, lA, oA, mB, lB, oB, kc, vc

        mA, lA, oA, mB, lB, oB, _, _ = _ring_hops(
            n - 1, lambda i, cr: hop(i + 1, cr),
            (mA, lA, oA, mB, lB, oB, kb, vb))
        out = jnp.concatenate(
            [oA / lA[..., None], oB / lB[..., None]], axis=2)
        return out.transpose(0, 2, 1, 3)  # [b, 2c, h, d]

    spec = P(_sp_batch_axis(mesh, q.shape[0]), axis, head_axis, None)
    qz, kz, vz = (x[:, perm] for x in (q, k, v))
    out = shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(qz, kz, vz)
    return out[:, inv]


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    axis: str = "sequence",
    causal: bool = False,
    scale: Optional[float] = None,
    attention_impl=None,
) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style).

    Same contract as :func:`ring_attention` — inputs [batch, seq, heads,
    head_dim] with seq sharded over ``axis``, exact results — but a
    different collective shape: ONE ``all_to_all`` re-shards sequence →
    heads (each device then holds the FULL sequence for ``heads/n`` heads),
    dense attention runs locally with ordinary global-position masking, and
    a second ``all_to_all`` restores the sequence sharding.

    Trade-off vs the ring: 2 collectives per attention instead of n
    ``ppermute`` hops (lower latency at moderate S), but the full sequence
    must fit per device and heads must divide by the axis size — when S/n
    is the memory bound or heads are scarce, the ring wins.  Does not
    compose with a tensor-parallel head split (the head dim is already
    consumed by the all_to_all); use the ring for SP×TP.

    ``attention_impl``: the device-local attention over the re-sharded
    [b, S, h/n, d] tensors — defaults to dense ``full_attention``; pass
    ``flash.flash_attention`` to keep the local softmax in VMEM.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = mesh.shape[axis]
    heads, hkv = q.shape[2], k.shape[2]
    if heads % n != 0:
        raise ValueError(
            f"ulysses attention needs heads ({heads}) divisible by the "
            f"{axis!r} axis size ({n}); use ring_attention otherwise"
        )
    if hkv != heads and hkv % n != 0:
        raise ValueError(
            f"ulysses attention needs KV heads ({hkv}) divisible by the "
            f"{axis!r} axis size ({n}); use ring_attention otherwise"
        )

    def local(qb, kb, vb):
        if kb.shape[2] == qb.shape[2]:
            # one collective for all three tensors: stack to
            # [3, b, s/n, h, d] and all_to_all seq -> heads (axes shifted
            # +1 by the stack dim)
            qkv = jax.lax.all_to_all(
                jnp.stack((qb, kb, vb)), axis, split_axis=3, concat_axis=2,
                tiled=True,
            )  # [3, b, s, h/n, d]
            q_, k_, v_ = qkv[0], qkv[1], qkv[2]
        else:
            # GQA: K/V carry fewer heads than Q so all three can't stack,
            # but K and V still share one collective; both move the SMALL
            # tensors (the heads broadcast happens locally, in the impl)
            q_ = jax.lax.all_to_all(
                qb, axis, split_axis=2, concat_axis=1, tiled=True)
            kv = jax.lax.all_to_all(
                jnp.stack((kb, vb)), axis, split_axis=3, concat_axis=2,
                tiled=True)
            k_, v_ = kv[0], kv[1]
        impl = attention_impl or full_attention
        out = impl(q_, k_, v_, causal=causal, scale=scale)
        # [b, s, h/n, d] -> [b, s/n, h, d]
        return jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    spec = P(_sp_batch_axis(mesh, q.shape[0]), axis, None, None)
    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Pipeline parallelism (GPipe microbatch schedule)
# ---------------------------------------------------------------------------


def pipeline(
    stage_fn,
    stacked_params: Any,
    x: jax.Array,
    mesh,
    *,
    axis: str = "pipeline",
    num_microbatches: Optional[int] = None,
    skip_idle: bool = True,
):
    """Run a layer stack split over the ``axis`` mesh dim as a GPipe
    pipeline.

    ``stacked_params``: pytree whose leaves carry a leading layer dim L
    (L % axis size == 0); stage s holds layers [s*L/n, (s+1)*L/n).
    ``stage_fn(local_params, x_mb)`` applies one stage's layers to one
    microbatch (local_params = the stage's slice of the stack).
    ``x``: [batch, ...] activations; batch is cut into ``num_microbatches``
    (default = the axis size) and streamed through the stages.

    Schedule: M + n - 1 ticks of a ``lax.scan``.  At tick t stage 0 ingests
    microbatch t, every stage applies its layers to the activation it
    holds, and ``ppermute`` shifts results one hop down the pipeline ring
    (neighbour-only ICI traffic, like the ring-attention rotation).  The
    last stage accumulates finished microbatches and a masked ``psum``
    broadcasts the result so the output is replicated over ``axis`` like
    the input.  The (n-1)/(M+n-1) bubble is the classic GPipe cost — raise
    ``num_microbatches`` to amortize it.  Gradients flow through the scan
    and the ppermute transpose, so one ``jax.grad`` of a pipelined loss is
    the full pipelined backward, compiled by XLA.

    ``skip_idle`` (default True): warmup/drain ticks skip the stage
    compute under ``lax.cond`` instead of processing zeros — in an SPMD
    lockstep schedule the bubble is *executed* FLOPs, not just idleness,
    and this eliminates that work (exact parity; the tick count and the
    ppermute barriers are unchanged).  For the 1F1B memory bound
    (activation stash ∝ stages instead of ∝ microbatches — which cannot
    be expressed through ``jax.grad`` of a forward-only schedule), see
    ``pipeline_schedule.pipeline_1f1b``: the fused interleaved
    forward+backward behind ``--pipeline-schedule 1f1b``.

    **Composes with tensor parallelism** (the Megatron TP x PP layout):
    only the pipeline and batch axes are manual in the shard_map; any
    other mesh axis (``tensor``) stays *auto*, so the per-layer kernels
    keep their rule-derived Megatron shardings inside the stages and
    GSPMD inserts the TP collectives there exactly as it does outside a
    pipeline.

    The reference has nothing like this (SURVEY.md §2.5: DP only); this is
    the ``pp`` in the framework's dp×tp×sp×ep×pp story.
    """
    n = mesh.shape[axis]
    m = num_microbatches or n
    leaves = jax.tree_util.tree_leaves(stacked_params)
    if not leaves:
        raise ValueError("stacked_params is empty")
    n_layers = leaves[0].shape[0]
    if n_layers % n != 0:
        raise ValueError(
            f"layer stack of {n_layers} does not divide over "
            f"{axis!r} axis size {n}")
    batch_axis = _sp_batch_axis(mesh, x.shape[0])
    b_local = x.shape[0] // (
        dist.batch_divisor(mesh, *batch_axis) if batch_axis else 1)
    if b_local % m != 0:
        raise ValueError(
            f"per-device batch {b_local} does not divide into "
            f"{m} microbatches")

    # jax < 0.5: the legacy shard_map partitioner mispartitions a stack
    # built inside the surrounding jit against a P(axis) in_spec (stages
    # read the wrong layer slices and the output conversion double-
    # reduces over the batch axis) — feed the stack replicated instead
    # and slice each stage's layers inside the manual region; the slice
    # transpose psums the layer-grad contributions back together
    legacy = not dist.shard_map_supports_partial_manual()

    def local(p_local, xb):
        idx = jax.lax.axis_index(axis)
        if legacy:
            per = n_layers // n
            p_local = jax.tree.map(
                lambda a: jax.lax.dynamic_slice_in_dim(
                    a, idx * per, per, 0), p_local)
        mb = xb.shape[0] // m
        x_mb = xb.reshape((m, mb) + xb.shape[1:])
        out0 = jnp.zeros_like(x_mb)
        buf0 = jnp.zeros_like(x_mb[0])

        def tick(carry, t):
            buf, out = carry
            # stage 0 ingests the next microbatch; later stages work on
            # what arrived from their neighbour last tick.
            feed = x_mb[jnp.clip(t, 0, m - 1)]
            cur = jnp.where(idx == 0, feed, buf)
            if skip_idle:
                # stage `idx` holds a real microbatch only for ticks
                # [idx, idx + m): warmup/drain ticks skip the stage
                # compute entirely (lax.cond) instead of chewing zeros —
                # the idle-stage half of the pipeline-bubble cost is
                # wasted FLOPs in an SPMD lockstep schedule, and this
                # removes them (the schedule length, and hence the
                # (n-1)/(m+n-1) wall-clock bubble, is unchanged: a tick
                # still waits on the ppermute barrier)
                active = jnp.logical_and(idx <= t, t < idx + m)
                y = jax.lax.cond(
                    active, lambda c: stage_fn(p_local, c), lambda c: c, cur)
            else:
                # numerically inert on idle stages (LN/softmax of 0 is
                # finite) and never written to `out`
                y = stage_fn(p_local, cur)
            widx = jnp.clip(t - (n - 1), 0, m - 1)
            write = jnp.logical_and(idx == n - 1, t >= n - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                out, y.astype(out.dtype), widx, 0)
            out = jnp.where(write, upd, out)
            nxt = jax.lax.ppermute(y, axis, [(i, i + 1) for i in range(n - 1)])
            return (nxt, out), None

        (_, out), _ = jax.lax.scan(tick, (buf0, out0), jnp.arange(m + n - 1))
        # only the last stage holds real outputs; broadcast to all stages
        out = jnp.where(idx == n - 1, out, jnp.zeros_like(out))
        out = jax.lax.psum(out, axis)
        return out.reshape(xb.shape)

    xspec = P(batch_axis, *([None] * (x.ndim - 1)))
    # manual over the pipeline + batch axes only; everything else (the
    # tensor axis) stays auto so Megatron parameter annotations drive the
    # TP collectives inside each stage
    manual = {axis} | set(dist.batch_axes(mesh))
    return shard_map(
        local, mesh=mesh,
        in_specs=(P() if legacy else P(axis), xspec), out_specs=xspec,
        check_vma=False, axis_names=frozenset(manual),
    )(stacked_params, x)


# ---------------------------------------------------------------------------
# Expert parallelism (sparse Mixture-of-Experts FFN)
# ---------------------------------------------------------------------------


def moe_capacity(tokens_per_group: int, num_experts: int, k: int,
                 capacity_factor: float) -> int:
    """Static per-expert buffer length for one routing group.

    ``capacity_factor`` 1.0 fits a perfectly balanced router; the usual
    1.25-2.0 slack absorbs imbalance before tokens drop.  Static because
    every shape under jit must be — overflowing assignments are dropped
    (their combine weight is zero, so the residual stream carries the
    token through unchanged, the standard Switch/GShard behavior).
    """
    return max(1, math.ceil(tokens_per_group * k * capacity_factor / num_experts))


def moe_ffn(
    x: jax.Array,
    router_kernel: jax.Array,
    wi: jax.Array,
    wo: jax.Array,
    mesh=None,
    *,
    axis: str = "expert",
    k: int = 2,
    capacity_factor: float = 1.25,
    activation=jax.nn.gelu,
    valid: Optional[jax.Array] = None,
):
    """Sparse MoE feed-forward with top-k routing and expert parallelism.

    ``x``: [batch, seq, d_model]; ``router_kernel``: [d_model, E];
    ``wi``: [E, d_model, d_ff]; ``wo``: [E, d_ff, d_model].
    Returns ``(y, metrics)`` with y shaped like x and ``metrics`` carrying
    ``load_balance`` (Switch-style aux loss, 1.0 when perfectly balanced)
    and ``router_z`` (logit-magnitude regularizer).

    ``valid``: optional [batch, seq] 0/1 mask — positions with 0 are not
    routed at all: they consume no expert-capacity slots and their output
    is 0 (the residual stream carries them).  This is what makes
    autoregressive decode over a fixed buffer causal: without it, padding
    positions past the cursor compete for capacity in k-major priority
    order and can evict an earlier position's assignment once an expert
    overflows (observed empirically — suffix edits changed prefix outputs
    at low capacity).  The aux metrics are computed over valid positions
    only.

    TPU-first dispatch (the GShard/GSPMD idiom): routing builds dense
    dispatch/combine masks per batch-row group and two einsums move tokens
    to [E, capacity] expert buffers — no gather/scatter, so XLA tiles
    everything onto the MXU.  Expert weights arrive sharded ``P(axis, ...)``
    (see ``bert.PARTITION_RULES``); a sharding constraint on the dispatched
    activations pins the expert dim to the same axis, and GSPMD derives the
    all-to-alls between the data and expert layouts.  The reference has no
    MoE at all (SURVEY.md §2.5 — DP only); this is the ``ep`` in the
    framework's dp×tp×sp×ep story.
    """
    b, s, d = x.shape
    num_experts = wi.shape[0]
    if not 1 <= k <= num_experts:
        raise ValueError(
            f"top-k k={k} must be in [1, num_experts={num_experts}]")
    cap = moe_capacity(s, num_experts, k, capacity_factor)

    # Router in fp32: tiny matmul, and exp/softmax on bf16 logits is where
    # MoE training classically diverges.
    logits = jnp.einsum(
        "bsd,de->bse", x.astype(jnp.float32), router_kernel.astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)
    gate, expert_idx = jax.lax.top_k(probs, k)  # [b, s, k]
    gate = gate / jnp.maximum(gate.sum(-1, keepdims=True), 1e-9)

    # Position of each assignment in its expert's buffer, first choices
    # before second choices (priority order = k-major), per group (=row).
    oh = jax.nn.one_hot(expert_idx, num_experts, dtype=jnp.float32)  # [b,s,k,E]
    if valid is not None:
        # unrouted positions: zero the whole assignment before the slot
        # cumsum so they never occupy (or steal) a capacity slot
        oh = oh * valid.astype(jnp.float32)[..., None, None]
    oh_prio = oh.transpose(0, 2, 1, 3).reshape(b, k * s, num_experts)
    pos = jnp.cumsum(oh_prio, axis=1) - 1.0  # [b, k*s, E]
    pos = jnp.sum(pos * oh_prio, axis=-1)  # [b, k*s] slot of each assignment
    keep = (pos < cap).astype(jnp.float32)
    slot = jax.nn.one_hot(pos.astype(jnp.int32), cap,
                          dtype=jnp.float32) * keep[..., None]
    # [b, k*s, E, cap] -> [b, s, k, E, cap]
    dispatch = (oh_prio[..., None] * slot[..., None, :]).reshape(
        b, k, s, num_experts, cap).transpose(0, 2, 1, 3, 4)
    combine = (dispatch * gate[..., None, None]).sum(2).astype(x.dtype)
    dispatch = dispatch.sum(2).astype(x.dtype)  # [b, s, E, cap]

    expert_in = jnp.einsum("bsec,bsd->becd", dispatch, x)
    if mesh is not None and axis in mesh.axis_names:
        # batch entry dropped when b doesn't divide the data axis (e.g. the
        # batch-1 trace during model.init)
        ep_spec = sanitize_spec(P(_sp_batch_axis(mesh, b), axis, None, None), mesh)
        constrain = lambda a: jax.lax.with_sharding_constraint(
            a, NamedSharding(mesh, ep_spec))
        expert_in = constrain(expert_in)
    else:
        constrain = lambda a: a
    h = activation(jnp.einsum("becd,edf->becf", expert_in, wi))
    out = constrain(jnp.einsum("becf,efd->becd", h, wo))
    y = jnp.einsum("bsec,becd->bsd", combine, out)

    # Switch aux loss: E * sum_e(density_e * mean_prob_e); 1.0 when
    # balanced.  density_e is the fraction of tokens whose TOP-1 choice is
    # expert e (the Switch/GShard formulation — counting all k assignments
    # minimizes at the same uniform point but carries slightly different
    # gradients).  router_z keeps logits small (numerical safety valve).
    if valid is not None:
        vmask = valid.astype(jnp.float32)
        denom = jnp.maximum(vmask.sum(), 1.0)
        density = oh[:, :, 0, :].sum(axis=(0, 1)) / denom
        mean_prob = (probs * vmask[..., None]).sum(axis=(0, 1)) / denom
    else:
        density = oh[:, :, 0, :].sum(axis=(0, 1)) / (b * s)
        mean_prob = probs.mean(axis=(0, 1))
    load_balance = num_experts * jnp.sum(density * mean_prob)
    router_z = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    return y, {"load_balance": load_balance, "router_z": router_z}


def _gqa_repeat(q, k, v):
    """Broadcast K/V heads up to the query heads (grouped-query
    attention): every attention path accepts k/v with h_kv | h heads and
    repeats at the latest possible point — after collectives, so ring
    rotation and Ulysses all-to-alls move the SMALL tensors."""
    rep = q.shape[2] // k.shape[2]
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"query heads {q.shape[2]} must be a multiple of KV heads "
            f"{k.shape[2]}")
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def full_attention(q, k, v, *, causal: bool = False,
                   scale: Optional[float] = None, window: int = 0):
    """Reference dense attention (same layout) for parity tests and the
    unsharded path.  Accepts grouped-query K/V (fewer heads) and a causal
    sliding ``window`` (keys more than window-1 positions behind the
    query are masked)."""
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("window (sliding-window attention) requires "
                         "causal=True")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    k, v = _gqa_repeat(q, k, v)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        delta = jnp.arange(sq)[:, None] - jnp.arange(sk)[None, :]
        mask = delta >= 0
        if window:
            mask = jnp.logical_and(mask, delta < window)
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
