"""Self-contained byte-level BPE tokenizer for the LM workloads.

The reference's example pipeline feeds a real dataset through a per-rank
DataLoader (``/root/reference/examples/mnist/mnist.py:117-132``); its LM
equivalent needs a tokenizer — which normally means a network download.
This module is the zero-egress answer: a trainable byte-level BPE
(GPT-2-style: 256 base byte tokens + learned merges), pure
numpy-vectorized so training and encoding stay fast without native code
or downloads.

Design points:

- **Training** samples at most ``max_bytes`` from the corpus (pair
  statistics converge long before that), counts adjacent pairs with one
  vectorized ``np.unique`` per merge, and records merges in rank order.
- **Encoding** applies merges rank-by-rank with one vectorized masked
  merge per rank — O(corpus) numpy work per merge, so multi-hundred-MB
  corpora encode in seconds, then cache to a memory-mapped sidecar (see
  ``data.token_dataset``).
- **Format**: one JSON file, ``{"version", "vocab_size", "merges"}`` —
  merge i creates token id 256+i from the pair ``merges[i]``.  Stable
  across runs: training is deterministic (ties broken by pair id).

CLI:
    python -m tpujob.workloads.tokenizer train --input corpus.txt \
        --vocab-size 512 --out tok.json
    python -m tpujob.workloads.tokenizer inspect --tokenizer tok.json \
        [--sample "text"]
"""
from __future__ import annotations

import argparse
import json
import os
from typing import List, Sequence, Tuple

import numpy as np


def _apply_merge(toks: np.ndarray, a: int, b: int, new_id: int) -> np.ndarray:
    """One vectorized BPE merge pass: every non-overlapping (a, b) pair
    becomes ``new_id``.  Overlaps (only possible when a == b) resolve
    left-to-right, matching the sequential algorithm."""
    if len(toks) < 2:
        return toks
    match = (toks[:-1] == a) & (toks[1:] == b)
    if not match.any():
        return toks
    idx = np.flatnonzero(match)
    if a == b:
        # runs like [a a a] match at consecutive positions but only every
        # other one may merge; greedy left-to-right over the (sparse)
        # match list
        keep = []
        last = -2
        for i in idx:
            if i == last + 1:
                continue  # overlaps the previously kept merge
            keep.append(i)
            last = i
        idx = np.asarray(keep, dtype=idx.dtype)
    out = toks.copy()
    out[idx] = new_id
    mask = np.ones(len(toks), dtype=bool)
    mask[idx + 1] = False
    return out[mask]


class BPETokenizer:
    """Byte-level BPE: ids [0, 256) are literal bytes; each merge adds one
    id.  ``vocab_size`` counts base bytes + merges."""

    def __init__(self, merges: Sequence[Tuple[int, int]]):
        self.merges: List[Tuple[int, int]] = [tuple(m) for m in merges]
        self.vocab_size = 256 + len(self.merges)

    # -- training ---------------------------------------------------------

    @classmethod
    def train(cls, data: bytes, vocab_size: int,
              max_bytes: int = 2_000_000) -> "BPETokenizer":
        if vocab_size < 256:
            raise ValueError(
                f"vocab_size must be >= 256 (the byte alphabet), got "
                f"{vocab_size}")
        toks = np.frombuffer(data[:max_bytes], dtype=np.uint8).astype(np.int64)
        merges: List[Tuple[int, int]] = []
        while 256 + len(merges) < vocab_size and len(toks) >= 2:
            # adjacent-pair histogram in one pass; ties break on the
            # smaller packed pair id, so training is deterministic
            width = 256 + len(merges)
            codes = toks[:-1] * width + toks[1:]
            uniq, counts = np.unique(codes, return_counts=True)
            best = uniq[np.argmax(counts)]
            if counts.max() < 2:
                break  # nothing left worth merging
            a, b = int(best // width), int(best % width)
            new_id = 256 + len(merges)
            merges.append((a, b))
            toks = _apply_merge(toks, a, b, new_id)
        return cls(merges)

    # -- encode / decode --------------------------------------------------

    def encode(self, data: bytes) -> np.ndarray:
        """bytes -> int32 token ids (vectorized, one pass per merge)."""
        toks = np.frombuffer(data, dtype=np.uint8).astype(np.int32)
        for rank, (a, b) in enumerate(self.merges):
            toks = _apply_merge(toks, a, b, 256 + rank)
        return toks.astype(np.int32)

    def decode(self, ids: Sequence[int]) -> bytes:
        """token ids -> bytes (unknown ids raise)."""
        table: List[bytes] = [bytes([i]) for i in range(256)]
        for a, b in self.merges:
            table.append(table[a] + table[b])
        out = bytearray()
        for i in ids:
            i = int(i)
            if not 0 <= i < len(table):
                raise ValueError(
                    f"token id {i} outside vocab of {len(table)}")
            out += table[i]
        return bytes(out)

    # -- persistence ------------------------------------------------------

    def save(self, path: str) -> None:
        # write-tmp-then-replace: load_or_train's exists-then-load flow
        # must never see a half-written file (multi-host shared fs)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"version": 1, "vocab_size": self.vocab_size,
                       "merges": [list(m) for m in self.merges]}, f)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            blob = json.load(f)
        if blob.get("version") != 1:
            raise ValueError(
                f"{path!r}: unsupported tokenizer format "
                f"{blob.get('version')!r}")
        tok = cls([tuple(m) for m in blob["merges"]])
        if tok.vocab_size != blob["vocab_size"]:
            raise ValueError(
                f"{path!r}: vocab_size {blob['vocab_size']} does not match "
                f"256 + {len(tok.merges)} merges")
        return tok


def load_or_train(path: str, corpus_path: str, vocab_size: int,
                  max_bytes: int = 2_000_000,
                  verbose: bool = True) -> BPETokenizer:
    """The workload flow for ``--tokenizer bpe:PATH``: load PATH if it
    exists, otherwise train on the corpus and save to PATH (deterministic,
    so every host of a multi-process job trains the identical tokenizer;
    the save is atomic, so a concurrent host never loads a torn file)."""
    if os.path.exists(path):
        return BPETokenizer.load(path)
    with open(corpus_path, "rb") as f:
        data = f.read(max_bytes)  # train() samples this much anyway
    tok = BPETokenizer.train(data, vocab_size, max_bytes)
    tok.save(path)
    if verbose:
        print(f"trained BPE tokenizer ({tok.vocab_size} ids) on "
              f"{corpus_path} -> {path}")
    return tok


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Byte-level BPE tokenizer (train / inspect)")
    sub = p.add_subparsers(dest="cmd", required=True)
    t = sub.add_parser("train", help="train on a corpus file")
    t.add_argument("--input", required=True)
    t.add_argument("--vocab-size", type=int, default=512)
    t.add_argument("--max-bytes", type=int, default=2_000_000,
                   help="sample at most this many corpus bytes for the "
                        "pair statistics")
    t.add_argument("--out", required=True)
    i = sub.add_parser("inspect", help="print tokenizer stats")
    i.add_argument("--tokenizer", required=True)
    i.add_argument("--sample", default=None,
                   help="round-trip this text and print the ids")
    args = p.parse_args(argv)
    if args.cmd == "train":
        with open(args.input, "rb") as f:
            data = f.read()
        tok = BPETokenizer.train(data, args.vocab_size, args.max_bytes)
        tok.save(args.out)
        print(f"trained {tok.vocab_size}-id tokenizer "
              f"({len(tok.merges)} merges) -> {args.out}")
    else:
        tok = BPETokenizer.load(args.tokenizer)
        print(f"{args.tokenizer}: vocab_size={tok.vocab_size} "
              f"merges={len(tok.merges)}")
        if args.sample is not None:
            ids = tok.encode(args.sample.encode())
            print(f"ids: {ids.tolist()}")
            print(f"round-trip: {tok.decode(ids).decode(errors='replace')!r}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
