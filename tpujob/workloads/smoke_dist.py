"""Send/recv smoke workload, TPU-native.

Mirror of ``examples/smoke-dist/dist_sendrecv.py``: the reference's master
sends a random 2x2 tensor to each worker, the worker squares it elementwise
and sends it back, and the master logs each result (dist_sendrecv.py:15-39)
— validating the injected rendezvous env end-to-end (SURVEY.md §4).

On TPU the idiom is SPMD, not point-to-point: process 0's tensor is
broadcast with ``psum`` (a masked sum — the collective send), every device
squares its copy, and an ``all_gather`` returns all results to every device
(the collective recv).  Device 0 verifies each participant's result equals
input², exercising ICI/DCN collectives exactly where the reference
exercises the gloo TCP ring.

Usage (as the TPUJob container entrypoint):
    python -m tpujob.workloads.smoke_dist
"""
from __future__ import annotations

import logging
import os
import sys

from tpujob.workloads import distributed as dist

log = logging.getLogger("tpujob.smoke_dist")


def run(mesh=None) -> bool:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpujob.workloads.distributed import shard_map

    if mesh is None:
        mesh = dist.make_mesh({"data": -1})
    n = mesh.size

    @jax.jit
    def smoke(seed):
        def body(seed_):
            idx = jax.lax.axis_index("data")
            # master draws the input; the psum is the "send" to every worker
            key = jax.random.PRNGKey(seed_[0])
            mine = jax.random.normal(key, (2, 2))
            inp = jax.lax.psum(jnp.where(idx == 0, mine, 0.0), "data")
            # worker computes elementwise square (dist_sendrecv.py:31-33)
            result = inp * inp
            # the all_gather is the "recv" of every worker's result
            all_results = jax.lax.all_gather(result, "data")
            expected = inp * inp
            ok = jnp.all(jnp.abs(all_results - expected[None]) < 1e-6)
            return ok, inp, all_results

        return shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=(P(), P(), P()),
            check_vma=False,
        )(seed)

    ok, inp, results = smoke(jnp.zeros((n,), jnp.int32))
    for i in range(n):
        log.info("Result from participant %d : %s", i, results[i])
    return bool(ok)


def main() -> int:
    logging.basicConfig(level=logging.INFO, format="%(levelname)s:%(name)s:%(message)s")
    # log the injected env exactly as the reference does (dist_sendrecv.py:44-54)
    for var in (
        "TPUJOB_COORDINATOR_ADDRESS", "TPUJOB_NUM_PROCESSES", "TPUJOB_PROCESS_ID",
        "TPU_WORKER_ID", "TPU_WORKER_HOSTNAMES", "TPU_TOPOLOGY",
        "MASTER_ADDR", "MASTER_PORT", "WORLD_SIZE", "RANK",
    ):
        log.info("%s: %s", var, os.environ.get(var, "{}"))
    pe = dist.initialize()
    import jax

    log.info("JAX version: %s, devices: %d, process %d/%d",
             jax.__version__, len(jax.devices()), pe.process_id, pe.num_processes)
    ok = run()
    log.info("smoke send/recv %s", "PASSED" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
