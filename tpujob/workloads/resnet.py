"""ResNet-50 workload (the multi-host north-star model, BASELINE.md:
"ResNet-50 ImageNet samples/sec/chip, multi-host v4-32").

The reference provides no ResNet code — its v4-32 config is a driver target
(BASELINE.json), the operator just schedules whatever image the user ships.
This is that image's workload: flax ResNet-50 v1.5 (stride-2 on the 3x3,
the variant every published benchmark uses), NHWC + bfloat16-friendly,
trained with the same SPMD DP machinery as MNIST.

Under jit the BatchNorm batch statistics are computed over the *global*
batch dimension (the array is one logical tensor; XLA inserts the
cross-device mean) — this is sync-BN for free, where torch DDP needs
SyncBatchNorm.

Entrypoint:
    python -m tpujob.workloads.resnet --steps 100 --batch-size 256
"""
from __future__ import annotations

import argparse
import time
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpujob.workloads import data as datalib
from tpujob.workloads import distributed as dist
from tpujob.workloads import train_lib

STAGE_SIZES = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}


class Bottleneck(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype,
        )
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)  # zero-init last BN gamma
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides),
                            name="downsample_conv")(residual)
            residual = norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    depth: int = 50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, name="conv_init")(x)
        x = nn.relu(norm(name="bn_init")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, blocks in enumerate(STAGE_SIZES[self.depth]):
            for block in range(blocks):
                x = Bottleneck(
                    filters=self.width * 2**stage,
                    strides=2 if block == 0 and stage > 0 else 1,
                    dtype=self.dtype,
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=logits.dtype)
    return -(onehot * jax.nn.log_softmax(logits)).sum(axis=-1).mean()


def make_model(args) -> ResNet:
    return ResNet(depth=args.depth, width=args.width,
                  dtype=jnp.bfloat16 if args.bf16 else jnp.float32)


def build_loss(model: ResNet):
    def loss_fn(params, batch_stats, batch):
        x, y = batch
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats}, x, train=True,
            mutable=["batch_stats"],
        )
        return cross_entropy(logits, y), mutated["batch_stats"]

    return loss_fn


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU-native ResNet-50")
    p.add_argument("--depth", type=int, default=50, choices=sorted(STAGE_SIZES))
    p.add_argument("--width", type=int, default=64,
                   help="base filter count (64 = standard ResNet)")
    p.add_argument("--batch-size", type=int, default=256,
                   help="global batch size")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--warmup-steps", type=int, default=2,
                   help="compile+warmup steps excluded from throughput")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--bf16", action="store_true", default=True)
    p.add_argument("--no-bf16", dest="bf16", action="store_false")
    p.add_argument("--log-interval", type=int, default=20)
    p.add_argument("--dir", default="logs")
    train_lib.add_profile_flags(p)
    return p


def run(args, mesh=None) -> Dict[str, Any]:
    pe = dist.initialize()
    if mesh is None:
        mesh = dist.make_mesh({"data": -1}, env=pe)
    writer = train_lib.SummaryWriter(args.dir, enabled=pe.process_id == 0)

    model = make_model(args)
    optimizer = train_lib.sgd(args.lr, args.momentum)
    rng = jax.random.PRNGKey(args.seed)
    sample = jnp.zeros((1, args.image_size, args.image_size, 3))
    variables = model.init(rng, sample, train=False)
    state = train_lib.init_state(
        variables["params"], optimizer, mesh, extra=variables["batch_stats"]
    )

    train_step = train_lib.make_train_step(
        build_loss(model), optimizer, mesh, has_extra=True
    )

    lo, sz = dist.local_batch_slice(args.batch_size, pe)
    x, y = datalib.synthetic_imagenet_batch(args.batch_size, args.image_size)
    batch = train_lib.put_batch((x[lo : lo + sz], y[lo : lo + sz]), mesh)

    # warmup (compile) then timed steps
    loss = None
    for _ in range(args.warmup_steps):
        state, loss = train_step(state, batch)
    if loss is not None:
        jax.block_until_ready(loss)
    profiler = train_lib.profiler_from_args(args, pe)
    t0 = time.perf_counter()
    try:
        for i in range(args.steps):
            profiler.step(i, block_on=loss)
            state, loss = train_step(state, batch)
            if i % args.log_interval == 0:
                writer.add_scalar("loss", float(loss), i)
        jax.block_until_ready(loss)
        # honest throughput under --profile-dir: exclude trace drain +
        # serialization time even when the window closed mid-loop
        wall = time.perf_counter() - t0 - profiler.overhead_s
    finally:
        profiler.close(block_on=loss)
    sps = args.steps * args.batch_size / wall
    writer.close()
    if pe.process_id == 0:
        print(f"resnet{args.depth}: {sps:.1f} samples/sec "
              f"({sps / max(1, len(jax.devices())):.1f}/device), loss={float(loss):.3f}")
    return {"samples_per_sec": sps, "wall_s": wall, "final_loss": float(loss),
            "state": state}


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    run(args)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
