"""Workload-side distributed bootstrap: the consumer of the injected env.

The reference workload contract is ``dist.init_process_group(backend)``
reading ``MASTER_ADDR``/``MASTER_PORT``/``WORLD_SIZE``/``RANK``
(``examples/mnist/mnist.py:114-116``, env injected by
``pkg/controller.v1/pytorch/pod.go:234-281``).  The TPU-native contract is
``jax.distributed.initialize(coordinator_address, num_processes, process_id)``
reading the ``TPUJOB_*`` variables injected by
``tpujob/controller/tpu_env.py`` — after which every host holds one JAX
process whose local devices are its slice chips, and collectives ride
ICI within a slice / DCN across slices via XLA.

Mesh construction lives here too: workloads declare logical axes
(data/fsdp/tensor/sequence/expert) and this module lays physical devices out
so that the fastest-varying axes land on ICI neighbours and only the data
axis crosses slice (DCN) boundaries.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Callable, Dict, Optional, Tuple

log = logging.getLogger("tpujob.workloads")


@dataclasses.dataclass(frozen=True)
class ProcessEnv:
    """The injected cluster spec, parsed (tpu_env.cluster_env is the writer)."""

    coordinator_address: Optional[str]
    num_processes: int
    process_id: int
    num_slices: int
    slice_id: int
    devices_per_host: Optional[int]
    global_devices: Optional[int]
    accelerator: Optional[str]
    topology: Optional[str]

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def _geti(env: Dict[str, str], key: str, default: Optional[int] = None) -> Optional[int]:
    v = env.get(key)
    if v is None or v == "":
        return default
    return int(v)


def process_env(env: Optional[Dict[str, str]] = None) -> ProcessEnv:
    """Parse TPUJOB_* (preferred) or MASTER_ADDR-compat env into a ProcessEnv.

    Mirrors the reference workload's env reads (dist_sendrecv.py:44-54) with
    the TPU vocabulary first and the torch.distributed spelling as fallback,
    so the same container image runs under either injection style.
    """
    e = dict(os.environ) if env is None else env
    coord = e.get("TPUJOB_COORDINATOR_ADDRESS")
    if coord is None and e.get("MASTER_ADDR"):
        coord = f"{e['MASTER_ADDR']}:{e.get('MASTER_PORT', '23456')}"
    num = _geti(e, "TPUJOB_NUM_PROCESSES") or _geti(e, "WORLD_SIZE", 1) or 1
    pid = _geti(e, "TPUJOB_PROCESS_ID")
    if pid is None:
        pid = _geti(e, "RANK", 0) or 0
    return ProcessEnv(
        coordinator_address=coord,
        num_processes=num,
        process_id=pid,
        num_slices=_geti(e, "TPUJOB_NUM_SLICES", 1) or 1,
        slice_id=_geti(e, "TPUJOB_SLICE_ID", 0) or 0,
        devices_per_host=_geti(e, "TPUJOB_DEVICES_PER_HOST"),
        global_devices=_geti(e, "TPUJOB_GLOBAL_DEVICES"),
        accelerator=e.get("TPU_ACCELERATOR_TYPE"),
        topology=e.get("TPU_TOPOLOGY"),
    )


def initialize(env: Optional[ProcessEnv] = None) -> ProcessEnv:
    """The TPU-native ``init_process_group``.

    Single-process jobs (the reference's WORLD_SIZE==1 fast path,
    mnist.py:68-70 ``should_distribute``) skip coordinator setup entirely;
    multi-process jobs dial the coordinator service the controller exposed
    via headless DNS.  Idempotent: safe to call when already initialized.
    """
    pe = env or process_env()
    if not pe.is_distributed:
        log.info("single-process job; skipping jax.distributed.initialize")
        return pe
    import jax

    # Idempotency probe must not touch the backend: jax.process_count()
    # would initialize XLA and make the subsequent initialize() raise.
    try:
        from jax._src.distributed import global_state

        if global_state.client is not None:  # already initialized
            return pe
    except (ImportError, AttributeError):
        # private API: a jax upgrade may move the module or rename the
        # attribute — fall through to the normal initialize path either way
        pass
    log.info(
        "jax.distributed.initialize coordinator=%s num_processes=%d process_id=%d",
        pe.coordinator_address, pe.num_processes, pe.process_id,
    )
    jax.distributed.initialize(
        coordinator_address=pe.coordinator_address,
        num_processes=pe.num_processes,
        process_id=pe.process_id,
    )
    return pe


# ---------------------------------------------------------------------------
# Mesh construction
# ---------------------------------------------------------------------------

# Canonical logical axis order, slowest-varying (DCN-friendly) first.  Data
# parallelism tolerates the slowest links, so it gets the outermost placement;
# tensor/sequence axes communicate per-layer and must stay on ICI neighbours.
AXIS_ORDER: Tuple[str, ...] = ("data", "fsdp", "expert", "pipeline", "sequence", "tensor")


def _factor_axes(
    n_devices: int, axes: Dict[str, int]
) -> Dict[str, int]:
    """Resolve at most one -1 axis to soak up the remaining devices."""
    sizes = dict(axes)
    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one axis may be -1, got {wild}")
    fixed = 1
    for k, v in sizes.items():
        if v != -1:
            if v <= 0:
                raise ValueError(f"axis {k!r} must be positive or -1, got {v}")
            fixed *= v
    if wild:
        if n_devices % fixed != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes product {fixed}"
            )
        sizes[wild[0]] = n_devices // fixed
        fixed = n_devices
    if fixed != n_devices:
        raise ValueError(
            f"mesh axes {sizes} multiply to {fixed}, but {n_devices} devices present"
        )
    return sizes


def hybrid_mesh_shapes(
    names: Tuple[str, ...], shape: Tuple[int, ...], num_slices: int
) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """Pure ICI/DCN split for a multislice mesh: the slowest axis absorbs
    the slice boundary so only it crosses the DCN (SURVEY.md §2.5 ICI/DCN
    accounting).  Returns ``(ici_shape, dcn_shape)`` with elementwise
    ``ici * dcn == shape``; raises if the slowest axis cannot be split.
    """
    if num_slices <= 1:
        raise ValueError(f"hybrid mesh needs num_slices > 1, got {num_slices}")
    first = shape[0]
    if first % num_slices != 0:
        raise ValueError(
            f"multislice mesh: slowest axis {names[0]!r}={first} must be "
            f"divisible by num_slices={num_slices}, or per-layer "
            f"collectives would cross the DCN"
        )
    dcn = [1] * len(shape)
    dcn[0] = num_slices
    ici = list(shape)
    ici[0] = first // num_slices
    return tuple(ici), tuple(dcn)


def devices_have_slice_index(devices) -> bool:
    """True when the device objects carry multislice placement info (real
    TPU devices in a multislice deployment).  Virtual CPU devices don't —
    make_mesh then falls back to a plain mesh so shardings still compile in
    tests/dryruns."""
    return bool(devices) and hasattr(devices[0], "slice_index")


def make_mesh(
    axes: Optional[Dict[str, int]] = None,
    *,
    env: Optional[ProcessEnv] = None,
    devices=None,
):
    """Build a ``jax.sharding.Mesh`` over all global devices.

    ``axes`` maps logical axis name -> size, with one ``-1`` wildcard
    (default ``{"data": -1}`` — pure DP, the reference's only strategy,
    SURVEY.md §2.5).  Axes are laid out in AXIS_ORDER so "data" varies
    slowest; for multislice jobs the data axis is additionally split across
    slices with ``create_hybrid_device_mesh`` so only DP gradient
    all-reduces cross the DCN.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    sizes = _factor_axes(n, dict(axes or {"data": -1}))
    names = [a for a in AXIS_ORDER if a in sizes]
    extra = [a for a in sizes if a not in AXIS_ORDER]
    names += sorted(extra)
    shape = [sizes[a] for a in names]

    pe = env or process_env()
    if pe.num_slices > 1 and n % pe.num_slices == 0 and devices_have_slice_index(devices):
        ici, dcn = hybrid_mesh_shapes(tuple(names), tuple(shape), pe.num_slices)
        dmesh = mesh_utils.create_hybrid_device_mesh(
            list(ici), list(dcn), devices=devices, allow_split_physical_axes=True
        )
        return Mesh(dmesh, axis_names=tuple(names))
    dmesh = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(dmesh, axis_names=tuple(names))


def batch_axes(mesh) -> Tuple[str, ...]:
    """The batch-parallel axes this mesh carries — the one home for the
    'data and fsdp split the batch' rule (FSDP is data parallelism with
    sharded state; every model axis replicates the batch)."""
    return tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)


def batch_sharding(mesh, *axes: str):
    """NamedSharding for a batch: dim 0 split over the given mesh axes
    (default: every batch-parallel axis present on the mesh)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if not axes:
        axes = batch_axes(mesh)
        if not axes:
            raise ValueError(
                f"mesh axes {mesh.axis_names} contain no batch axis "
                "('data'/'fsdp'); pass batch_axes explicitly"
            )
    return NamedSharding(mesh, P(axes if len(axes) > 1 else axes[0]))


def batch_divisor(mesh, *axes: str) -> int:
    """Global batch dim 0 must be a multiple of this (the number of batch
    shards the mesh produces)."""
    if not axes:
        axes = batch_axes(mesh)
    out = 1
    for a in axes:
        out *= mesh.shape[a]
    return out


def constrain_activation(x, mesh):
    """Pin a [batch, seq, ...] activation to the canonical layout: batch
    split over the batch axes, seq over the sequence axis when present,
    feature dims replicated.

    This is the GSPMD activation-annotation idiom: without it, sharding
    propagation can pull a kernel's layout backward into the activations —
    e.g. on an fsdp x tensor mesh the QKV/MLP kernels' fsdp-sharded
    contracting dim makes the partitioner shard inter-layer activations
    hidden-over-fsdp while other uses want them batch-sharded, and the
    conflict resolves by "involuntary full rematerialization" (replicate,
    then repartition) every step.  Annotating the block boundaries keeps
    activations batch-sharded and the weights all-gather instead (the
    ZeRO-3 pattern).

    No-op when ``mesh`` is None, when the mesh has no batch axis, or when
    the leading dim doesn't divide the batch shards (e.g. the batch-1
    trace during ``model.init`` or a small decode batch).
    """
    if mesh is None:
        return x
    axes = batch_axes(mesh)
    if not axes or x.shape[0] % batch_divisor(mesh, *axes) != 0:
        return x
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    seq = None
    if x.ndim >= 3 and "sequence" in mesh.axis_names \
            and x.shape[1] % mesh.shape["sequence"] == 0:
        seq = "sequence"
    spec = P(axes if len(axes) > 1 else axes[0], seq,
             *([None] * (x.ndim - 2)))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def local_batch_slice(global_batch: int, env: Optional[ProcessEnv] = None) -> Tuple[int, int]:
    """(start, size) of this host's rows of a globally-sharded batch — the
    per-rank DistributedSampler split, TPU-style (each host feeds only its
    local devices)."""
    pe = env or process_env()
    if global_batch % pe.num_processes != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by {pe.num_processes} processes"
        )
    per = global_batch // pe.num_processes
    return pe.process_id * per, per


# ---------------------------------------------------------------------------
# Elastic resize: the workload half of the drain/join protocol
# ---------------------------------------------------------------------------
#
# The controller publishes the LIVE world size on job annotations (pod env
# is bootstrap-only — see tpujob.api.constants ANNOTATION_*): a pending
# shrink publishes `target-world-size` first so every process can hit a
# checkpoint barrier, and the committed world arrives as `world-size` +
# a bumped `resize-generation` once the join/drain staging completed.  A
# real pod reads the annotations through a downward-API file mount (the
# `metadata.annotations` fieldRef format: one `key="escaped value"` line
# per annotation); the in-process harness reads the job object directly.

# Env var naming the downward-API file carrying the job annotations (the
# conventional mount point for the elastic signal); absent = not elastic.
RESIZE_SIGNAL_ENV = "TPUJOB_RESIZE_SIGNAL_FILE"

# resize plan actions (plan_resize return values)
PLAN_CHECKPOINT = "checkpoint"  # drain pending: checkpoint NOW and ack
PLAN_LEAVE = "leave"  # this process is beyond the target: checkpoint, then
# idle until the controller deletes the pod
PLAN_REJOIN = "rejoin"  # world republished: re-initialize at the new size
# and restore from the latest checkpoint


@dataclasses.dataclass(frozen=True)
class WorldSignal:
    """The published elastic state, parsed from the job annotations."""

    world_size: int  # committed world (every live replica rendezvouses here)
    target_world_size: Optional[int]  # pending drain target (None = steady)
    resize_generation: int  # bumps on every completed resize

    @property
    def drain_pending(self) -> bool:
        return (self.target_world_size is not None
                and self.target_world_size != self.world_size)


def parse_world_signal(annotations: Dict[str, str],
                       default_world: int) -> WorldSignal:
    """Build a :class:`WorldSignal` from job annotations.  ``default_world``
    is the bootstrap world (this process's injected TPUJOB_NUM_PROCESSES) —
    the committed world before the controller ever published one."""
    from tpujob.api import constants as c

    def _geti_ann(key):
        v = annotations.get(key)
        if v is None or v == "":
            return None
        try:
            return int(v)
        except ValueError:
            return None

    world = _geti_ann(c.ANNOTATION_WORLD_SIZE)
    return WorldSignal(
        world_size=world if world is not None else default_world,
        target_world_size=_geti_ann(c.ANNOTATION_TARGET_WORLD_SIZE),
        resize_generation=_geti_ann(c.ANNOTATION_RESIZE_GENERATION) or 0,
    )


def parse_downward_annotations(text: str) -> Dict[str, str]:
    """Parse the downward-API `metadata.annotations` file format: one
    ``key="escaped value"`` line per annotation (Go strconv.Quote)."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or "=" not in line:
            continue
        key, _, raw = line.partition("=")
        raw = raw.strip()
        if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
            raw = raw[1:-1].encode().decode("unicode_escape")
        out[key.strip()] = raw
    return out


def read_world_signal(path: Optional[str] = None,
                      default_world: Optional[int] = None) -> Optional[WorldSignal]:
    """Read the elastic signal from the downward-API annotations file named
    by ``path`` (default: $TPUJOB_RESIZE_SIGNAL_FILE).  Returns None when
    the job is not elastic (no file configured/present) — callers then run
    the classic fixed-world loop."""
    path = path if path is not None else os.environ.get(RESIZE_SIGNAL_ENV)
    if not path:
        return None
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None
    if default_world is None:
        default_world = process_env().num_processes
    return parse_world_signal(parse_downward_annotations(text), default_world)


def plan_resize(pe: ProcessEnv, signal: Optional[WorldSignal]) -> Optional[str]:
    """What this process must do about the published elastic state:

    - ``None`` — steady state: keep training.
    - :data:`PLAN_CHECKPOINT` — a drain is pending: checkpoint now, ack the
      target, and PAUSE stepping until the world republishes (collectives
      with the leaving hosts would hang anyway; pausing after the barrier
      is what makes a clean resize lossless).
    - :data:`PLAN_LEAVE` — this process is beyond the target: checkpoint
      (it may hold the most recent state), then idle until deleted.
    - :data:`PLAN_REJOIN` — the world republished at a size this runtime is
      not initialized for: re-rendezvous (:func:`reinitialize`) and restore
      from the latest checkpoint.
    """
    if signal is None:
        return None
    if signal.drain_pending:
        if pe.process_id >= signal.target_world_size:
            return PLAN_LEAVE
        return PLAN_CHECKPOINT
    if signal.world_size != pe.num_processes:
        if pe.process_id >= signal.world_size:
            # beyond the committed world with no drain pending: either a
            # JOINER born into the new (larger) world the controller has
            # not republished yet — it must WAIT (its own readiness gates
            # that republish), never "rejoin" a world it has no seat in —
            # or a drained process awaiting deletion
            return None
        return PLAN_REJOIN
    return None


def shutdown() -> None:
    """Tear down the distributed runtime (tolerant: a never-initialized or
    already-shut-down runtime is a no-op) — the first half of an elastic
    re-rendezvous."""
    try:
        import jax

        jax.distributed.shutdown()
    except (ImportError, RuntimeError, ValueError):
        pass


def reinitialize(pe: ProcessEnv, num_processes: int,
                 process_id: Optional[int] = None) -> ProcessEnv:
    """Re-rendezvous at a new world size (the elastic resize commit on the
    workload side): shut the old runtime down, then ``initialize`` with the
    new ``num_processes`` — the coordinator re-``initialize`` the staged
    resize protocol promises.  Process ids are stable under the drain/join
    protocol (scale-down drains the HIGHEST indices; scale-up appends), so
    the default keeps this process's id.

    Device arrays do not survive the teardown: restore the train state from
    the latest checkpoint after this returns
    (``Checkpointer.restore_latest``) — for a shrink that is a cheap
    restore, not a cold start."""
    new = dataclasses.replace(
        pe,
        num_processes=num_processes,
        process_id=process_id if process_id is not None else pe.process_id,
    )
    if new.process_id >= new.num_processes:
        # guard BEFORE the teardown: a drained process that reaches here by
        # mistake must keep its healthy runtime (and its state) intact
        # while it waits for the controller to delete its pod
        raise ValueError(
            f"process {new.process_id} is beyond the new world "
            f"{new.num_processes}: a drained process must exit, not rejoin")
    shutdown()
    return initialize(new)


# ---------------------------------------------------------------------------
# Progress heartbeats: the workload -> controller telemetry channel
# ---------------------------------------------------------------------------
#
# The reverse direction of the world-size channel above: the coordinator
# process publishes a compact `tpujob.dev/progress` record (step, smoothed
# samples/sec, last checkpoint step, resize epoch — tpujob.api.progress) on
# its OWN pod annotation, rate-limited and merge-patched so it composes with
# every other annotation writer and never amplifies the API write path.  The
# controller ingests it from its informer cache into the tpujob_job_* metric
# families and the Stalled-job watchdog.

# Pod self-identity env (downward-API fieldRef convention): names the pod
# whose annotation the reporter patches.  Absent = not running under the
# operator; the reporter then stays disabled.
POD_NAME_ENV = "TPUJOB_POD_NAME"
POD_NAMESPACE_ENV = "TPUJOB_POD_NAMESPACE"


class ProgressReporter:
    """Rate-limited publisher of the progress heartbeat.

    ``publish(value)`` ships one annotation value (a merge patch of this
    pod's ``tpujob.dev/progress`` key) and may raise on transport failure —
    failures are swallowed with a rate-limited warning, because telemetry
    must never take training down.  ``interval_s`` bounds the publish rate:
    a 10 ms step loop heartbeats every few seconds, not every step.
    """

    def __init__(self, publish: Optional[Callable[[str], None]],
                 interval_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.publish = publish
        self.interval_s = interval_s
        self._clock = clock
        self._last_pub: Optional[float] = None
        self._last_warn: Optional[float] = None
        self.published = 0  # successful publishes (test/debug visibility)

    @property
    def enabled(self) -> bool:
        return self.publish is not None

    def report(self, step: int, samples_per_sec: Optional[float] = None,
               checkpoint_step: Optional[int] = None,
               resize_generation: int = 0, force: bool = False) -> bool:
        """Publish one heartbeat unless rate-limited; True when it shipped.
        ``force`` bypasses the interval (checkpoint saves, loop exit)."""
        if self.publish is None:
            return False
        now = self._clock()
        if (not force and self._last_pub is not None
                and now - self._last_pub < self.interval_s):
            return False
        from tpujob.api.progress import format_progress

        value = format_progress(
            step, samples_per_sec=samples_per_sec,
            checkpoint_step=checkpoint_step,
            resize_generation=resize_generation,
            published_at=time.time(),
        )
        # stamp BEFORE the attempt: a failing transport must not turn every
        # step into a publish attempt (the rate limit covers failures too)
        self._last_pub = now
        try:
            self.publish(value)
        except Exception as e:  # noqa: TPL005 - telemetry is best-effort;
            # a dead transport must not kill training (warned, rate-limited)
            if self._last_warn is None or now - self._last_warn >= 60.0:
                self._last_warn = now
                log.warning("progress heartbeat publish failed: %s", e)
            return False
        self.published += 1
        return True


def pod_progress_patch(value: str) -> Dict[str, Dict[str, Dict[str, str]]]:
    """The merge-patch body publishing one heartbeat on a pod: patching only
    this one annotation key composes with concurrent metadata writers (the
    controller's world-size publications, adoption owner-refs)."""
    from tpujob.api import constants as c

    return {"metadata": {"annotations": {c.ANNOTATION_PROGRESS: value}}}


def progress_publisher_from_env(
    env: Optional[Dict[str, str]] = None,
) -> Optional[Callable[[str], None]]:
    """Build a publish callable for the conventional in-cluster setup: the
    pod patches its own annotation through the cluster apiserver, using the
    downward-API-injected pod identity (TPUJOB_POD_NAME / _NAMESPACE).
    Returns None — reporter disabled — when the identity or a cluster
    config is absent (local runs, dryruns, tests)."""
    e = dict(os.environ) if env is None else env
    pod = e.get(POD_NAME_ENV)
    if not pod:
        return None
    namespace = e.get(POD_NAMESPACE_ENV) or "default"
    try:
        from tpujob.kube.kubetransport import KubeApiTransport, KubeConfig

        transport = KubeApiTransport(KubeConfig.load())
    except Exception as e_cfg:  # noqa: TPL005 - no cluster config is the
        # normal local-run case, not an error worth crashing a workload over
        log.info("progress heartbeats disabled (no cluster config): %s", e_cfg)
        return None

    def publish(value: str) -> None:
        transport.patch("pods", namespace, pod, pod_progress_patch(value))

    return publish


def shard_map_supports_partial_manual() -> bool:
    """Whether this jax can leave some mesh axes *auto* inside a shard_map
    region (``axis_names``/``auto``).  Releases without the top-level
    ``jax.shard_map`` export (< 0.5) accept the kwarg but their SPMD
    partitioner crashes on the resulting program (PartitionId /
    IsManualSubgroup check failures), so callers must fall back or skip."""
    try:
        from jax import shard_map as _  # noqa: F401
        return True
    except ImportError:
        return False


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
              axis_names=None):
    """Version-compat ``shard_map``: the modern ``jax.shard_map`` surface
    (``check_vma``, ``axis_names`` = the *manual* axes) translated for older
    releases where it lives under ``jax.experimental`` and speaks
    ``check_rep`` / ``auto`` (= the complement: axes left automatic)."""
    try:
        from jax import shard_map as _native
    except ImportError:
        from jax.experimental.shard_map import shard_map as _legacy

        kwargs = {}
        if axis_names is not None:
            kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_rep=check_vma, **kwargs)
    kwargs = {} if axis_names is None else {"axis_names": axis_names}
    return _native(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check_vma, **kwargs)
