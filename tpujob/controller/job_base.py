"""Base job-controller kernel.

The equivalent of the vendored kubeflow/common JobController
(``vendor/github.com/kubeflow/tf-operator/pkg/common/jobcontroller/``):
workqueue + expectations wiring, pod/service event plumbing with
controller-ref resolution, claim/adopt/orphan of pods and services, and the
name/label/expectation-key conventions shared by reconciler and tests.
"""
from __future__ import annotations

import contextlib
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpujob.analysis import lockgraph
from tpujob.api import constants as c
from tpujob.api.types import TPUJob
from tpujob.kube.client import (
    RESOURCE_NODES,
    RESOURCE_PODS,
    RESOURCE_SERVICES,
    RESOURCE_TPUJOBS,
    ClientSet,
)
from tpujob.kube.control import (
    EventRecorder,
    PodControl,
    ServiceControl,
    gen_labels,
)
from tpujob.kube.errors import NotFoundError
from tpujob.kube.informers import (
    INDEX_JOB_NAME,
    INDEX_OWNER_UID,
    InformerFactory,
    SharedInformer,
)
from tpujob.kube.objects import Pod, Service
from tpujob.obs.recorder import CONTROLLER_TIMELINE_KEY, FlightRecorder
from tpujob.obs.trace import TRACER, KeyedTokenBucket
from tpujob.runtime import ExpectationsCache, WorkQueue
from tpujob.server import metrics

log = logging.getLogger("tpujob.controller")


class _DedupWarner:
    """Rate-limits duplicate warnings keyed by (object, reason).

    A stuck out-of-range pod re-warned on every sync would flood the log at
    high resync rates; one line per interval carries the same information.
    """

    def __init__(self, interval: float = 300.0, max_entries: int = 4096):
        self._interval = interval
        self._max = max_entries
        self._lock = lockgraph.new_lock("dedup-warner")
        self._last: Dict[Tuple, float] = {}  # guarded by self._lock

    def warning(self, logger: logging.Logger, key: Tuple, msg: str, *args) -> None:
        now = time.monotonic()
        with self._lock:
            last = self._last.get(key)
            if last is not None and now - last < self._interval:
                return
            if len(self._last) >= self._max:
                self._last = {
                    k: t for k, t in self._last.items() if now - t < self._interval
                }
                if len(self._last) >= self._max:
                    # bounded memory beats perfect dedup under key churn
                    self._last.clear()
            self._last[key] = now
        logger.warning(msg, *args)


_slice_warner = _DedupWarner()


@dataclass
class ControllerConfig:
    """Operator knobs (reference ServerOption, options.go:27-84)."""

    threadiness: int = 1
    resync_period: float = 12 * 3600.0
    enable_gang_scheduling: bool = False
    gang_scheduler_name: str = c.DEFAULT_GANG_SCHEDULER_NAME
    init_container_image: str = "alpine:3.10"
    expectations_ttl: float = 300.0
    backoff_base_delay: float = 0.005
    backoff_max_delay: float = 1200.0
    # decaying delay between a counted ExitCode restart and the replacement
    # pod's creation: 0 on the first failure (a transient blip restarts
    # promptly), then restart_backoff_seconds * 2^(n-2) capped at the max —
    # a crash-looping container churns pods at this pace instead of at full
    # controller speed until backoffLimit.  <= 0 disables (instant
    # recreate, the pre-backoff behavior).
    restart_backoff_seconds: float = 1.0
    restart_backoff_max_seconds: float = 300.0
    # elastic resize drain barrier: how long a scale-down waits for the
    # workload's checkpoint ack (the tpujob.dev/checkpoint-ack annotation
    # naming the target world size) before deleting the drained replicas
    # anyway.  Bounded: a wedged workload cannot block a shrink forever —
    # the invariant is "no progress lost past the LAST checkpoint", which
    # holds either way.  <= 0 skips the barrier (delete immediately).
    resize_drain_grace_s: float = 15.0
    namespace: Optional[str] = None  # None = all namespaces
    # flight-recorder/tracing subsystem (tpujob/obs): per-sync span trees,
    # per-job lifecycle timelines, /debug/* endpoints.  Tracing is process-
    # wide (the transports reach the tracer without plumbing), so the last
    # controller constructed wins — one controller per process in practice.
    enable_tracing: bool = True
    # a sync slower than this dumps its full span tree through the
    # structured logger (rate-limited per job); <= 0 disables the dump
    slow_sync_threshold_s: float = 5.0
    flight_recorder_size: int = 256  # timeline entries retained per job
    # --- API write-path knobs (status persistence proportional to change) ---
    # skip the status write when the recomputed status is semantically
    # identical to the informer-cached one (volatile timestamp refreshes do
    # not count); counted as status_writes_total{result="suppressed"}
    suppress_noop_status: bool = True
    # ship status writes as a JSON-merge-patch of only the changed fields
    # instead of a full-object PUT (False restores the PUT path, e.g. for a
    # transport without the verb or as a bench control)
    status_patch: bool = True
    # per-job-key event coalescing: a pod/service/job watch event schedules
    # the sync this many seconds out, and every further event on the same
    # key inside the window rides that one sync instead of enqueueing its
    # own — a 256-pod slice coming up triggers a handful of syncs, not 256.
    # <= 0 disables (every event enqueues immediately, the pre-PR behavior).
    settle_window_s: float = 0.02
    # --- API read-path knobs (LIST/watch cost proportional to change) ---
    # LIST chunk size for informer initial syncs and relists: continue-token
    # paging keeps transient memory O(page) at six-figure object counts and
    # makes mid-LIST faults recoverable per page.  <= 0 restores one unpaged
    # LIST (the pre-overhaul read path; also the bench control).
    informer_page_size: int = 500
    # request watch BOOKMARK events so a quiet informer's resume point
    # tracks the server head and a reconnect resumes instead of relisting
    # the world after history compaction.  Only transports advertising
    # supports_bookmarks honor it; False is the bench control.
    watch_bookmarks: bool = True
    # cold-start barrier budget: how long run() waits for every informer's
    # initial LIST.  The 10s default fits test clusters; a six-figure
    # object count (bench_controller --objects) needs minutes, not seconds.
    cache_sync_timeout_s: float = 10.0
    # --- workload telemetry plane (progress heartbeats + stall watchdog) ---
    # ingest tpujob.dev/progress pod-annotation heartbeats from the informer
    # cache into per-job progress state + the tpujob_job_* metric families.
    # False disables the whole plane (the bench_controller --watchdog
    # control); jobs that never publish a heartbeat cost nothing either way.
    enable_telemetry: bool = True
    # progress watchdog: flip the job's Stalled condition when its reported
    # step has not advanced for this long (monotonic clock; gaps during
    # resize/restart/replica-churn windows are exempt and re-arm the
    # deadline).  <= 0 disables the watchdog (heartbeat metrics still flow).
    stall_timeout_s: float = 600.0
    # what a detected stall does beyond the condition + event: "event" =
    # observability only; "restart" = delete the stuck heartbeat-publishing
    # replica once per stall episode (the normal reconcile recreates it)
    stall_policy: str = "event"
    # watchdog re-check cadence (requeued like ActiveDeadline); <= 0 derives
    # stall_timeout_s / 4 clamped to [0.05s, 60s]
    stall_check_interval_s: float = 0.0
    # --- goodput accounting plane (the per-job phase ledger) ---
    # attribute every second of each job's life to a phase (queued /
    # scheduling / initializing / training / checkpointing / stalled /
    # resizing / migrating / preempted / restarting) and export the
    # tpujob_job_goodput_* / tpujob_job_badput_* families + the
    # GoodputView the gang scheduler's victim choice consumes.  False
    # disables the whole plane (the bench_controller --goodput control);
    # the scheduler then falls back to raw steps-past-checkpoint.
    enable_goodput: bool = True
    # --- multi-cluster federation (the meta-controller above clusters) ---
    # which cluster THIS controller's member belongs to.  Non-empty
    # activates the reconciler's federation gate: a job whose durable
    # tpujob.dev/cluster annotation names ANOTHER cluster is held dark —
    # pods evicted without failure strikes, telemetry exempt — because the
    # named cluster is the exactly-one owner and running it here would
    # duplicate the gang.  "" (default) = not federated; the gate is inert
    # and single-cluster behavior is unchanged.
    cluster_name: str = ""
    extra: Dict[str, Any] = field(default_factory=dict)

    def stall_check_interval(self) -> float:
        """The effective telemetry tick: the watchdog's re-check cadence,
        or — with the watchdog disabled — the metrics-refresh cadence that
        keeps the age gauges moving after a publisher dies (the
        "heartbeat metrics still flow" contract)."""
        if self.stall_check_interval_s > 0:
            return self.stall_check_interval_s
        if self.stall_timeout_s > 0:
            return min(60.0, max(0.05, self.stall_timeout_s / 4.0))
        return 60.0


def expectation_key(job_key: str, rtype: str, kind: str) -> str:
    """jobcontroller/util.go:46-51: job/replicatype/{pods,services}."""
    return f"{job_key}/{rtype.lower()}/{kind}"


class _InstrumentedQueue:
    """WorkQueue proxy stamping when each key became due, so dequeue can
    observe true queue latency (add→get for immediate adds, due→get for
    delayed ones — client-go's workqueue_queue_duration_seconds role), plus
    per-key event coalescing for the storm path.

    The EARLIEST due stamp wins while a key is queued: an immediate add
    makes a delayed key actionable now, and a later duplicate must not
    overwrite the first enqueue's stamp — either way queue_latency would be
    misstated for the coalesced batch.  The stamp is popped at dequeue.
    Everything else delegates to the wrapped queue (which may be the native
    C++ one).
    """

    def __init__(self, inner):
        self._inner = inner
        self._due: Dict[str, float] = {}  # guarded by self._lock
        # keys with a coalescing add_after in flight (scheduled, not yet
        # dequeued): further event adds for them are absorbed
        self._coalescing: set = set()  # guarded by self._lock
        self._lock = lockgraph.new_lock("instrumented-queue")

    def _stamp(self, key: str, delay: float) -> None:
        due = time.monotonic() + delay
        with self._lock:
            cur = self._due.get(key)
            if cur is None or due < cur:
                self._due[key] = due

    def add(self, key: str) -> None:
        self._stamp(key, 0.0)
        self._inner.add(key)

    def add_after(self, key: str, delay: float) -> None:
        self._stamp(key, delay)
        self._inner.add_after(key, delay)

    def add_coalesced(self, key: str, window: float) -> None:
        """Event-driven add with burst dedup: the first event schedules the
        sync ``window`` seconds out; every further event on the same key
        before that sync is DEQUEUED rides along (counted, not enqueued).

        Dequeue—not promotion—bounds the absorb phase: an event arriving
        after the worker picked the key up must trigger a fresh sync, or a
        change landing mid-sync would go unseen until resync (the inner
        queue's dirty-while-processing handling then collapses it into one
        follow-up sync, exactly like client-go).
        """
        if window <= 0:
            self.add(key)
            return
        with self._lock:
            if key in self._coalescing:
                absorbed = True
            else:
                absorbed = False
                self._coalescing.add(key)
        if absorbed:
            metrics.syncs_coalesced.inc()
            return
        self._stamp(key, window)
        self._inner.add_after(key, window)

    def add_rate_limited(self, key: str) -> None:
        # no stamp: the inner queue computes the backoff delay internally,
        # so the proxy cannot know when the key becomes due.  The dequeue
        # path treats a missing stamp as "became due just now" (wait=0) —
        # under-counting a requeued item's post-backoff wait beats folding
        # the whole failure backoff (up to workqueue_max_backoff_s) into
        # queue_latency, which would destroy it as a contention signal.
        # client-go excludes AddRateLimited delays the same way (its stamp
        # happens at the post-delay Add()).
        self._inner.add_rate_limited(key)

    def pop_due(self, key: str) -> Optional[float]:
        with self._lock:
            # the key is being dequeued: end its coalescing window so the
            # next event schedules a fresh sync
            self._coalescing.discard(key)
            return self._due.pop(key, None)

    def __len__(self) -> int:
        return len(self._inner)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class JobController:
    """Shared controller state and pod/service event plumbing."""

    def __init__(
        self,
        clients: ClientSet,
        factory: Optional[InformerFactory] = None,
        recorder: Optional[EventRecorder] = None,
        config: Optional[ControllerConfig] = None,
    ):
        self.clients = clients
        self.config = config or ControllerConfig()
        # --namespace scopes every informer's list/watch, the way the
        # reference scopes its informer factories (app/server.go:111-114)
        self.factory = factory or InformerFactory(
            clients.server, namespace=self.config.namespace,
            page_size=self.config.informer_page_size,
            bookmarks=self.config.watch_bookmarks,
        )
        self.recorder = recorder or EventRecorder(clients)
        self.pod_control = PodControl(clients, self.recorder)
        self.service_control = ServiceControl(clients, self.recorder)
        self.queue = _InstrumentedQueue(WorkQueue(
            base_delay=self.config.backoff_base_delay,
            max_delay=self.config.backoff_max_delay,
        ))
        self.expectations = ExpectationsCache(ttl=self.config.expectations_ttl)

        # flight recorder + tracing (tpujob/obs): per-sync span trees and
        # per-job lifecycle timelines, served on /debug/* by the monitoring
        # server.  The tracer is process-wide (transports reach it without
        # plumbing); recorded events feed the per-job timelines via the
        # recorder sink.
        TRACER.enabled = self.config.enable_tracing
        self.flight = FlightRecorder(ring_size=self.config.flight_recorder_size)
        if hasattr(self.recorder, "sinks"):
            self.recorder.sinks.append(self.flight.record_event)
        self._slow_dump_limiter = KeyedTokenBucket(
            capacity=3.0, refill_per_s=1 / 60.0)

        # cold-start bookkeeping: run() stamps the start, the first completed
        # sync closes the measurement (process start -> caches synced ->
        # first sync)
        self._run_started_mono: Optional[float] = None
        self._first_sync_recorded = False  # guarded by self._cold_start_lock
        self._cold_start_lock = lockgraph.new_lock("cold-start")

        # sharded control plane (PR 8): when a sharder (ShardCoordinator
        # surface: shard_of_uid / is_active / sync_shard_context) is set,
        # this controller is one fleet member — it enqueues and syncs only
        # the job shards its coordinator currently owns, and each sync runs
        # under the shard's fencing context.  None = the single-controller
        # world, zero behavior change.
        self.sharder = None
        # native gang scheduler (PR 11): when set, the reconciler's
        # admission gate holds a job's pods back until the scheduler
        # commits its all-or-nothing assignment annotation, and evicts
        # them (not failure strikes) when the assignment is revoked.
        # None = no admission queue, zero behavior change.
        self.scheduler = None
        self._inflight_lock = lockgraph.new_lock("shard-inflight")
        # keys currently mid-sync per shard: the drain barrier the handoff
        # protocol waits on before a shard lease may be released
        self._inflight_by_shard: Dict[int, set] = {}  # guarded by self._inflight_lock

        self.job_informer = self.factory.informer(RESOURCE_TPUJOBS)
        self.pod_informer = self.factory.informer(RESOURCE_PODS)
        self.service_informer = self.factory.informer(RESOURCE_SERVICES)
        # node inventory (fleet repair): every member watches every Node so
        # the scheduler rebuilds its capacity model from the live cache and
        # the reconciler gates pod creation on host health.  An empty store
        # costs one quiet watch; the scheduler synthesizes Nodes from
        # --sched-capacity at bootstrap when none exist.
        self.node_informer = self.factory.informer(RESOURCE_NODES)
        self.node_informer.on_delete(self._on_node_delete)

        self.pod_informer.on_add(self.add_pod)
        self.pod_informer.on_update(self.update_pod)
        self.pod_informer.on_delete(self.delete_pod)
        self.service_informer.on_add(self.add_service)
        self.service_informer.on_update(self.update_service)
        self.service_informer.on_delete(self.delete_service)

    # ------------------------------------------------------------------
    # enqueueing
    # ------------------------------------------------------------------

    @staticmethod
    def job_key_of(obj: Dict[str, Any]) -> str:
        meta = obj.get("metadata") or {}
        return f"{meta.get('namespace') or 'default'}/{meta.get('name')}"

    # ------------------------------------------------------------------
    # sharding (PR 8): ownership resolution, sync context, drain barrier
    # ------------------------------------------------------------------

    def set_sharder(self, sharder) -> None:
        """Attach the shard coordinator BEFORE run(): every enqueue and
        dequeue from then on is filtered to the shards it owns."""
        self.sharder = sharder

    def set_scheduler(self, scheduler) -> None:
        """Attach the gang scheduler BEFORE run(): from then on the
        admission gate holds every job's pods until its gang is admitted."""
        self.scheduler = scheduler

    def _on_node_delete(self, obj: Dict[str, Any]) -> None:
        """A Node object left the cluster: sweep its per-node damper and
        health-anchor ledgers from the scheduler (the LRU-map hygiene the
        PR-3 token buckets follow) so node churn cannot grow them."""
        if self.scheduler is None:
            return
        name = (obj.get("metadata") or {}).get("name")
        if name:
            self.scheduler.forget_node(name)

    def _shard_of_obj(self, obj: Optional[Dict[str, Any]]) -> Optional[int]:
        """The shard a job object lives in (consistent hash of its UID), or
        None when unsharded / the object carries no UID."""
        if self.sharder is None or obj is None:
            return None
        uid = (obj.get("metadata") or {}).get("uid") or ""
        return self.sharder.shard_of_uid(uid) if uid else None

    def _shard_of_key(self, key: str) -> Optional[int]:
        if self.sharder is None:
            return None
        ns, _, name = key.partition("/")
        return self._shard_of_obj(self.job_informer.store.get(ns or "default", name))

    def _owns_key(self, key: str) -> bool:
        """Does this member currently sync ``key``?  Unsharded = always.
        A key whose job is gone from the cache resolves to True everywhere:
        the sync is a cheap cache-miss no-op, and dropping it could strand
        a deletion cleanup."""
        if self.sharder is None:
            return True
        shard = self._shard_of_key(key)
        if shard is None:
            return True
        return self.sharder.is_active(shard)

    def _shard_call_context(self, shard: Optional[int]):
        """Bind the in-flight work to its shard so every mutating API call
        underneath carries the shard's fencing token (the PR-4 call-token
        pattern, per shard)."""
        if self.sharder is None:
            return contextlib.nullcontext()
        return self.sharder.sync_shard_context(shard)

    def _shard_inflight_add(self, shard: Optional[int], key: str) -> None:
        if shard is None:
            return
        with self._inflight_lock:
            self._inflight_by_shard.setdefault(shard, set()).add(key)

    def _shard_inflight_remove(self, shard: Optional[int], key: str) -> None:
        if shard is None:
            return
        with self._inflight_lock:
            keys = self._inflight_by_shard.get(shard)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    self._inflight_by_shard.pop(shard, None)

    def drain_shard(self, shard: int, timeout: float = 5.0) -> bool:
        """The handoff protocol's drain barrier: wait until no sync of the
        shard's jobs is in flight.  The coordinator marks the shard
        *draining* BEFORE calling this, so dequeues of its keys are being
        dropped and the wait is bounded by the one in-flight sync per key
        the workqueue allows.  Returns False on timeout (a wedged sync):
        the caller must then let the shard lease expire instead of
        releasing it."""
        deadline = time.monotonic() + timeout
        drained = True
        while True:
            with self._inflight_lock:
                busy = bool(self._inflight_by_shard.get(shard))
            if not busy:
                break
            if time.monotonic() >= deadline:
                drained = False
                break
            time.sleep(0.005)
        # either way the shard is leaving this member (graceful release, or
        # lease expiry after the timeout): per-shard state that must not be
        # exported by two members — the telemetry series — is dropped here,
        # behind the barrier, so no in-flight sync can resurrect it
        self.on_shard_drained(shard)
        return drained

    def on_shard_drained(self, shard: int) -> None:
        """Hook invoked after the drain barrier settled (successfully or
        not) for a shard leaving this member; subclasses drop per-shard
        derived state here."""

    def enqueue_shard(self, shard: int) -> int:
        """Acquisition replay: enqueue every cached job of a just-acquired
        shard.  Events for these jobs were filtered while another member
        owned the shard; the shared informer cache (every member watches
        everything) makes the replay complete without any API traffic."""
        if self.sharder is None:
            return 0
        n = 0
        for obj in self.job_informer.store.list():
            if self._shard_of_obj(obj) == shard:
                self.enqueue_job(self.job_key_of(obj))
                n += 1
        return n

    def on_shard_acquired(self, shard: int) -> None:
        """Hook the coordinator invokes right after a shard turned active
        (post-activation half of acquisition; the pre-activation half is
        the reconciler's ``prepare_shard``)."""
        n = self.enqueue_shard(shard)
        self.flight.record(
            CONTROLLER_TIMELINE_KEY, "shard",
            f"shard {shard} acquired: {n} cached job(s) enqueued",
            {"shard": shard, "jobs": n})

    def enqueue_job(self, key: str) -> None:
        if not self._owns_key(key):
            return  # another member's shard: its owner syncs it
        self.queue.add(key)

    def enqueue_job_event(self, key: str) -> None:
        """Enqueue driven by an object watch event (pod/service/job change):
        burst events on one job coalesce into a single sync behind a short
        settle window (``settle_window_s``), so a 256-pod slice coming up —
        or an event-storm replay — costs a handful of syncs, not one per
        event.  Direct workflow enqueues (job creation, resync, deadline
        requeues) stay immediate via :meth:`enqueue_job`."""
        if not self._owns_key(key):
            return  # informer event filtering by owned shards
        self.queue.add_coalesced(key, self.config.settle_window_s)

    # ------------------------------------------------------------------
    # pod/service event handlers (jobcontroller/pod.go:20-160)
    # ------------------------------------------------------------------

    def _owner_job_key(self, obj: Dict[str, Any]) -> Optional[str]:
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace") or "default"
        for ref in meta.get("ownerReferences") or []:
            if not ref.get("controller"):
                continue
            if ref.get("kind") != c.KIND:
                continue
            # UID-checked resolution (jobcontroller.go:283-299)
            cached = self.job_informer.store.get(ns, ref.get("name"))
            if cached is None:
                return None
            if (cached.get("metadata") or {}).get("uid") != ref.get("uid"):
                return None
            return f"{ns}/{ref.get('name')}"
        return None

    def _replica_type_of(self, obj: Dict[str, Any]) -> Optional[str]:
        labels = (obj.get("metadata") or {}).get("labels") or {}
        return labels.get(c.LABEL_REPLICA_TYPE)

    def add_pod(self, obj: Dict[str, Any]) -> None:
        key = self._owner_job_key(obj)
        if key is None:
            return
        rtype = self._replica_type_of(obj)
        if rtype:
            self.expectations.observe_add(expectation_key(key, rtype, "pods"))
        self.enqueue_job_event(key)

    def update_pod(self, old: Dict[str, Any], new: Dict[str, Any]) -> None:
        if (old.get("metadata") or {}).get("resourceVersion") == (
            (new.get("metadata") or {}).get("resourceVersion")
        ):
            return
        key = self._owner_job_key(new) or self._owner_job_key(old)
        if key is not None:
            self.enqueue_job_event(key)

    def delete_pod(self, obj: Dict[str, Any]) -> None:
        key = self._owner_job_key(obj)
        if key is None:
            return
        rtype = self._replica_type_of(obj)
        if rtype:
            self.expectations.observe_del(expectation_key(key, rtype, "pods"))
        self.enqueue_job_event(key)

    def add_service(self, obj: Dict[str, Any]) -> None:
        key = self._owner_job_key(obj)
        if key is None:
            return
        rtype = self._replica_type_of(obj)
        if rtype:
            self.expectations.observe_add(expectation_key(key, rtype, "services"))
        self.enqueue_job_event(key)

    def update_service(self, old: Dict[str, Any], new: Dict[str, Any]) -> None:
        self.update_pod(old, new)

    def delete_service(self, obj: Dict[str, Any]) -> None:
        key = self._owner_job_key(obj)
        if key is None:
            return
        rtype = self._replica_type_of(obj)
        if rtype:
            self.expectations.observe_del(expectation_key(key, rtype, "services"))
        self.enqueue_job_event(key)

    # ------------------------------------------------------------------
    # claim / adopt / orphan (jobcontroller/pod.go:165-196)
    # ------------------------------------------------------------------

    def _claim_for_job(
        self,
        informer: SharedInformer,
        resource: str,
        job: TPUJob,
        from_dict: Callable[[Dict[str, Any]], Any],
    ) -> List[Any]:
        """Indexed claim loop shared by pods and services.

        Owned objects resolve through the controller-owner-UID index and
        adoption candidates through the job-name label index restricted to
        orphans, so the cost is O(objects-of-job) regardless of cluster size
        — no full-store scan on either path.  Objects controller-owned by
        someone else are never touched (pod.go:165-196 semantics).
        """
        ns = job.metadata.namespace or "default"
        selector = gen_labels(job.metadata.name)
        store = informer.store
        out: List[Any] = []
        for obj in store.by_index(INDEX_OWNER_UID, job.metadata.uid):
            meta = obj.get("metadata") or {}
            if (meta.get("namespace") or "default") != ns:
                continue
            out.append(from_dict(obj))
        for obj in store.by_index(INDEX_JOB_NAME, selector[c.LABEL_JOB_NAME]):
            meta = obj.get("metadata") or {}
            if (meta.get("namespace") or "default") != ns:
                continue
            if any(r.get("controller") for r in meta.get("ownerReferences") or []):
                continue  # owned (by us: already collected; by another: skip)
            labels = meta.get("labels") or {}
            if not all(labels.get(k) == v for k, v in selector.items()):
                continue
            adopted = self._adopt(resource, job, meta)
            if adopted is not None:
                out.append(from_dict(adopted))
        return out

    def get_pods_for_job(self, job: TPUJob) -> List[Pod]:
        return self._claim_for_job(self.pod_informer, RESOURCE_PODS, job, Pod.from_dict)

    def get_services_for_job(self, job: TPUJob) -> List[Service]:
        return self._claim_for_job(
            self.service_informer, RESOURCE_SERVICES, job, Service.from_dict
        )

    def _adopt(self, resource: str, job: TPUJob, meta: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Adopt an orphan by patching a controller owner ref onto it, with an
        uncached quorum recheck of the owner (pod.go:184-195): a deleted or
        terminal job must not adopt."""
        try:
            fresh = self.clients.tpujobs.get(job.metadata.namespace or "default", job.metadata.name)
        except NotFoundError:
            return None
        if fresh.metadata.uid != job.metadata.uid or fresh.metadata.deletion_timestamp:
            return None
        ref = {
            "apiVersion": job.api_version,
            "kind": job.kind,
            "name": job.metadata.name,
            "uid": job.metadata.uid,
            "controller": True,
            "blockOwnerDeletion": True,
        }
        try:
            return self.clients.server.patch(
                resource,
                meta.get("namespace") or "default",
                meta.get("name"),
                {"metadata": {"ownerReferences": [ref]}},
            )
        except NotFoundError:
            return None

    # ------------------------------------------------------------------
    # slicing helpers (jobcontroller/pod.go:199-219, service.go:104-148)
    # ------------------------------------------------------------------

    @staticmethod
    def filter_by_replica_type(objs, rtype: str):
        return [o for o in objs if o.metadata.labels.get(c.LABEL_REPLICA_TYPE) == rtype.lower()]

    @staticmethod
    def get_slices(objs, replicas: int) -> List[List]:
        """Index objects into per-replica-index slices; out-of-range indexes
        are logged and ignored (pod.go:118-137)."""
        slices: List[List] = [[] for _ in range(replicas)]
        for o in objs:
            idx_s = o.metadata.labels.get(c.LABEL_REPLICA_INDEX)
            try:
                idx = int(idx_s)
            except (TypeError, ValueError):
                _slice_warner.warning(
                    log,
                    (o.metadata.namespace, o.metadata.name, "invalid-index", idx_s),
                    "object %s has no/invalid replica index %r", o.metadata.name, idx_s)
                continue
            if 0 <= idx < replicas:
                slices[idx].append(o)
            else:
                _slice_warner.warning(
                    log,
                    (o.metadata.namespace, o.metadata.name, "out-of-range", idx),
                    "object %s index %d out of range [0,%d)", o.metadata.name, idx, replicas)
        return slices

    # ------------------------------------------------------------------
    # run loop (controller.go:185-274)
    # ------------------------------------------------------------------

    def satisfied_expectations(self, job: TPUJob) -> bool:
        """controller.go:497-516: sync only when informer caches reflect our
        own writes for every replica type."""
        key = job.key
        for rtype in job.spec.tpu_replica_specs:
            if not self.expectations.satisfied(expectation_key(key, rtype, "pods")):
                return False
            if not self.expectations.satisfied(expectation_key(key, rtype, "services")):
                return False
        return True

    def sync_handler(self, key: str) -> bool:  # pragma: no cover - overridden
        raise NotImplementedError

    def process_next_item(self, timeout: Optional[float] = None) -> bool:
        """One worker iteration: dequeue, sync, forget-or-backoff.

        Each item processed under tracing opens a root ``sync`` span tagged
        with a fresh correlation id; the queue wait rides along as a
        pre-measured child span, and the finished span tree feeds the
        flight recorder, the span-derived metrics and (for slow syncs) a
        rate-limited span-tree dump.
        """
        from tpujob.runtime import SHUTDOWN

        try:
            key = self.queue.get(timeout=timeout)
        except SHUTDOWN:
            return False
        if key is None:
            return True
        metrics.queue_depth.set(len(self.queue))
        shard = self._shard_of_key(key)
        # register in-flight BEFORE the ownership check: the coordinator
        # marks a shard draining and THEN polls the in-flight set, so a
        # check-then-register order would let a drain observe "no sync in
        # flight" in the instant between our passing check and our
        # registration — and release the lease under a sync that is about
        # to write.  Registered first, either our check sees the drain (we
        # drop below) or the drain sees us (it waits us out).
        self._shard_inflight_add(shard, key)
        if (self.sharder is not None and shard is not None
                and not self.sharder.is_active(shard)):
            # rebalanced away (or draining) between enqueue and dequeue:
            # drop WITHOUT syncing.  The shard's new owner enqueues every
            # cached job of the shard at acquisition, so nothing is lost —
            # and syncing here would be exactly the two-owners window the
            # handoff protocol exists to close.
            self._shard_inflight_remove(shard, key)
            self.queue.pop_due(key)
            self.queue.forget(key)
            self.queue.done(key)
            return True
        due = self.queue.pop_due(key)
        start = time.monotonic()
        ctx = TRACER.sync_root("sync", job=key)
        with self._shard_call_context(shard), ctx as root:
            try:
                # a missing stamp means the key was dirty-requeued at done()
                # while its stamp was being consumed (watch-event re-add
                # racing the dequeue): it became due at the requeue, i.e.
                # just now
                wait = max(0.0, start - due) if due is not None else 0.0
                metrics.queue_latency.observe(wait)
                ctx.add_closed("queue_wait", wait)
            except Exception:
                # best-effort observability must not skip the sync (or the
                # queue.done below that keeps the key processable)
                log.exception("error recording queue wait for job %s", key)
            synced_ok = False
            try:
                forget = self.sync_handler(key)
                synced_ok = True
                if forget:
                    self.queue.forget(key)
                else:
                    self.queue.add_rate_limited(key)
            except Exception:
                if root is not None:
                    root.error = "sync raised; requeued with backoff"
                log.exception("error syncing job %s", key)
                self.queue.add_rate_limited(key)
            finally:
                metrics.reconcile_duration.observe(time.monotonic() - start)
                # deregister BEFORE done(): done() is what makes a
                # dirty-requeued key dequeueable again, and the next worker
                # registers itself before syncing — remove-after-done would
                # let our removal delete THAT worker's (set-shared) entry
                # and blind the drain barrier to its in-flight sync
                self._shard_inflight_remove(shard, key)
                self.queue.done(key)
        try:
            if synced_ok:
                # only a sync that ran to completion closes the cold-start
                # measurement — a first dequeue that died on a transient API
                # error would under-report recovery latency exactly in the
                # degraded runs the metric exists to expose
                self._note_first_sync()
            self._sink_trace(key, ctx)
        except Exception:
            # observers are best-effort: a sink failure must not kill the
            # worker thread (same contract as the EventRecorder sinks)
            log.exception("error delivering sync trace for job %s", key)
        return True

    def _sink_trace(self, key: str, ctx) -> None:
        """Deliver one finished sync trace to its sinks: span-derived
        metrics, the flight recorder, and the slow-sync dump."""
        spans = ctx.spans
        if not spans:
            return  # tracing disabled
        for sp in spans:
            if sp.duration is None:
                continue
            if sp.name == "api":
                metrics.api_request_duration.labels(
                    verb=str(sp.tags.get("verb", "")),
                    resource=str(sp.tags.get("resource", "")),
                    code=str(sp.tags.get("code", "")),
                ).observe(sp.duration)
            elif sp.name == "phase":
                metrics.sync_phase_duration.labels(
                    phase=str(sp.tags.get("phase", ""))
                ).observe(sp.duration)
        self.flight.record_sync(key, ctx.trace_id, spans)
        root = next((s for s in spans if s.parent_id is None), None)
        threshold = self.config.slow_sync_threshold_s
        if (root is not None and root.duration is not None and threshold > 0
                and root.duration >= threshold):
            # token bucket per job: a crash-looping job dumps a few traces,
            # then is damped — it cannot flood the log (the restart-backoff
            # damper pattern applied to logging)
            if self._slow_dump_limiter.allow(key):
                from tpujob.controller.joblogger import logger_for_key
                from tpujob.obs.debug import span_tree

                logger_for_key(log, key).with_fields(
                    corr_id=ctx.trace_id, trace=span_tree(spans),
                ).warning("slow sync: %.3fs exceeds threshold %.3fs",
                          root.duration, threshold)

    def _note_first_sync(self) -> None:
        """Close the cold-start measurement on the first completed sync."""
        # benign double-checked fast path: a stale False re-checks under
        # the lock below; a stale True can only occur after the first sync
        # already recorded, when skipping is the correct outcome
        if self._first_sync_recorded or self._run_started_mono is None:  # noqa: TPL003
            return
        with self._cold_start_lock:
            if self._first_sync_recorded:
                return
            self._first_sync_recorded = True
            elapsed = time.monotonic() - self._run_started_mono
        metrics.cold_start_duration.labels(stage="first_sync").observe(elapsed)
        self.flight.record(
            CONTROLLER_TIMELINE_KEY, "coldstart",
            f"first sync completed {elapsed * 1e3:.1f}ms after controller start",
            {"stage": "first_sync", "duration_s": round(elapsed, 6)})

    def on_caches_synced(self) -> None:
        """Hook invoked by run() after the initial LIST landed and before any
        worker dequeues — the point where durable state (job status) is fully
        visible and in-memory ledgers may be reconstructed from it."""

    def resync_all(self) -> int:
        """Re-enqueue every cached job (the informer resync replay: drift
        between cluster and desired state heals even if a watch event was
        lost).  Returns the number of jobs enqueued."""
        keys = [self.job_key_of(obj) for obj in self.job_informer.store.list()]
        for key in keys:
            self.enqueue_job(key)
        return len(keys)

    def run(self, stop_event: threading.Event, threadiness: Optional[int] = None) -> List[threading.Thread]:
        """Start informers + N workers (controller.go:185-213).

        Cold start is correct by construction: no worker thread exists until
        the initial LIST of every informer landed (the wait-for-cache-sync
        barrier below), and a fresh ExpectationsCache treats unknown keys as
        satisfied — so the first sync of every job sees the full durable
        state, never a half-filled cache that would double-create pods.
        """
        self._run_started_mono = time.monotonic()
        # pre-worker reset: no worker thread exists yet, so the write
        # happens-before any concurrent _note_first_sync
        self._first_sync_recorded = False  # noqa: TPL003
        self.factory.start(stop_event)
        if not self.factory.wait_for_cache_sync(self.config.cache_sync_timeout_s):
            raise RuntimeError("informer caches failed to sync")
        synced_s = time.monotonic() - self._run_started_mono
        metrics.cold_start_duration.labels(stage="caches_synced").observe(synced_s)
        self.flight.record(
            CONTROLLER_TIMELINE_KEY, "coldstart",
            f"informer caches synced in {synced_s * 1e3:.1f}ms "
            f"({self.job_informer.store.count()} job(s) listed)",
            {"stage": "caches_synced", "duration_s": round(synced_s, 6)})
        # ledger reconstruction from durable state happens behind the
        # barrier, before the first dequeue
        self.on_caches_synced()

        def worker():
            while not stop_event.is_set():
                if not self.process_next_item(timeout=0.1):
                    return

        n = threadiness or self.config.threadiness
        threads = [
            threading.Thread(target=worker, daemon=True, name=f"tpujob-worker-{i}")
            for i in range(n)
        ]

        # periodic resync (--resync-period, options.go:62): the reference's
        # 12h informer resync; <= 0 disables
        period = self.config.resync_period
        if period and period > 0:

            def resync_loop():
                while not stop_event.wait(period):
                    count = self.resync_all()
                    log.info("periodic resync: re-enqueued %d jobs", count)

            threads.append(
                threading.Thread(target=resync_loop, daemon=True, name="tpujob-resync")
            )
        for t in threads:
            t.start()
        return threads
