"""Per-job structured logging.

Mirrors the reference's context-tagged loggers
(``vendor/github.com/kubeflow/tf-operator/pkg/logger/logger.go:26-79``:
``LoggerForJob/Replica/Pod/Key/Unstructured``): every reconcile log line
carries job / uid / replica-type / pod fields so operators can grep one
job out of a many-jobs controller log.

Fields ride on the ``LogRecord`` as ``record.fields`` (a dict); the
formatters below render them for both text and JSON output.  Loggers are
cheap adapters — build them per call site, don't cache.
"""
from __future__ import annotations

import json
import logging
from typing import Any, Dict


class FieldsAdapter(logging.LoggerAdapter):
    """LoggerAdapter carrying structured fields (logrus ``WithFields``)."""

    def process(self, msg, kwargs):
        extra = dict(kwargs.get("extra") or {})
        fields = dict(self.extra)
        fields.update(extra.get("fields") or {})
        extra["fields"] = fields
        kwargs["extra"] = extra
        return msg, kwargs

    def with_fields(self, **fields) -> "FieldsAdapter":
        merged = dict(self.extra)
        merged.update(fields)
        return FieldsAdapter(self.logger, merged)


def logger_for_key(logger: logging.Logger, key: str) -> FieldsAdapter:
    """logger.go:67-73 (LoggerForKey)."""
    return FieldsAdapter(logger, {"job": key})


def logger_for_job(logger: logging.Logger, job) -> FieldsAdapter:
    """logger.go:26-33 (LoggerForJob): job=ns/name + uid."""
    fields: Dict[str, Any] = {"job": job.key}
    if job.metadata.uid:
        fields["uid"] = job.metadata.uid
    return FieldsAdapter(logger, fields)


def logger_for_replica(logger: logging.Logger, job, rtype: str) -> FieldsAdapter:
    """logger.go:35-44 (LoggerForReplica)."""
    return logger_for_job(logger, job).with_fields(replica_type=rtype)


def logger_for_pod(logger: logging.Logger, pod, job=None) -> FieldsAdapter:
    """logger.go:46-56 (LoggerForPod)."""
    ns = pod.metadata.namespace or "default"
    fields: Dict[str, Any] = {"pod": f"{ns}/{pod.metadata.name}"}
    if pod.metadata.uid:
        fields["pod_uid"] = pod.metadata.uid
    base = logger_for_job(logger, job) if job is not None else FieldsAdapter(logger, {})
    return base.with_fields(**fields)


def logger_for_unstructured(logger: logging.Logger, obj: Dict[str, Any]) -> FieldsAdapter:
    """logger.go:75-79 (LoggerForUnstructured): raw dict before conversion."""
    meta = obj.get("metadata") or {}
    ns = meta.get("namespace") or "default"
    fields: Dict[str, Any] = {"job": f"{ns}/{meta.get('name')}"}
    if meta.get("uid"):
        fields["uid"] = meta["uid"]
    return FieldsAdapter(logger, fields)


# ---------------------------------------------------------------------------
# Formatters rendering record.fields (wired by tpujob.server.app)
# ---------------------------------------------------------------------------


class TextFieldsFormatter(logging.Formatter):
    """Plain text with a logfmt-style field suffix: ``msg (job=ns/n uid=..)``."""

    def __init__(self):
        super().__init__("%(asctime)s %(levelname)s %(name)s: %(message)s")

    def format(self, record: logging.LogRecord) -> str:
        out = super().format(record)
        fields = getattr(record, "fields", None)
        if fields:
            rendered = []
            for k, v in fields.items():
                try:
                    rendered.append(f"{k}={v}")
                except Exception:  # noqa: TPL005 - logging contract: a
                    # hostile __str__ must not kill the log line
                    rendered.append(f"{k}=<unrepresentable {type(v).__name__}>")
            out += " (" + " ".join(rendered) + ")"
        return out


def _json_safe(value: Any) -> str:
    """Fallback serializer for non-JSON field values (exceptions, arbitrary
    objects): a log line must never raise inside logging — a formatter
    crash turns one diagnostic into a logging-handler error cascade."""
    try:
        return repr(value)
    except Exception:  # noqa: TPL005 - logging contract: even a hostile
        # __repr__ must not kill the log line
        return f"<unrepresentable {type(value).__name__}>"


class JsonFieldsFormatter(logging.Formatter):
    """One JSON object per line with the fields inlined (the reference's
    logrus JSON format for Stackdriver, main.go:42-58).  Non-JSON-safe
    field values (exceptions, objects) are serialized via ``repr`` instead
    of raising inside the logging call."""

    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, Any] = {
            "time": self.formatTime(record),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        out.update(getattr(record, "fields", None) or {})
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=_json_safe)


def configure_root_logging(json_format: bool, level: int = logging.INFO) -> None:
    """Install the fields-aware formatter on the root logger (idempotent)."""
    root = logging.getLogger()
    root.setLevel(level)
    formatter: logging.Formatter = (
        JsonFieldsFormatter() if json_format else TextFieldsFormatter()
    )
    if root.handlers:
        for h in root.handlers:
            h.setFormatter(formatter)
    else:
        h = logging.StreamHandler()
        h.setFormatter(formatter)
        root.addHandler(h)
