"""Job condition state machine.

Mirrors reference ``pkg/controller.v1/pytorch/status.go:226-272`` (condition
set/filter logic with Running↔Restarting mutual exclusion and terminal-state
handling) and the replica-status bookkeeping (``status.go:162-182``).
"""
from __future__ import annotations

import time
from typing import List, Optional

from tpujob.api import constants as c
from tpujob.api.types import JobCondition, JobStatus, ReplicaStatus
from tpujob.kube.objects import Pod


def now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


# reasons (status.go:34-45 equivalents)
REASON_JOB_CREATED = "TPUJobCreated"
REASON_JOB_RUNNING = "TPUJobRunning"
REASON_JOB_RESTARTING = "TPUJobRestarting"
REASON_JOB_SUCCEEDED = "TPUJobSucceeded"
REASON_JOB_FAILED = "TPUJobFailed"


def get_condition(status: JobStatus, cond_type: str) -> Optional[JobCondition]:
    for cond in status.conditions:
        if cond.type == cond_type:
            return cond
    return None


def has_condition(status: JobStatus, cond_type: str) -> bool:
    cond = get_condition(status, cond_type)
    return cond is not None and cond.status == "True"

def is_succeeded(status: JobStatus) -> bool:
    return has_condition(status, c.JOB_SUCCEEDED)


def is_failed(status: JobStatus) -> bool:
    return has_condition(status, c.JOB_FAILED)


def is_finished(status: JobStatus) -> bool:
    return is_succeeded(status) or is_failed(status)


def _new_condition(cond_type: str, reason: str, message: str) -> JobCondition:
    now = now_iso()
    return JobCondition(
        type=cond_type,
        status="True",
        reason=reason,
        message=message,
        last_update_time=now,
        last_transition_time=now,
    )


def _filter_out(conditions: List[JobCondition], cond_type: str) -> List[JobCondition]:
    """Drop conditions of `cond_type` (status.go filterOutCondition)."""
    return [cond for cond in conditions if cond.type != cond_type]


def set_condition(status: JobStatus, condition: JobCondition) -> None:
    """Set/refresh a condition with the reference's exclusion semantics
    (status.go:226-272):

    - Running=True removes Restarting; Restarting=True removes Running.
    - Succeeded/Failed=True flips Running to False (job no longer running)
      rather than dropping history.
    - Re-setting an identical condition (same status+reason) is a no-op so
      lastTransitionTime is preserved.
    """
    current = get_condition(status, condition.type)
    if current is not None and current.status == condition.status and current.reason == condition.reason:
        current.last_update_time = condition.last_update_time
        current.message = condition.message
        return

    conditions = _filter_out(status.conditions, condition.type)
    if condition.status == "True":
        if condition.type == c.JOB_RUNNING:
            conditions = _filter_out(conditions, c.JOB_RESTARTING)
        elif condition.type == c.JOB_RESTARTING:
            conditions = _filter_out(conditions, c.JOB_RUNNING)
        elif condition.type in (c.JOB_SUCCEEDED, c.JOB_FAILED):
            for cond in conditions:
                if cond.type == c.JOB_RUNNING and cond.status == "True":
                    cond.status = "False"
                    cond.last_transition_time = condition.last_transition_time
                    cond.last_update_time = condition.last_update_time
    conditions.append(condition)
    status.conditions = conditions


def update_job_conditions(status: JobStatus, cond_type: str, reason: str, message: str) -> None:
    set_condition(status, _new_condition(cond_type, reason, message))


def initialize_replica_statuses(status: JobStatus, rtype: str) -> None:
    """status.go:162-168: reset the replica status for a type each sync.

    Phase counters (active/succeeded/failed) are recomputed from pods every
    sync, but ``restarts`` is cumulative history — a recreated pod carries no
    trace of its predecessors — so it survives the reset."""
    prev = status.replica_statuses.get(rtype)
    status.replica_statuses[rtype] = ReplicaStatus(
        restarts=prev.restarts if prev is not None else 0
    )


def update_replica_statuses(status: JobStatus, rtype: str, pod: Pod) -> None:
    """status.go:172-182: bump counters from a pod phase."""
    rs = status.replica_statuses.setdefault(rtype, ReplicaStatus())
    phase = pod.status.phase
    if phase == "Running":
        rs.active += 1
    elif phase == "Succeeded":
        rs.succeeded += 1
    elif phase == "Failed":
        rs.failed += 1
