"""Job condition state machine and status diffing.

Mirrors reference ``pkg/controller.v1/pytorch/status.go:226-272`` (condition
set/filter logic with Running↔Restarting mutual exclusion and terminal-state
handling) and the replica-status bookkeeping (``status.go:162-182``), plus
the semantic status diff the write path uses to suppress no-op writes and to
ship JSON-merge-patches of only the changed fields.
"""
from __future__ import annotations

import calendar
import copy
import time
from typing import Any, Dict, List, Optional

from tpujob.api import constants as c
from tpujob.api.types import JobCondition, JobStatus, ReplicaStatus
from tpujob.kube.objects import Pod


def now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def parse_iso(ts: Optional[str]) -> Optional[float]:
    """Inverse of :func:`now_iso` — THE status-timestamp parser (epoch
    seconds), shared by every consumer so the grammar lives in one place.
    Garbage parses as unset: one corrupted timestamp write must degrade
    the feature reading it, never crash-loop the sync."""
    if not ts:
        return None
    try:
        return calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
    except ValueError:
        return None


# reasons (status.go:34-45 equivalents)
REASON_JOB_CREATED = "TPUJobCreated"
REASON_JOB_RUNNING = "TPUJobRunning"
REASON_JOB_RESTARTING = "TPUJobRestarting"
REASON_JOB_SUCCEEDED = "TPUJobSucceeded"
REASON_JOB_FAILED = "TPUJobFailed"
# elastic resize (staged drain/join state machine)
REASON_JOB_RESIZING = "TPUJobResizing"
REASON_RESIZE_COMPLETED = "TPUJobResizeCompleted"
REASON_RESIZE_ROLLED_BACK = "TPUJobResizeRolledBack"
# progress watchdog (workload telemetry plane)
REASON_JOB_STALLED = "TPUJobStalled"
REASON_PROGRESS_RESUMED = "TPUJobProgressResumed"
# native gang scheduler (all-or-nothing admission queue + preemption)
REASON_JOB_QUEUED = "TPUJobQueued"
REASON_JOB_ADMITTED = "TPUJobAdmitted"
REASON_JOB_PREEMPTED = "TPUJobPreempted"
REASON_JOB_MIGRATED = "TPUJobMigrated"  # evicted off a dead/cordoned host
REASON_JOB_UNSCHEDULABLE = "TPUJobUnschedulable"


def get_condition(status: JobStatus, cond_type: str) -> Optional[JobCondition]:
    for cond in status.conditions:
        if cond.type == cond_type:
            return cond
    return None


def has_condition(status: JobStatus, cond_type: str) -> bool:
    cond = get_condition(status, cond_type)
    return cond is not None and cond.status == "True"

def is_succeeded(status: JobStatus) -> bool:
    return has_condition(status, c.JOB_SUCCEEDED)


def is_failed(status: JobStatus) -> bool:
    return has_condition(status, c.JOB_FAILED)


def is_finished(status: JobStatus) -> bool:
    return is_succeeded(status) or is_failed(status)


def _new_condition(cond_type: str, reason: str, message: str) -> JobCondition:
    now = now_iso()
    return JobCondition(
        type=cond_type,
        status="True",
        reason=reason,
        message=message,
        last_update_time=now,
        last_transition_time=now,
    )


def _filter_out(conditions: List[JobCondition], cond_type: str) -> List[JobCondition]:
    """Drop conditions of `cond_type` (status.go filterOutCondition)."""
    return [cond for cond in conditions if cond.type != cond_type]


def set_condition(status: JobStatus, condition: JobCondition) -> None:
    """Set/refresh a condition with the reference's exclusion semantics
    (status.go:226-272):

    - Running=True removes Restarting; Restarting=True removes Running.
    - Succeeded/Failed=True flips every live condition (Running,
      Restarting, Resizing, Stalled, Queued) to False rather than
      dropping history.
    - Re-setting an identical condition (same status+reason) is a no-op so
      lastTransitionTime is preserved.
    """
    current = get_condition(status, condition.type)
    if current is not None and current.status == condition.status and current.reason == condition.reason:
        current.last_update_time = condition.last_update_time
        current.message = condition.message
        return

    conditions = _filter_out(status.conditions, condition.type)
    if condition.status == "True":
        if condition.type == c.JOB_RUNNING:
            conditions = _filter_out(conditions, c.JOB_RESTARTING)
        elif condition.type == c.JOB_RESTARTING:
            conditions = _filter_out(conditions, c.JOB_RUNNING)
        elif condition.type in (c.JOB_SUCCEEDED, c.JOB_FAILED):
            # a finished job is neither running, nor restarting, nor
            # mid-resize, nor stalled, nor waiting in the admission queue:
            # flip every live condition to False (history preserved) rather
            # than dropping them.  TPL202 checks this tuple against every
            # condition set True anywhere in the controller.
            for cond in conditions:
                if cond.type in (c.JOB_RUNNING, c.JOB_RESTARTING,
                                 c.JOB_RESIZING, c.JOB_STALLED,
                                 c.JOB_QUEUED) \
                        and cond.status == "True":
                    cond.status = "False"
                    cond.last_transition_time = condition.last_transition_time
                    cond.last_update_time = condition.last_update_time
    conditions.append(condition)
    status.conditions = conditions


def update_job_conditions(status: JobStatus, cond_type: str, reason: str, message: str) -> None:
    set_condition(status, _new_condition(cond_type, reason, message))


def mark_condition_false(status: JobStatus, cond_type: str, reason: str, message: str) -> None:
    """Flip a condition to False with a fresh reason/message (history kept):
    the resize state machine's completion transition (Resizing True->False)."""
    cond = _new_condition(cond_type, reason, message)
    cond.status = "False"
    set_condition(status, cond)


def initialize_replica_statuses(status: JobStatus, rtype: str) -> None:
    """status.go:162-168: reset the replica status for a type each sync.

    Phase counters (active/succeeded/failed) are recomputed from pods every
    sync, but ``restarts`` is cumulative history — a recreated pod carries no
    trace of its predecessors — so it survives the reset."""
    prev = status.replica_statuses.get(rtype)
    status.replica_statuses[rtype] = ReplicaStatus(
        restarts=prev.restarts if prev is not None else 0
    )


def update_replica_statuses(status: JobStatus, rtype: str, pod: Pod) -> None:
    """status.go:172-182: bump counters from a pod phase."""
    rs = status.replica_statuses.setdefault(rtype, ReplicaStatus())
    phase = pod.status.phase
    if phase == "Running":
        rs.active += 1
    elif phase == "Succeeded":
        rs.succeeded += 1
    elif phase == "Failed":
        rs.failed += 1


# ---------------------------------------------------------------------------
# semantic status diffing (the API write path's no-op filter + patch builder)
# ---------------------------------------------------------------------------

# Fields that change on every sync without carrying state: re-setting an
# identical condition refreshes only its lastUpdateTime, and the controller
# stamps lastReconcileTime at write time.  Treating these as changes would
# turn every sync of a running job into a status write — exactly the
# redundant write QPS this diff exists to eliminate.  lastTransitionTime is
# NOT volatile: it moves only on real condition transitions.
_VOLATILE_TOP = ("lastReconcileTime",)
_VOLATILE_CONDITION = ("lastUpdateTime",)

_MISSING = object()


def _strip_volatile(status: Dict[str, Any]) -> Dict[str, Any]:
    out = copy.deepcopy(status)
    for k in _VOLATILE_TOP:
        out.pop(k, None)
    for cond in out.get("conditions") or []:
        if isinstance(cond, dict):
            for k in _VOLATILE_CONDITION:
                cond.pop(k, None)
    return out


def _merge_diff(old: Dict[str, Any], new: Dict[str, Any]) -> Dict[str, Any]:
    """RFC 7386 merge patch transforming ``old`` into ``new``.

    Dicts recurse; lists are atomic (a changed list ships whole — merge
    patch has no per-element semantics); keys present in ``old`` but absent
    in ``new`` become explicit ``None`` deletions, which matters because the
    status serialization omits zero-valued fields — without the null, stale
    server-side keys (``active: 2`` on a completed job) would survive the
    merge forever."""
    patch: Dict[str, Any] = {}
    for k, v in new.items():
        ov = old.get(k, _MISSING)
        if ov is _MISSING:
            patch[k] = v
        elif isinstance(v, dict) and isinstance(ov, dict):
            sub = _merge_diff(ov, v)
            if sub:
                patch[k] = sub
        elif v != ov:
            patch[k] = v
    for k in old:
        if k not in new:
            patch[k] = None
    return patch


def status_merge_patch(
    old: Optional[Dict[str, Any]], new: Dict[str, Any]
) -> Optional[Dict[str, Any]]:
    """The JSON-merge-patch that brings status dict ``old`` to ``new``, or
    ``None`` when the two are semantically identical (volatile timestamp
    refreshes do not count as change).

    When a condition changed semantically, the patch carries the ENTIRE raw
    ``new`` conditions list (volatile fields included): conditions are a
    list, atomic under merge patch, so a partial rendering would drop
    history."""
    n_old = _strip_volatile(old or {})
    n_new = _strip_volatile(new)
    patch = _merge_diff(n_old, n_new)
    if not patch:
        return None
    if "conditions" in patch and new.get("conditions") is not None:
        patch["conditions"] = copy.deepcopy(new["conditions"])
    return patch


def raw_status_merge_patch(
    old: Optional[Dict[str, Any]], new: Dict[str, Any]
) -> Dict[str, Any]:
    """Volatile-INCLUSIVE merge patch: every differing key ships, timestamp
    refreshes included.  Used when no-op suppression is disabled — the write
    must land the refreshed volatile fields in the cache, or the
    object-equality gate upstream would see drift forever and write every
    sync (a self-sustaining write storm a full PUT never had)."""
    return _merge_diff(old or {}, new)


def patch_touches_restarts(patch: Dict[str, Any]) -> bool:
    """Whether a status merge patch writes (or deletes) a cumulative
    ``restarts`` counter.  Such writes must be resourceVersion-checked:
    ``restarts`` is history, not derived state — a merge patch built from a
    stale cache would silently regress it, where every other status field is
    recomputed from live pods each sync and self-heals."""
    rs = patch.get("replicaStatuses", _MISSING)
    if rs is _MISSING:
        return False
    if not isinstance(rs, dict):
        return True  # null-delete of the whole map drops counters
    for entry in rs.values():
        if not isinstance(entry, dict) or "restarts" in entry:
            return True
    return False
