"""TPU cluster-spec environment injection.

The TPU-native replacement for the reference's ``setClusterSpec``
(``pkg/controller.v1/pytorch/pod.go:234-281``), which injects the
``torch.distributed`` TCP rendezvous (MASTER_ADDR/MASTER_PORT/WORLD_SIZE/
RANK).  Here the rendezvous is the JAX/PJRT distributed coordinator plus
libtpu slice topology:

- ``PJRT_DEVICE=TPU`` selects the PJRT TPU plugin.
- ``TPUJOB_COORDINATOR_ADDRESS``/``TPUJOB_NUM_PROCESSES``/
  ``TPUJOB_PROCESS_ID`` drive ``jax.distributed.initialize`` (and
  ``torch_xla`` via ``PJRT_*`` aliases).
- ``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES``/``TPU_ACCELERATOR_TYPE``/
  ``TPU_TOPOLOGY`` are the libtpu multi-host contract.
- ``MEGASCALE_*`` appear only for multislice (num_slices > 1), carrying the
  DCN coordinator.
- ``MASTER_ADDR``/``MASTER_PORT``/``WORLD_SIZE``/``RANK`` are kept for
  torch.distributed-style compatibility, with the TPU rank arithmetic:
  WORLD_SIZE is the *process* world (hosts × slices), not the pod count.

The single biggest semantic delta vs the reference (SURVEY.md §7 step 4):
each host pod runs one XLA process owning ``devices_per_host`` chips, so
rank/world-size derive from the slice topology, not from replica counts.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from tpujob.api import constants as c
from tpujob.api.topology import SliceTopology
from tpujob.api.types import TPUJob
from tpujob.kube.control import gen_general_name
from tpujob.kube.objects import EnvVar, Pod

# The DCN (cross-slice) coordinator port.  Contract: the injected
# MEGASCALE_COORDINATOR_ADDRESS is always host:port — libtpu defaults the
# port when absent, but an explicit port keeps the address dialable under
# any libtpu version and lets the coordinator service expose it by name.
MEGASCALE_PORT = 8080


def coordinator_replica(job: TPUJob) -> str:
    """The replica type hosting process 0: Master, or Worker for
    master-less jobs (whose worker 0 is then the coordinator)."""
    if c.REPLICA_TYPE_MASTER in job.spec.tpu_replica_specs:
        return c.REPLICA_TYPE_MASTER
    return c.REPLICA_TYPE_WORKER


def coordinator_service_name(job_name: str, coord_rtype: str = c.REPLICA_TYPE_MASTER) -> str:
    """The headless rendezvous service, named after the coordinator pod
    (reference: service.go:123-139 names it {job}-master-0)."""
    return gen_general_name(job_name, coord_rtype, 0)


def coordinator_dns(job: TPUJob) -> str:
    ns = job.metadata.namespace or "default"
    return f"{coordinator_service_name(job.metadata.name, coordinator_replica(job))}.{ns}"


def is_multislice(job: TPUJob) -> bool:
    """True when ANY replica's slice spec resolves to num_slices > 1 — the
    same any-spec resolution set_cluster_spec uses, so the service-port
    declaration can never diverge from the MEGASCALE_* env injection."""
    for rspec in job.spec.tpu_replica_specs.values():
        tpu = rspec.tpu
        if tpu is not None and tpu.accelerator:
            try:
                if tpu.resolve().num_slices > 1:
                    return True
            except (TypeError, ValueError):
                continue
    return False


def pod_name_of_process(job_name: str, pid: int, has_master: bool) -> str:
    if has_master and pid == 0:
        return gen_general_name(job_name, c.REPLICA_TYPE_MASTER, 0)
    widx = pid - 1 if has_master else pid
    return gen_general_name(job_name, c.REPLICA_TYPE_WORKER, widx)


def worker_hostnames(
    job: TPUJob, topo: SliceTopology, has_master: bool, slice_id: int
) -> List[str]:
    """Pod hostnames for the hosts of ONE slice, TPU_WORKER_ID order.

    libtpu interprets the list as this slice's hosts indexed by
    TPU_WORKER_ID; cross-slice coordination rides MEGASCALE_*, so the list
    must not span slices.
    """
    base = slice_id * topo.hosts
    return [
        pod_name_of_process(job.metadata.name, base + h, has_master)
        for h in range(topo.hosts)
    ]


def process_id_for(rtype: str, index: int, has_master: bool) -> int:
    """Pod (rtype, index) -> global process id.  Master is process 0; worker
    i is process i+1 (reference rank arithmetic, pod.go:267-274)."""
    if rtype == c.REPLICA_TYPE_MASTER:
        return 0
    return index + 1 if has_master else index


def cluster_env(
    job: TPUJob,
    rtype: str,
    index: int,
    topo: Optional[SliceTopology],
    port: int,
) -> Dict[str, str]:
    """Compute the full injected environment for one pod."""
    has_master = c.REPLICA_TYPE_MASTER in job.spec.tpu_replica_specs
    is_coordinator = (rtype == coordinator_replica(job)) and index == 0

    # Coordinator: process 0 resolves itself as localhost (reference
    # pod.go:250); everyone else dials the coordinator's headless service DNS.
    coord_host = "localhost" if is_coordinator else coordinator_dns(job)
    coord = f"{coord_host}:{port}"

    # pod self-identity (downward-API convention): lets the workload address
    # its OWN pod — the progress-heartbeat channel publishes on it
    self_env = {
        "TPUJOB_POD_NAME": gen_general_name(job.metadata.name, rtype, index),
        "TPUJOB_POD_NAMESPACE": job.metadata.namespace or "default",
    }

    if topo is None:
        # No TPU spec: fall back to flat 1-pod-1-process accounting, exactly
        # the reference's WORLD_SIZE = Σ replicas (pod.go:252).
        world = sum(
            (r.replicas if r.replicas is not None else 1)
            for r in job.spec.tpu_replica_specs.values()
        )
        pid = process_id_for(rtype, index, has_master)
        env = {
            "TPUJOB_COORDINATOR_ADDRESS": coord,
            "TPUJOB_NUM_PROCESSES": str(world),
            "TPUJOB_PROCESS_ID": str(pid),
            **self_env,
            "MASTER_ADDR": coord_host,
            "MASTER_PORT": str(port),
            "WORLD_SIZE": str(world),
            "RANK": str(pid),
            "PYTHONUNBUFFERED": "1",
        }
        return env

    pid = process_id_for(rtype, index, has_master)
    slice_id, host_index = topo.host_of_process(pid)
    env = {
        "PJRT_DEVICE": "TPU",
        "TPUJOB_COORDINATOR_ADDRESS": coord,
        "TPUJOB_NUM_PROCESSES": str(topo.num_processes),
        "TPUJOB_PROCESS_ID": str(pid),
        "TPUJOB_NUM_SLICES": str(topo.num_slices),
        "TPUJOB_SLICE_ID": str(slice_id),
        "TPUJOB_HOST_INDEX": str(host_index),
        **self_env,
        "TPUJOB_DEVICES_PER_HOST": str(topo.devices_per_host),
        "TPUJOB_GLOBAL_DEVICES": str(topo.global_devices),
        # libtpu multi-host contract (per-slice: ids and hostnames must agree)
        "TPU_WORKER_ID": str(host_index),
        "TPU_WORKER_HOSTNAMES": ",".join(worker_hostnames(job, topo, has_master, slice_id)),
        "TPU_ACCELERATOR_TYPE": topo.accelerator,
        "TPU_TOPOLOGY": topo.topology,
        # torch.distributed-style compatibility (process-level world)
        "MASTER_ADDR": coord_host,
        "MASTER_PORT": str(port),
        "WORLD_SIZE": str(topo.num_processes),
        "RANK": str(pid),
        "PYTHONUNBUFFERED": "1",
    }
    if topo.num_slices > 1:
        env["MEGASCALE_COORDINATOR_ADDRESS"] = f"{coordinator_dns(job)}:{MEGASCALE_PORT}"
        env["MEGASCALE_NUM_SLICES"] = str(topo.num_slices)
        env["MEGASCALE_SLICE_ID"] = str(slice_id)
    return env


def set_cluster_spec(pod: Pod, job: TPUJob, rtype: str, index: int, port: int) -> None:
    """Inject the cluster env into every container of the pod (in place).

    User-specified env wins over injected env (same precedence as the
    reference, which appends only missing vars).
    """
    rspec = job.spec.tpu_replica_specs.get(rtype)
    tpu = rspec.tpu if rspec else None
    # the slice spec may live on either replica spec (Master carries it for
    # single-host jobs; sharing one slice is the common case)
    if tpu is None or not tpu.accelerator:
        for other in job.spec.tpu_replica_specs.values():
            if other.tpu and other.tpu.accelerator:
                tpu = other.tpu
                break
    topo = tpu.resolve() if tpu and tpu.accelerator else None
    env = cluster_env(job, rtype, index, topo, port)
    for container in pod.spec.containers:
        existing = {e.name for e in container.env}
        for name, value in env.items():
            if name not in existing:
                container.env.append(EnvVar(name=name, value=value))
