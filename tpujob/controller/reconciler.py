"""The TPUJob reconciler.

Mirrors the reference's reconcile heart, behavior-for-behavior, with TPU
cluster-spec injection in place of the NCCL wiring:

- ``syncTPUJob``/``reconcileTPUJobs`` — ``pkg/controller.v1/pytorch/controller.go:290-492``
- pod reconcile + ExitCode restart — ``pod.go:49-232`` + ``pod.go:91-109``
- master-only headless service — ``service.go:36-153``, ``controller.go:474-477``
- status convergence — ``status.go:63-152``
- terminal cleanup / CleanPodPolicy / TTL — ``job.go:153-209``
- backoff limit / active deadline — ``controller.go:391-461,520-568``
- gang scheduling PodGroup — ``jobcontroller.go:224-278``
"""
from __future__ import annotations

import calendar
import json
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from tpujob.api import constants as c
from tpujob.api.defaults import set_defaults_tpujob
from tpujob.api.types import ReplicaStatus, TPUJob
from tpujob.api.validation import validate_tpujob_spec
from tpujob.controller import status as st
from tpujob.controller import tpu_env
from tpujob.controller.config import render_init_containers
from tpujob.controller.joblogger import (
    logger_for_job,
    logger_for_key,
    logger_for_pod,
    logger_for_replica,
    logger_for_unstructured,
)
from tpujob.controller.job_base import JobController, _DedupWarner, expectation_key
from tpujob.kube.client import RESOURCE_TPUJOBS
from tpujob.kube.control import gen_general_name, gen_labels, gen_pod_group_name
from tpujob.kube.errors import ConflictError, NotFoundError, ServerTimeoutError
from tpujob.kube.objects import (
    Container,
    ObjectMeta,
    Pod,
    PodGroup,
    PodGroupSpec,
    ResourceRequirements,
    Service,
    ServicePort,
    ServiceSpec,
)
from tpujob.obs.trace import TRACER
from tpujob.runtime import is_retryable_exit_code
from tpujob.server import metrics

log = logging.getLogger("tpujob.reconciler")


_time_warner = _DedupWarner(interval=60.0)


def _parse_time(ts: Optional[str]) -> Optional[float]:
    """Parse a status timestamp, treating garbage as unset: one corrupted
    ``start_time``/``completion_time`` write must degrade the affected
    feature (deadline/TTL), not turn every subsequent sync of the job into
    a permanent ValueError crash-loop."""
    if not ts:
        return None
    try:
        return calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
    except ValueError:
        _time_warner.warning(
            log, ("unparseable-timestamp", ts),
            "unparseable status timestamp %r; treating as unset", ts)
        return None


def get_port_from_job(job: TPUJob, rtype: str) -> int:
    """Coordinator port lookup (util.go:34-47)."""
    rspec = job.spec.tpu_replica_specs.get(rtype)
    if rspec:
        for container in rspec.template.spec.containers:
            if container.name == c.DEFAULT_CONTAINER_NAME:
                for port in container.ports:
                    if port.name == c.DEFAULT_PORT_NAME:
                        return port.container_port
    return c.DEFAULT_PORT


def get_total_replicas(job: TPUJob) -> int:
    return sum(
        (r.replicas if r.replicas is not None else 1)
        for r in job.spec.tpu_replica_specs.values()
    )


class TPUJobController(JobController):
    """The operator's reconcile loop over TPUJob resources."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.job_informer.on_add(self._on_job_add)
        self.job_informer.on_update(self._on_job_update)
        self.job_informer.on_delete(self._on_job_delete)
        # injectable handlers for tests (controller.go:81-89)
        self.update_status_handler = self._update_job_status
        self.delete_job_handler = self._delete_job
        # restart increments made by the CURRENT sync, keyed by job key:
        # consumed by _update_job_status to rebase the cumulative counter
        # onto the fresh object when the status write hits 409 (a stale
        # informer cache must not swallow a counted recreation).  Safe
        # across worker threads: the workqueue never runs one key twice
        # concurrently, and keys don't share entries.
        self._restart_deltas: Dict[str, Dict[str, int]] = {}
        # per-(job key, rtype, replica index) crash-loop damper: (strikes,
        # last strike monotonic, not-before monotonic).  Keyed per index so
        # one crash-looping replica never delays a healthy sibling's
        # replacement.  Written only by the worker holding the job's
        # workqueue key (same safety argument as _restart_deltas above).
        self._restart_backoff: Dict[Tuple[str, str, int], Tuple[int, float, float]] = {}

    # ------------------------------------------------------------------
    # cold-start recovery (crash-only controller semantics)
    # ------------------------------------------------------------------

    def on_caches_synced(self) -> None:
        """Reconstruct in-memory ledgers from durable state after a (re)start.

        The crash-loop damper (`_restart_backoff`) dies with the process; a
        fresh controller starting at zero would prompt-restart every
        crash-looping replica at full speed — a restart storm each time the
        CONTROLLER itself crash-loops.  Rebuild it conservatively from
        ``status.replicaStatuses[].restarts`` (durable, cumulative) anchored
        at the newest condition transition timestamp.  Over-delaying is safe:
        the damper only gates REPLACEMENT of missing pods, so a healthy
        running replica is never touched.
        """
        seeded = self._rebuild_restart_backoff()
        if seeded:
            from tpujob.obs.recorder import CONTROLLER_TIMELINE_KEY

            self.flight.record(
                CONTROLLER_TIMELINE_KEY, "coldstart",
                f"restart-backoff damper reconstructed from status for "
                f"{seeded} replica type(s)",
                {"stage": "damper_rebuild", "seeded": seeded})

    def prepare_shard(self, shard: int) -> None:
        """Shard-acquisition hook (pre-activation): rebuild the crash-loop
        damper for the shard's jobs from durable status BEFORE any worker
        can sync them.  A rebalanced-in shard must not prompt-restart a
        crash-looping job it just inherited — the previous owner's damper
        died with its ownership, exactly as a cold-started controller's
        damper dies with its process, and the cold-start rebuild only ran
        for the shards owned back then."""
        seeded = self._rebuild_restart_backoff(shard=shard)
        if seeded:
            from tpujob.obs.recorder import CONTROLLER_TIMELINE_KEY

            self.flight.record(
                CONTROLLER_TIMELINE_KEY, "shard",
                f"shard {shard}: restart-backoff damper reconstructed from "
                f"status for {seeded} replica type(s)",
                {"shard": shard, "seeded": seeded})

    def on_shard_acquired(self, shard: int) -> None:
        """One combined post-activation pass over the inherited shard's
        jobs (a rebalance storm acquires many shards back to back, and each
        extra full-store scan rides the coordinator thread that also
        heartbeats under a sub-second soak lease): enqueue the replay AND
        re-arm the ActiveDeadlineSeconds requeues — the add_after the
        previous owner scheduled at job creation died with it, and at the
        production 12h resync a deadline could otherwise slip by hours
        before the next event surfaces it."""
        enqueued = 0
        for obj in self.job_informer.store.list():
            if self._shard_of_obj(obj) != shard:
                continue
            self.enqueue_job(self.job_key_of(obj))
            enqueued += 1
            try:
                job = TPUJob.from_dict(obj)
                set_defaults_tpujob(job)
            except (TypeError, ValueError):
                continue  # malformed CR: the enqueue replay's sync reports it
            if st.is_finished(job.status):
                continue
            ads = job.spec.run_policy.active_deadline_seconds
            if ads is None or ads < 0:
                continue
            started = _parse_time(job.status.start_time)
            # wall-vs-persisted-timestamp math like _past_active_deadline
            # (the two baselined TPL004 sites): status.startTime was written
            # by another process's wall clock, so monotonic cannot compare
            remaining = (float(ads) if started is None
                         else max(0.0, started + float(ads) - time.time()))  # noqa: TPL004
            self.queue.add_after(job.key, remaining)
        from tpujob.obs.recorder import CONTROLLER_TIMELINE_KEY

        self.flight.record(
            CONTROLLER_TIMELINE_KEY, "shard",
            f"shard {shard} acquired: {enqueued} cached job(s) enqueued",
            {"shard": shard, "jobs": enqueued})

    def _rebuild_restart_backoff(self, shard: Optional[int] = None) -> int:
        base = self.config.restart_backoff_seconds
        if base <= 0:
            return 0
        max_delay = self.config.restart_backoff_max_seconds
        now_mono, now_wall = time.monotonic(), time.time()
        seeded = 0
        for obj in self.job_informer.store.list():
            if shard is not None and self._shard_of_obj(obj) != shard:
                continue  # per-shard rebuild: only the acquired shard's jobs
            try:
                job = TPUJob.from_dict(obj)
                set_defaults_tpujob(job)
            except (TypeError, ValueError):
                continue  # malformed CR: the sync path reports it
            if st.is_finished(job.status):
                continue
            # anchor at the newest condition transition — the closest durable
            # proxy for "when the last counted restart happened"
            last_wall = max(
                (t for t in (_parse_time(cond.last_transition_time)
                             for cond in job.status.conditions) if t is not None),
                default=None,
            )
            for rtype, rspec in job.spec.tpu_replica_specs.items():
                if rspec.restart_policy != c.RESTART_POLICY_EXIT_CODE:
                    continue
                rs = job.status.replica_statuses.get(rtype)
                restarts = rs.restarts if rs is not None else 0
                if restarts <= 0:
                    continue
                strikes = min(restarts, 32)
                delay = 0.0 if strikes == 1 else min(
                    base * (2 ** min(strikes - 2, 30)), max_delay)
                # condition times are wall clock; the damper runs on the
                # monotonic clock — translate, clamping to "just now" if the
                # timestamp is in the future (clock skew)
                last_mono = (now_mono if last_wall is None
                             else now_mono - max(0.0, now_wall - last_wall))
                not_before = last_mono + delay
                # restarts are per-type, not per-index: seed every index
                # (conservative — only replacements of MISSING pods wait)
                replicas = rspec.replicas if rspec.replicas is not None else 1
                for index in range(replicas):
                    self._restart_backoff[(job.key, rtype, index)] = (
                        strikes, last_mono, not_before)
                seeded += 1
                self.flight.record(
                    job.key, "backoff",
                    f"cold start: damper reconstructed for {rtype} from "
                    f"status ({restarts} counted restart(s) -> strikes="
                    f"{strikes}, replacement delay {delay:.2f}s)",
                    {"rtype": rtype, "restarts": restarts, "strikes": strikes,
                     "delay_s": round(delay, 3)})
        return seeded

    # ------------------------------------------------------------------
    # job event handlers (job.go:35-149)
    # ------------------------------------------------------------------

    def _on_job_add(self, obj: Dict) -> None:
        key = self.job_key_of(obj)
        shard = self._shard_of_obj(obj)
        if (self.sharder is not None and shard is not None
                and not self.sharder.is_active(shard)):
            # another member's shard: its owner enqueues, schedules the
            # deadline requeue, and reports malformation — doing any of it
            # here would double the work (and the writes) fleet-wide
            return
        try:
            job = TPUJob.from_dict(obj)
            set_defaults_tpujob(job)
            errs = validate_tpujob_spec(job.spec, strict_topology=True)
        except (TypeError, ValueError) as e:
            errs = [str(e)]
            job = None
        if errs:
            # malformed CR: write a Failed condition back instead of crashing
            # (job.go:60-111 / informer.go:83-104 tolerance semantics).  The
            # shard context fences the write on this shard's lease.
            with self._shard_call_context(shard):
                self._fail_malformed(obj, errs)
            return
        metrics.jobs_created.inc()
        self.enqueue_job(key)
        # ActiveDeadlineSeconds: re-enqueue at the deadline (job.go:133-149)
        ads = job.spec.run_policy.active_deadline_seconds
        if ads is not None and ads >= 0:
            self.queue.add_after(key, float(ads))

    def _on_job_update(self, old: Dict, new: Dict) -> None:
        if (old.get("metadata") or {}).get("resourceVersion") == (
            (new.get("metadata") or {}).get("resourceVersion")
        ):
            return  # periodic resync replay, nothing changed
        # coalesced: most job MODIFIED events are the echo of our own status
        # writes, and they burst together with the pod events of the same
        # reconcile round — one settled sync covers them all
        self.enqueue_job_event(self.job_key_of(new))

    def _on_job_delete(self, obj: Dict) -> None:
        metrics.jobs_deleted.inc()
        key = self.job_key_of(obj)
        self._restart_deltas.pop(key, None)  # no leak; no carry-over to a
        # future job recreated under the same namespace/name
        for rtype in (c.REPLICA_TYPE_MASTER, c.REPLICA_TYPE_WORKER):
            self.expectations.delete(expectation_key(key, rtype, "pods"))
            self.expectations.delete(expectation_key(key, rtype, "services"))
        # pop in place (like _restart_deltas above) rather than rebinding a
        # rebuilt dict: a rebind would silently drop a concurrent worker
        # thread's _note_restart write for an unrelated job.  The snapshot
        # is list(dict) — a single C-level op that cannot interleave with a
        # worker's insert the way per-item comprehension iteration can.
        for k in list(self._restart_backoff):
            if k[0] == key:
                self._restart_backoff.pop(k, None)

    def _fail_malformed(self, obj: Dict, errs: List[str]) -> None:
        meta = obj.get("metadata") or {}
        ns, name = meta.get("namespace") or "default", meta.get("name")
        logger_for_unstructured(log, obj).warning("invalid TPUJob: %s", errs)
        # write back through the raw transport: the typed client would choke
        # on the very malformation we are reporting (job.go:60-111 uses the
        # raw CRD REST client for the same reason)
        from tpujob.api.types import JobStatus

        status = JobStatus.from_dict(obj.get("status") if isinstance(obj.get("status"), dict) else {})
        message = f"TPUJob {name} is invalid: " + "; ".join(errs)
        existing = st.get_condition(status, c.JOB_FAILED)
        if existing is not None and existing.status == "True" and existing.message == message:
            return  # already reported: avoid a write->watch->sync busy loop
        st.update_job_conditions(status, c.JOB_FAILED, st.REASON_JOB_FAILED, message)
        try:
            self.clients.server.update_status(
                RESOURCE_TPUJOBS,
                {"metadata": {"namespace": ns, "name": name}, "status": status.to_dict()},
            )
        except NotFoundError:
            return
        metrics.jobs_failed.inc()

    # ------------------------------------------------------------------
    # sync (controller.go:290-332)
    # ------------------------------------------------------------------

    def sync_handler(self, key: str) -> bool:
        with TRACER.span("phase", phase="cache_get"):
            ns, _, name = key.partition("/")
            cached = self.job_informer.store.get(ns, name)
            if cached is None:
                logger_for_key(log, key).info("job no longer exists")
                return True
            try:
                job = TPUJob.from_dict(cached)
                set_defaults_tpujob(job)
                # strict topology: a replicas-vs-slice mismatch cannot be
                # env-injected coherently, so it must fail visibly instead
                # of looping
                errs = validate_tpujob_spec(job.spec, strict_topology=True)
            except (TypeError, ValueError) as e:
                job, errs = None, [str(e)]
        if errs:
            self._fail_malformed(cached, errs)
            return True
        if not self.satisfied_expectations(job):
            # informer cache stale; a watch event will re-enqueue
            self.flight.record(
                key, "expectation",
                "sync gated: informer cache still awaiting our own writes")
            return True
        forget = self.reconcile_tpujobs(job)
        # one observation point for the whole sync's condition churn: every
        # path through reconcile (incl. _fail_job) mutates job.status in
        # place before returning here
        self.flight.note_conditions(key, job.status.conditions)
        return forget

    # ------------------------------------------------------------------
    # reconcile (controller.go:336-492)
    # ------------------------------------------------------------------

    def reconcile_tpujobs(self, job: TPUJob) -> bool:
        key = job.key
        old_status = job.status.deepcopy()
        # Deltas re-stashed by a failed status write count recreations the
        # cached status doesn't know about yet: fold them in up front (after
        # the old_status snapshot, so the fold alone registers as a change
        # to write) and put them back on the ledger — they stay unpersisted
        # until a status write lands.  At-least-once accounting: bounded
        # churn prefers the rare overcount of a lost-response write over
        # silently undercounting.
        carried = self._restart_deltas.pop(key, None) or {}
        if carried:
            for rtype, d in carried.items():
                rs = job.status.replica_statuses.setdefault(rtype, ReplicaStatus())
                rs.restarts += d
            self._restart_deltas[key] = dict(carried)
        with TRACER.span("phase", phase="claim"):
            pods = self.get_pods_for_job(job)
            services = self.get_services_for_job(job)

        # terminal: clean up and freeze (controller.go:362-389)
        if st.is_finished(job.status):
            self._delete_pods_and_services(job, pods, services)
            self._cleanup_ttl(job)
            if self.config.enable_gang_scheduling:
                self._delete_pod_group(job)
            self._persist_status(job, old_status)
            return True

        # backoff limit (controller.go:391-453, 520-556)
        exceeded, reason = self._past_backoff_limit(job, pods)
        if exceeded:
            return self._fail_job(job, old_status, pods, services,
                                  self._backoff_message(job, reason))
        if self._past_active_deadline(job):
            return self._fail_job(job, old_status, pods, services,
                                  f"TPUJob {job.metadata.name} has failed because it was "
                                  "active longer than specified deadline")

        if self.config.enable_gang_scheduling:
            self._sync_pod_group(job)

        if not st.get_condition(job.status, c.JOB_CREATED):
            st.update_job_conditions(
                job.status, c.JOB_CREATED, st.REASON_JOB_CREATED,
                f"TPUJob {job.metadata.name} is created.",
            )

        coord_rtype = tpu_env.coordinator_replica(job)
        for rtype, rspec in job.spec.tpu_replica_specs.items():
            typed_pods = self.filter_by_replica_type(pods, rtype)
            with TRACER.span("phase", phase="pod_diff", rtype=rtype):
                restarting = self._reconcile_pods(job, typed_pods, rtype, rspec, pods)
            if rtype == coord_rtype:
                # coordinator-only headless service (controller.go:474-477;
                # worker-0 coordinates master-less jobs)
                typed_svcs = self.filter_by_replica_type(services, rtype)
                with TRACER.span("phase", phase="service_diff", rtype=rtype):
                    self._reconcile_services(job, typed_svcs, rtype, rspec)
            self._update_status_single(job, rtype, rspec, restarting)

        # re-check the backoff limit with the counts updated THIS sync:
        # the entry check reads the informer-cached status, which can trail
        # the restart just counted — without this, event-ordering (pod
        # DELETED seen before the job's status MODIFIED) lets one extra
        # pod incarnation launch beyond the configured limit.  Guarded on
        # is_finished: a job whose completion-bearing replica succeeded
        # this very sync must not also be flipped to Failed.
        if not st.is_finished(job.status):
            exceeded, reason = self._past_backoff_limit(job, pods)
            if exceeded:
                return self._fail_job(job, old_status, pods, services,
                                      self._backoff_message(job, reason))

        self._persist_status(job, old_status)
        return True

    # ------------------------------------------------------------------
    # pods (pod.go:49-232)
    # ------------------------------------------------------------------

    def _reconcile_pods(self, job: TPUJob, pods: List[Pod], rtype: str, rspec,
                        all_pods: Optional[List[Pod]] = None) -> bool:
        replicas = rspec.replicas if rspec.replicas is not None else 1
        st.initialize_replica_statuses(job.status, rtype)
        slices = self.get_slices(pods, replicas)
        restarting = False
        missing: List[int] = []
        for index in range(replicas):
            pod_slice = slices[index]
            if len(pod_slice) > 1:
                logger_for_replica(log, job, rtype).warning(
                    "%d pods share index %d", len(pod_slice), index)
                continue
            if not pod_slice:
                missing.append(index)
                continue
            pod = pod_slice[0]
            # ExitCode restart policy (pod.go:91-109)
            if pod.status.phase == "Failed" and rspec.restart_policy == c.RESTART_POLICY_EXIT_CODE:
                code = self._managed_exit_code(pod)
                if code is not None and is_retryable_exit_code(code):
                    restarting = True
                    # deletion_timestamp guard: a pod stuck Terminating past
                    # the expectations TTL (finalizer, dead node) must stay
                    # in Restarting without being re-deleted and re-counted
                    # every sync — that would spuriously trip backoffLimit
                    if not pod.metadata.deletion_timestamp:
                        # count the restart decision in status: a recreated
                        # pod has restartCount 0, so without this a
                        # preemption loop is invisible and unbounded (vs
                        # controller.go:520-556 which only sees kubelet
                        # in-place restarts)
                        job.status.replica_statuses[rtype].restarts += 1
                        exceeded, _ = self._past_backoff_limit(
                            job, all_pods if all_pods is not None else pods)
                        if exceeded:
                            # this restart trips the limit: keep the final
                            # failed pod in place (its logs/events are the
                            # debugging evidence; cleanPodPolicy decides its
                            # fate at failure time) — the post-loop check
                            # fails the job this same sync.  NOT put on the
                            # delta ledger: the evidence pod survives, so a
                            # 409'd fail-write re-derives this count from
                            # the fresh cache instead of a rebase
                            # double-applying it every lagged sync
                            logger_for_pod(log, pod, job).info(
                                "retryable exit %d reaches the backoff "
                                "limit; failing job", code)
                        else:
                            logger_for_pod(log, pod, job).info(
                                "exited with retryable code %d; restarting",
                                code)
                            ekey = expectation_key(job.key, rtype, "pods")
                            self.expectations.expect(ekey, adds=0, dels=1)
                            self.flight.record(
                                job.key, "expectation",
                                f"raise +1 pod-delete expectation [{rtype}/{index}] "
                                f"(retryable exit {code})",
                                {"rtype": rtype, "index": index, "dels": 1})
                            try:
                                self.pod_control.delete_pod(
                                    pod.metadata.namespace,
                                    pod.metadata.name, job,
                                )
                            except NotFoundError:
                                # already gone (raced with node GC or a
                                # concurrent sync's delete): the intended
                                # outcome happened, so KEEP the count — but
                                # clear our expectation, whose DELETED
                                # event may have been observed before we
                                # registered it (it would otherwise gate
                                # syncs until the TTL)
                                self.expectations.observe_del(ekey)
                            except ServerTimeoutError:
                                # ambiguous 504: the delete may or may not
                                # have executed (lost response).  Rolling
                                # back would make an executed delete an
                                # UNCOUNTED, undamped restart — so keep the
                                # count, at-least-once style: if the pod
                                # survives, the retry sync re-deletes it and
                                # overcounts by one occurrence; if it is
                                # gone, the count is exactly right.  Clear
                                # our expectation either way (no DELETED
                                # event is guaranteed to arrive).
                                self.expectations.observe_del(ekey)
                            except Exception:
                                # the restart did not happen: roll back the
                                # count and the expectation so the retry
                                # sync re-derives exactly one restart from
                                # the still-present Failed pod
                                job.status.replica_statuses[rtype].restarts -= 1
                                self.expectations.observe_del(ekey)
                                raise
                            # ledger entry only after the delete executed:
                            # the delta survives a failed STATUS write, and
                            # the delete is what destroys the evidence pod
                            deltas = self._restart_deltas.setdefault(job.key, {})
                            deltas[rtype] = deltas.get(rtype, 0) + 1
                            self._note_restart(job.key, rtype, index)
                    # fall through: the failure still counts this sync, so the
                    # status machine emits Restarting (reference pod.go:91-109
                    # deletes async and the pod is still counted)
            st.update_replica_statuses(job.status, rtype, pod)
        if missing:
            waits = {i: self._restart_backoff_remaining(job.key, rtype, i)
                     for i in missing}
            delayed = [i for i in missing if waits[i] > 0]
            ready = [i for i in missing if waits[i] <= 0]
            if delayed:
                # crash-loop damper: only the striking replica waits out its
                # decayed exponential delay instead of relaunching at full
                # controller speed until backoffLimit; healthy siblings (the
                # `ready` set) are untouched
                wait = min(waits[i] for i in delayed)
                logger_for_replica(log, job, rtype).info(
                    "restart backoff: delaying replacement pod(s) %s for %.2fs",
                    delayed, wait)
                self.flight.record(
                    job.key, "backoff",
                    f"delaying replacement pod(s) {delayed} [{rtype}] "
                    f"for {wait:.2f}s",
                    {"rtype": rtype, "indices": delayed,
                     "wait_s": round(wait, 3)})
                self.queue.add_after(job.key, wait)
            if ready:
                # all unthrottled missing replicas of this type launch
                # concurrently (a v4-32 job's 8 hosts cost ~1 API round
                # trip, not 8 sequential ones)
                self._create_pods_batch(job, rtype, rspec, ready)
        return restarting

    def _note_restart(self, key: str, rtype: str, index: int) -> None:
        """Record a counted ExitCode restart in the crash-loop damper.

        First strike carries no delay (a single transient failure restarts
        promptly); each further strike doubles the wait, capped at the max.
        A replica that ran healthy well past its previous window decays back
        to a clean slate (the kubelet's CrashLoopBackOff resets the same
        way after a long enough run)."""
        base = self.config.restart_backoff_seconds
        if base <= 0:
            return
        max_delay = self.config.restart_backoff_max_seconds
        now = time.monotonic()
        strikes, last, _ = self._restart_backoff.get(
            (key, rtype, index), (0, 0.0, 0.0))
        # the healthy-run threshold is fixed (~2x the backoff cap; ~10 min at
        # the defaults, the kubelet's CrashLoopBackOff reset), NOT a multiple
        # of the previous strike's tiny delay — early strikes carry 0-delay
        # windows that any real crash cycle (schedule + start + crash) would
        # outlast, and the damper would never escalate
        if strikes and now - last > 2 * max_delay + base:
            strikes = 0
        strikes += 1
        delay = 0.0 if strikes == 1 else min(
            base * (2 ** min(strikes - 2, 30)), max_delay)
        self._restart_backoff[(key, rtype, index)] = (strikes, now, now + delay)
        self.flight.record(
            key, "backoff",
            f"restart strike {strikes} for {rtype}[{index}]: "
            f"next replacement delayed {delay:.2f}s",
            {"rtype": rtype, "index": index, "strikes": strikes,
             "delay_s": round(delay, 3)})

    def _restart_backoff_remaining(self, key: str, rtype: str, index: int) -> float:
        entry = self._restart_backoff.get((key, rtype, index))
        if entry is None:
            return 0.0
        return max(0.0, entry[2] - time.monotonic())

    def _create_pods_batch(self, job: TPUJob, rtype: str, rspec, indices: List[int]) -> None:
        """Slow-start parallel create with reference expectation bookkeeping
        (controller.go:430-470): raise for every intended create up front,
        lower for every create that did not happen — failed or skipped after
        a failing batch — then surface the first error to the workqueue."""
        ekey = expectation_key(job.key, rtype, "pods")
        pods = [self._build_pod(job, rtype, rspec, index) for index in indices]
        self.expectations.expect(ekey, adds=len(pods), dels=0)
        self.flight.record(
            job.key, "expectation",
            f"raise +{len(pods)} pod-create expectation(s) [{rtype}]",
            {"rtype": rtype, "adds": len(pods), "indices": list(indices)})
        with TRACER.span("phase", phase="slow_start_create", kind="pods",
                         count=len(pods)):
            created, err = self.pod_control.create_pods(
                job.metadata.namespace or "default", pods, job)
        for _ in range(len(pods) - created):
            self.expectations.observe_add(ekey)
        if err is not None:
            self.flight.record(
                job.key, "expectation",
                f"lower {len(pods) - created} unmet pod-create "
                f"expectation(s) [{rtype}]: {type(err).__name__}",
                {"rtype": rtype, "created": created, "intended": len(pods)})
            raise err

    @staticmethod
    def _managed_exit_code(pod: Pod) -> Optional[int]:
        for cs in pod.status.container_statuses:
            if cs.name == c.DEFAULT_CONTAINER_NAME and cs.state and cs.state.terminated:
                return cs.state.terminated.exit_code
        return None

    def _build_pod(self, job: TPUJob, rtype: str, rspec, index: int) -> Pod:
        """Render the pod for one replica index (no API writes)."""
        name = gen_general_name(job.metadata.name, rtype, index)
        template = rspec.template.deepcopy()
        labels = gen_labels(job.metadata.name)
        labels[c.LABEL_REPLICA_TYPE] = rtype.lower()
        labels[c.LABEL_REPLICA_INDEX] = str(index)
        template.metadata.labels.update(labels)
        pod = Pod(metadata=template.metadata, spec=template.spec)
        pod.metadata.name = name
        pod.metadata.namespace = job.metadata.namespace or "default"

        port = get_port_from_job(job, c.REPLICA_TYPE_MASTER
                                 if c.REPLICA_TYPE_MASTER in job.spec.tpu_replica_specs
                                 else rtype)
        tpu_env.set_cluster_spec(pod, job, rtype, index, port)
        self._set_restart_policy(pod, rspec)
        self._apply_tpu_scheduling(pod, rspec, job)

        # non-coordinator pods wait for the coordinator DNS
        # (pod.go:189-198, util.go:61-87); in master-less jobs worker-0 is
        # the coordinator and must not gate on itself
        is_coordinator = rtype == tpu_env.coordinator_replica(job) and index == 0
        if rtype == c.REPLICA_TYPE_WORKER and not is_coordinator:
            rendered = render_init_containers(
                tpu_env.coordinator_dns(job), self.config.init_container_image
            )
            pod.spec.init_containers.extend(Container.from_dict(d) for d in rendered)

        if self.config.enable_gang_scheduling:
            # scheduler name + PodGroup annotation (pod.go:200-216)
            if pod.spec.scheduler_name and pod.spec.scheduler_name != self.config.gang_scheduler_name:
                logger_for_replica(log, job, rtype).warning(
                    "pod %s scheduler %s overridden by gang scheduler %s",
                    name, pod.spec.scheduler_name, self.config.gang_scheduler_name)
            pod.spec.scheduler_name = self.config.gang_scheduler_name
            pod.metadata.annotations[c.POD_GROUP_ANNOTATION] = gen_pod_group_name(job.metadata.name)
        return pod

    @staticmethod
    def _set_restart_policy(pod: Pod, rspec) -> None:
        """ExitCode forces pod RestartPolicy Never so the controller, not the
        kubelet, owns the restart decision (pod.go:283-289)."""
        if rspec.restart_policy == c.RESTART_POLICY_EXIT_CODE:
            pod.spec.restart_policy = "Never"
        elif rspec.restart_policy:
            pod.spec.restart_policy = rspec.restart_policy

    @staticmethod
    def _apply_tpu_scheduling(pod: Pod, rspec, job: TPUJob) -> None:
        """TPU-first scheduling: google.com/tpu resource requests + GKE node
        selectors derived from the slice spec (the reference's GPU resource
        request analog, e.g. examples/.../pytorch_job_mnist_nccl.yaml:20-21)."""
        tpu = rspec.tpu
        if tpu is None or not tpu.accelerator:
            for other in job.spec.tpu_replica_specs.values():
                if other.tpu and other.tpu.accelerator:
                    tpu = other.tpu
                    break
        if tpu is None or not tpu.accelerator:
            return
        topo = tpu.resolve()
        pod.spec.node_selector.setdefault(c.TPU_ACCELERATOR_NODE_SELECTOR, topo.accelerator)
        pod.spec.node_selector.setdefault(c.TPU_TOPOLOGY_NODE_SELECTOR, topo.topology)
        for container in pod.spec.containers:
            if container.name != c.DEFAULT_CONTAINER_NAME:
                continue
            if container.resources is None:
                container.resources = ResourceRequirements()
            container.resources.limits.setdefault(c.TPU_RESOURCE, topo.chips_per_host)

    # ------------------------------------------------------------------
    # services (service.go:36-153)
    # ------------------------------------------------------------------

    def _reconcile_services(self, job: TPUJob, services: List[Service], rtype: str, rspec) -> None:
        replicas = 1  # master-only
        slices = self.get_slices(services, replicas)
        missing = [index for index in range(replicas) if not slices[index]]
        if missing:
            self._create_services_batch(job, rtype, missing)

    def _create_services_batch(self, job: TPUJob, rtype: str, indices: List[int]) -> None:
        """Mirror of _create_pods_batch for the headless service(s)."""
        ekey = expectation_key(job.key, rtype, "services")
        services = [self._build_service(job, rtype, index) for index in indices]
        self.expectations.expect(ekey, adds=len(services), dels=0)
        self.flight.record(
            job.key, "expectation",
            f"raise +{len(services)} service-create expectation(s) [{rtype}]",
            {"rtype": rtype, "adds": len(services)})
        with TRACER.span("phase", phase="slow_start_create", kind="services",
                         count=len(services)):
            created, err = self.service_control.create_services(
                job.metadata.namespace or "default", services, job)
        for _ in range(len(services) - created):
            self.expectations.observe_add(ekey)
        if err is not None:
            raise err

    def _build_service(self, job: TPUJob, rtype: str, index: int) -> Service:
        """Render the headless rendezvous service (no API writes)."""
        port = get_port_from_job(job, rtype)
        labels = gen_labels(job.metadata.name)
        labels[c.LABEL_REPLICA_TYPE] = rtype.lower()
        labels[c.LABEL_REPLICA_INDEX] = str(index)
        ports = [ServicePort(name=c.DEFAULT_PORT_NAME, port=port)]
        if tpu_env.is_multislice(job):
            # multislice: the DCN coordinator rides the same headless
            # service — declare its port by name so the injected
            # MEGASCALE_COORDINATOR_ADDRESS (host:MEGASCALE_PORT) matches
            # a named ServicePort (tpu_env.py contract)
            ports.append(ServicePort(name="megascale", port=tpu_env.MEGASCALE_PORT))
        return Service(
            metadata=ObjectMeta(
                name=gen_general_name(job.metadata.name, rtype, index),
                namespace=job.metadata.namespace or "default",
                labels=dict(labels),
            ),
            spec=ServiceSpec(
                cluster_ip="None",  # headless: DNS resolves to the pod IP
                selector=dict(labels),
                ports=ports,
            ),
        )

    # ------------------------------------------------------------------
    # status convergence (status.go:63-152)
    # ------------------------------------------------------------------

    def _update_status_single(self, job: TPUJob, rtype: str, rspec, restarting: bool) -> None:
        replicas = rspec.replicas if rspec.replicas is not None else 1
        rs = job.status.replica_statuses.get(rtype)
        if rs is None:
            return
        expected = replicas - rs.succeeded
        if job.status.start_time is None:
            job.status.start_time = st.now_iso()

        has_master = c.REPLICA_TYPE_MASTER in job.spec.tpu_replica_specs
        completion_bearing = (
            rtype == c.REPLICA_TYPE_MASTER
            or (not has_master and rtype == c.REPLICA_TYPE_WORKER)
        )
        if completion_bearing:
            if rs.active > 0:
                st.update_job_conditions(
                    job.status, c.JOB_RUNNING, st.REASON_JOB_RUNNING,
                    f"TPUJob {job.metadata.name} is running.",
                )
            if expected == 0:
                # master-completion semantics (status.go:99-112)
                self.recorder.event(job, "Normal", st.REASON_JOB_SUCCEEDED,
                                    f"TPUJob {job.metadata.name} successfully completed.")
                st.update_job_conditions(
                    job.status, c.JOB_SUCCEEDED, st.REASON_JOB_SUCCEEDED,
                    f"TPUJob {job.metadata.name} successfully completed.",
                )
                if job.status.completion_time is None:
                    job.status.completion_time = st.now_iso()
                metrics.jobs_successful.inc()
                return
        if rs.failed > 0:
            if restarting:
                # event + metric only on the TRANSITION into Restarting: a
                # pod stuck Terminating keeps restarting=True across many
                # syncs and must not spam events / inflate jobs_restarted
                newly_restarting = not st.has_condition(job.status, c.JOB_RESTARTING)
                if newly_restarting:
                    self.recorder.event(job, "Warning", st.REASON_JOB_RESTARTING,
                                        f"TPUJob {job.metadata.name} is restarting because "
                                        f"{rs.failed} {rtype} replica(s) failed.")
                st.update_job_conditions(
                    job.status, c.JOB_RESTARTING, st.REASON_JOB_RESTARTING,
                    f"TPUJob {job.metadata.name} is restarting because "
                    f"{rs.failed} {rtype} replica(s) failed.",
                )
                if newly_restarting:
                    metrics.jobs_restarted.inc()
            else:
                self.recorder.event(job, "Warning", st.REASON_JOB_FAILED,
                                    f"TPUJob {job.metadata.name} has failed because "
                                    f"{rs.failed} {rtype} replica(s) failed.")
                st.update_job_conditions(
                    job.status, c.JOB_FAILED, st.REASON_JOB_FAILED,
                    f"TPUJob {job.metadata.name} has failed because "
                    f"{rs.failed} {rtype} replica(s) failed.",
                )
                if job.status.completion_time is None:
                    job.status.completion_time = st.now_iso()
                metrics.jobs_failed.inc()

    # ------------------------------------------------------------------
    # failure paths (controller.go:391-453, 520-568)
    # ------------------------------------------------------------------

    def _past_backoff_limit(self, job: TPUJob, pods: List[Pod]) -> Tuple[bool, str]:
        limit = job.spec.run_policy.backoff_limit
        if limit is None:
            return False, ""
        restarts = 0
        for rtype, rspec in job.spec.tpu_replica_specs.items():
            if rspec.restart_policy in (c.RESTART_POLICY_ON_FAILURE, c.RESTART_POLICY_ALWAYS):
                # kubelet in-place restarts (controller.go:527-533)
                for pod in self.filter_by_replica_type(pods, rtype):
                    for cs in pod.status.container_statuses:
                        restarts += cs.restart_count
            elif rspec.restart_policy == c.RESTART_POLICY_EXIT_CODE:
                # controller-driven recreations, accumulated in status —
                # bounds the TPU-preemption churn loop the reference
                # cannot see (it only counts restartCount, which is 0 on
                # every recreated pod)
                rs = job.status.replica_statuses.get(rtype)
                if rs is not None:
                    restarts += rs.restarts
        if restarts >= limit:
            return True, f"total restart count {restarts} >= backoffLimit {limit}"
        return False, ""

    @staticmethod
    def _backoff_message(job: TPUJob, reason: str) -> str:
        return (f"TPUJob {job.metadata.name} has failed because it has "
                f"reached the specified backoff limit ({reason})")

    def _past_active_deadline(self, job: TPUJob) -> bool:
        ads = job.spec.run_policy.active_deadline_seconds
        start = _parse_time(job.status.start_time)
        if ads is None or start is None:
            return False
        return time.time() - start >= ads

    def _fail_job(self, job: TPUJob, old_status, pods, services, message: str) -> bool:
        logger_for_job(log, job).info(message)
        self._delete_pods_and_services(job, pods, services)
        self.recorder.event(job, "Warning", st.REASON_JOB_FAILED, message)
        if job.status.completion_time is None:
            job.status.completion_time = st.now_iso()
        st.update_job_conditions(job.status, c.JOB_FAILED, st.REASON_JOB_FAILED, message)
        metrics.jobs_failed.inc()
        if self.config.enable_gang_scheduling:
            self._delete_pod_group(job)
        self._persist_status(job, old_status)
        return True

    # ------------------------------------------------------------------
    # cleanup (job.go:153-209)
    # ------------------------------------------------------------------

    def _delete_pods_and_services(self, job: TPUJob, pods: List[Pod], services: List[Service]) -> None:
        policy = job.spec.run_policy.clean_pod_policy or c.CLEAN_POD_POLICY_NONE
        if policy == c.CLEAN_POD_POLICY_NONE:
            return
        for pod in pods:
            # Running policy deletes only phase==Running pods (job.go:165 —
            # exact reference semantics: terminal AND Pending/Unknown pods
            # stay for debugging).  Beyond the reference: a pod already
            # carrying a deletionTimestamp is not re-deleted.
            if policy == c.CLEAN_POD_POLICY_RUNNING and (
                pod.status.phase != "Running" or pod.metadata.deletion_timestamp
            ):
                continue
            try:
                self.pod_control.delete_pod(pod.metadata.namespace, pod.metadata.name, job)
            except NotFoundError:
                pass
        for svc in services:
            try:
                self.service_control.delete_service(svc.metadata.namespace, svc.metadata.name, job)
            except NotFoundError:
                pass

    def _cleanup_ttl(self, job: TPUJob) -> None:
        ttl = job.spec.run_policy.ttl_seconds_after_finished
        if ttl is None:
            return
        finish = _parse_time(job.status.completion_time)
        if finish is None:
            if job.status.completion_time:
                # CORRUPTED completion_time: it can never be measured
                # against the TTL, but re-anchoring to the current time on
                # every sync would requeue every ttl seconds forever and
                # never collect the job.  Anchor at the server-set
                # creationTimestamp instead — collection stays guaranteed
                # and bounded without reaping a long TTL early on one bad
                # write.  If even that is garbage, the object is junk: reap.
                finish = _parse_time(job.metadata.creation_timestamp)
                if finish is None:
                    finish = float("-inf")
            else:
                # no timestamp landed yet: anchor at first observation
                finish = time.time()
        remaining = finish + ttl - time.time()
        if remaining <= 0:
            try:
                self.delete_job_handler(job)
            except NotFoundError:
                pass
        else:
            self.queue.add_after(job.key, remaining)

    # ------------------------------------------------------------------
    # gang scheduling (jobcontroller.go:224-278)
    # ------------------------------------------------------------------

    def _sync_pod_group(self, job: TPUJob) -> None:
        name = gen_pod_group_name(job.metadata.name)
        ns = job.metadata.namespace or "default"
        min_member = get_total_replicas(job)
        sp = job.spec.run_policy.scheduling_policy
        if sp and sp.min_available is not None:
            min_member = sp.min_available
        try:
            existing = self.clients.podgroups.get(ns, name)
            if existing.spec.min_member != min_member:
                existing.spec.min_member = min_member
                self.clients.podgroups.update(existing)
        except NotFoundError:
            pg = PodGroup(
                metadata=ObjectMeta(name=name, namespace=ns, labels=gen_labels(job.metadata.name)),
                spec=PodGroupSpec(min_member=min_member,
                                  queue=sp.queue if sp else None,
                                  priority_class_name=sp.priority_class if sp else None),
            )
            from tpujob.kube.control import gen_owner_reference

            pg.metadata.owner_references.append(gen_owner_reference(job))
            self.clients.podgroups.create(pg)

    def _delete_pod_group(self, job: TPUJob) -> None:
        try:
            self.clients.podgroups.delete(job.metadata.namespace or "default",
                                          gen_pod_group_name(job.metadata.name))
        except NotFoundError:
            pass

    # ------------------------------------------------------------------
    # write-back handlers (injectable for tests)
    # ------------------------------------------------------------------

    def _persist_status(self, job: TPUJob, old_status) -> None:
        """Persist the sync's recomputed status iff it changed.

        ``old_status`` is the informer-cached status snapshotted at sync
        start: when the recomputed object equals it field for field, the
        sync was a pure no-op and nothing is written (counted as
        suppressed).  Anything else goes through the injectable
        ``update_status_handler``, where the semantic diff decides between
        a merge-patch write and suppression of volatile-only refreshes."""
        if job.status == old_status:
            if self.config.suppress_noop_status:
                metrics.status_writes.labels(result="suppressed").inc()
            return
        self.update_status_handler(job)

    def _update_job_status(self, job: TPUJob) -> None:
        with TRACER.span("phase", phase="status_update"):
            self._write_job_status(job)

    def _write_job_status(self, job: TPUJob) -> None:
        deltas = self._restart_deltas.pop(job.key, None)
        if self.config.status_patch and hasattr(
            self.clients.tpujobs.server, "patch_status"
        ):
            self._patch_job_status(job, deltas)
        else:
            self._put_job_status(job, deltas)

    # -- merge-patch write path (the default) ---------------------------

    def _patch_job_status(self, job: TPUJob, deltas: Optional[Dict[str, int]]) -> None:
        """Ship the semantic diff between the recomputed status and the
        informer-cached one as a JSON-merge-patch of /status.

        Three write classes fall out of the diff:

        - **empty diff** — the sync re-derived exactly what the cache (and
          therefore, to our best knowledge, the server) already holds: skip
          the write entirely (``status_writes_total{result="suppressed"}``).
          Terminal transitions (Succeeded/Failed first landing) always
          write through, and a cache that drifted from the recomputed truth
          (a resync repairing a foreign/corrupt status write) diffs nonzero
          by construction — suppression can never swallow either.
        - **derived-fields-only diff** — conditions, phase counters,
          timestamps: patched WITHOUT a resourceVersion precondition.
          Last-writer-wins per key is safe (every such field is recomputed
          from live pods each sync), and the patch no longer 409s against
          concurrent spec/metadata writers the way the full-object PUT did —
          that conflict/refetch/retry loop was pure overhead.
        - **cumulative-counter diff** (``restarts``) — history, not derived
          state: patched WITH the cached resourceVersion.  On conflict the
          executed deletions are rebased onto the freshly read object via a
          restarts-only RV-checked patch (client-go RetryOnConflict
          discipline), never a blind full-object write that could resurrect
          this sync's stale view of everything else.
        """
        ns = job.metadata.namespace or "default"
        name = job.metadata.name
        cached = self.job_informer.store.get(ns, name)
        if not self._same_incarnation(cached, job):
            # the cache now holds a DIFFERENT incarnation of ns/name (the
            # job was deleted and recreated mid-sync): this sync's status —
            # terminal conditions, restart counts — belongs to the dead
            # object and must not be born onto the new one.  The full-object
            # PUT got this protection for free (it carried the dead
            # incarnation's resourceVersion and 409/404'd); the patch path
            # must check identity itself.  The deltas die with the old
            # incarnation, exactly like the NotFound path.
            logger_for_job(log, job).info(
                "job was recreated mid-sync; dropping the stale status write")
            return
        old = (cached or {}).get("status")
        old = old if isinstance(old, dict) else {}
        patch = st.status_merge_patch(old, job.status.to_dict())
        if patch is None:
            # a semantically empty diff can never hide a condition
            # transition (terminal ones included): is_finished depends only
            # on condition type/status, which the volatile strip preserves —
            # equality here implies the cache already shows the same
            # terminal/non-terminal state
            if self.config.suppress_noop_status:
                # the cached status already reflects everything this sync
                # computed — including any carried restart deltas, which are
                # therefore persisted; dropping them here is what retires a
                # delta whose lost-response write actually landed
                metrics.status_writes.labels(result="suppressed").inc()
                return
            # suppression disabled: write the volatile-only drift too, so
            # the cache converges the way it did under a full PUT — a
            # stamp-only patch would leave the refreshed condition
            # timestamps un-persisted and the object-equality gate upstream
            # dirty on every subsequent sync
            patch = st.raw_status_merge_patch(old, job.status.to_dict())
        job.status.last_reconcile_time = st.now_iso()
        patch["lastReconcileTime"] = job.status.last_reconcile_time
        rv = None
        if st.patch_touches_restarts(patch):
            rv = ((cached or {}).get("metadata") or {}).get("resourceVersion")
        try:
            self.clients.tpujobs.patch_status(ns, name, patch, resource_version=rv)
        except NotFoundError:
            return
        except ConflictError:
            logger_for_job(log, job).info(
                "status patch conflicted (stale cache); requeueing")
        except Exception:
            # transient transport failure: the recreations of this sync are
            # already executed — re-stash their deltas so the next sync
            # folds them in instead of silently undercounting
            self._restash_deltas(job, deltas)
            raise
        else:
            self._count_patch_write(patch, job.status.to_dict())
            return
        if deltas:
            self._rebase_restart_deltas(job, deltas)
        # rate-limited, not immediate: the cache stays stale for the whole
        # watch-latency window after the conflicting write, so an immediate
        # requeue would spin patch-409 against the apiserver (client-go
        # RetryOnConflict backs off the same way)
        self.queue.add_rate_limited(job.key)

    @staticmethod
    def _same_incarnation(cached: Optional[Dict], job: TPUJob) -> bool:
        """Whether ``cached`` (the informer's current ns/name entry) is the
        same object incarnation the sync was computed for.  A store miss
        passes — the server's 404 resolves it; missing uids (hand-built test
        objects) pass open."""
        if cached is None:
            return True
        cached_uid = (cached.get("metadata") or {}).get("uid")
        return (not cached_uid or not job.metadata.uid
                or cached_uid == job.metadata.uid)

    @staticmethod
    def _count_patch_write(patch: Dict[str, Any], full: Dict[str, Any]) -> None:
        metrics.status_writes.labels(result="written").inc()
        metrics.status_patch_bytes.inc(
            len(json.dumps(patch, separators=(",", ":"))))
        metrics.status_full_bytes.inc(
            len(json.dumps(full, separators=(",", ":"))))

    def _rebase_restart_deltas(self, job: TPUJob, deltas: Dict[str, int]) -> None:
        """A conflicted restarts write: refetch the fresh object, fold the
        executed deletions onto ITS counters, and ship a restarts-only
        RV-checked patch.  Every other status field is recomputed from pods
        on the requeued sync anyway — writing it from this sync's stale base
        would resurrect exactly the stale fields the 409 protected."""
        ns = job.metadata.namespace or "default"
        name = job.metadata.name
        try:
            for _ in range(3):
                try:
                    fresh = self.clients.tpujobs.get(ns, name)
                except NotFoundError:
                    deltas = None  # job gone: nothing left to count
                    return
                if (job.metadata.uid and fresh.metadata.uid
                        and fresh.metadata.uid != job.metadata.uid):
                    # ns/name was deleted and recreated: the counted
                    # restarts belong to the dead incarnation — folding them
                    # onto the newborn would trip its backoffLimit early
                    deltas = None
                    return
                rebase: Dict[str, Any] = {"replicaStatuses": {}}
                for rtype, d in deltas.items():
                    rs = fresh.status.replica_statuses.get(rtype)
                    base = rs.restarts if rs is not None else 0
                    rebase["replicaStatuses"][rtype] = {"restarts": base + d}
                try:
                    self.clients.tpujobs.patch_status(
                        ns, name, rebase,
                        resource_version=fresh.metadata.resource_version)
                    self._count_patch_write(rebase, fresh.status.to_dict())
                    deltas = None
                    return
                except NotFoundError:
                    deltas = None
                    return
                except ConflictError:
                    continue
        finally:
            # rebase exhausted or died mid-flight (transient transport
            # error): keep the ledger for the next sync
            self._restash_deltas(job, deltas)

    # -- full-object PUT path (status_patch=False, and transports without
    #    the patch verb) --------------------------------------------------

    def _put_job_status(self, job: TPUJob, deltas: Optional[Dict[str, int]]) -> None:
        job.status.last_reconcile_time = st.now_iso()
        try:
            self.clients.tpujobs.update_status(job)
            metrics.status_writes.labels(result="written").inc()
            return
        except NotFoundError:
            return
        except ConflictError:
            # stale informer cache (409 via the RV the status write carries):
            # do NOT clobber the newer status — but the restart increments
            # of THIS sync count real pod deletions that already executed,
            # so rebase them onto the fresh object before requeueing
            # (client-go RetryOnConflict discipline); everything else is
            # recomputed from pods on the requeued sync anyway
            logger_for_job(log, job).info(
                "status write conflicted (stale cache); requeueing")
        except Exception:
            # transient transport failure: the recreations of this sync are
            # already executed — re-stash their deltas so the next sync
            # folds them in instead of silently undercounting
            self._restash_deltas(job, deltas)
            raise
        if deltas:
            try:
                for _ in range(3):
                    try:
                        fresh = self.clients.tpujobs.get(
                            job.metadata.namespace or "default", job.metadata.name)
                    except NotFoundError:
                        deltas = None  # job gone: nothing left to count
                        return
                    if (job.metadata.uid and fresh.metadata.uid
                            and fresh.metadata.uid != job.metadata.uid):
                        deltas = None  # recreated under the same name
                        return
                    for rtype, d in deltas.items():
                        rs = fresh.status.replica_statuses.setdefault(rtype, ReplicaStatus())
                        rs.restarts += d
                    try:
                        self.clients.tpujobs.update_status(fresh)
                        metrics.status_writes.labels(result="written").inc()
                        deltas = None
                        break
                    except NotFoundError:
                        deltas = None
                        return
                    except ConflictError:
                        continue
            finally:
                # rebase exhausted or died mid-flight (transient transport
                # error): keep the ledger for the next sync
                self._restash_deltas(job, deltas)
        # rate-limited, not immediate: the cache stays stale for the whole
        # watch-latency window after the conflicting write, so an immediate
        # requeue would spin PUT-409 against the apiserver (client-go
        # RetryOnConflict backs off the same way)
        self.queue.add_rate_limited(job.key)

    def _restash_deltas(self, job: TPUJob, deltas: Optional[Dict[str, int]]) -> None:
        """Put unpersisted restart deltas back on the ledger — unless the job
        is gone from the informer cache: racing _on_job_delete's cleanup
        would leave a phantom entry that poisons a future job recreated
        under the same namespace/name."""
        if not deltas:
            return
        if self.job_informer.store.get(
                job.metadata.namespace or "default", job.metadata.name) is None:
            return
        self._restart_deltas[job.key] = deltas

    def _delete_job(self, job: TPUJob) -> None:
        self.clients.tpujobs.delete(job.metadata.namespace or "default", job.metadata.name)
        self.recorder.event(job, "Normal", "SuccessfulDeleteJob",
                            f"Deleted job: {job.metadata.name}")
