"""The TPUJob reconciler.

Mirrors the reference's reconcile heart, behavior-for-behavior, with TPU
cluster-spec injection in place of the NCCL wiring:

- ``syncTPUJob``/``reconcileTPUJobs`` — ``pkg/controller.v1/pytorch/controller.go:290-492``
- pod reconcile + ExitCode restart — ``pod.go:49-232`` + ``pod.go:91-109``
- master-only headless service — ``service.go:36-153``, ``controller.go:474-477``
- status convergence — ``status.go:63-152``
- terminal cleanup / CleanPodPolicy / TTL — ``job.go:153-209``
- backoff limit / active deadline — ``controller.go:391-461,520-568``
- gang scheduling PodGroup — ``jobcontroller.go:224-278``
"""
from __future__ import annotations

import json
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from tpujob.api import constants as c
from tpujob.api.defaults import set_defaults_tpujob
from tpujob.api.progress import parse_progress
from tpujob.api.quota import gang_request
from tpujob.api.topology import TopologyError
from tpujob.api.types import ReplicaStatus, ResizeStatus, TPUJob
from tpujob.api.validation import validate_tpujob_spec
from tpujob.controller import barrier
from tpujob.controller import status as st
from tpujob.controller import tpu_env
from tpujob.controller.config import render_init_containers
from tpujob.controller.progress import (
    EVENT_ADVANCE,
    EVENT_CHECKPOINT,
    EVENT_FIRST,
    JobProgress,
    ProgressTracker,
)
from tpujob.controller.joblogger import (
    logger_for_job,
    logger_for_key,
    logger_for_pod,
    logger_for_replica,
    logger_for_unstructured,
)
from tpujob.controller.job_base import JobController, _DedupWarner, expectation_key
from tpujob.kube.client import RESOURCE_TPUJOBS
from tpujob.obs import goodput as gp
from tpujob.kube.control import gen_general_name, gen_labels, gen_pod_group_name
from tpujob.kube.errors import ConflictError, NotFoundError, ServerTimeoutError
from tpujob.kube.objects import (
    Container,
    ObjectMeta,
    Pod,
    PodGroup,
    PodGroupSpec,
    ResourceRequirements,
    Service,
    ServicePort,
    ServiceSpec,
)
from tpujob.obs.trace import TRACER
from tpujob.runtime import is_retryable_exit_code
from tpujob.server import metrics

log = logging.getLogger("tpujob.reconciler")


_time_warner = _DedupWarner(interval=60.0)


def _parse_time(ts: Optional[str]) -> Optional[float]:
    """st.parse_iso plus the reconciler's rate-limited warning: a corrupt
    ``start_time``/``completion_time`` degrades the affected feature
    (deadline/TTL) but should still be heard about once a minute."""
    t = st.parse_iso(ts)
    if t is None and ts:
        _time_warner.warning(
            log, ("unparseable-timestamp", ts),
            "unparseable status timestamp %r; treating as unset", ts)
    return t


def get_port_from_job(job: TPUJob, rtype: str) -> int:
    """Coordinator port lookup (util.go:34-47)."""
    rspec = job.spec.tpu_replica_specs.get(rtype)
    if rspec:
        for container in rspec.template.spec.containers:
            if container.name == c.DEFAULT_CONTAINER_NAME:
                for port in container.ports:
                    if port.name == c.DEFAULT_PORT_NAME:
                        return port.container_port
    return c.DEFAULT_PORT


def get_total_replicas(job: TPUJob) -> int:
    return sum(
        (r.replicas if r.replicas is not None else 1)
        for r in job.spec.tpu_replica_specs.values()
    )


def _replica_index(pod: Pod) -> Optional[int]:
    try:
        return int(pod.metadata.labels.get(c.LABEL_REPLICA_INDEX))
    except (TypeError, ValueError):
        return None


def _pod_env_world(pod: Pod) -> Optional[int]:
    """The world size this pod was BORN into — its injected
    ``TPUJOB_NUM_PROCESSES``.  Pod env is bootstrap-only, so live pods are
    the durable record of the last world they rendezvoused at before the
    controller ever published an annotation (the first resize of a job has
    no annotation to read)."""
    for container in pod.spec.containers:
        if container.name != c.DEFAULT_CONTAINER_NAME:
            continue
        for env in container.env:
            if env.name == "TPUJOB_NUM_PROCESSES":
                try:
                    return int(env.value)
                except (TypeError, ValueError):
                    return None
    return None


class TPUJobController(JobController):
    """The operator's reconcile loop over TPUJob resources."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.job_informer.on_add(self._on_job_add)
        self.job_informer.on_update(self._on_job_update)
        self.job_informer.on_delete(self._on_job_delete)
        # injectable handlers for tests (controller.go:81-89)
        self.update_status_handler = self._update_job_status
        self.delete_job_handler = self._delete_job
        # restart increments made by the CURRENT sync, keyed by job key:
        # consumed by _update_job_status to rebase the cumulative counter
        # onto the fresh object when the status write hits 409 (a stale
        # informer cache must not swallow a counted recreation).  Safe
        # across worker threads: the workqueue never runs one key twice
        # concurrently, and keys don't share entries.
        self._restart_deltas: Dict[str, Dict[str, int]] = {}
        # per-(job key, rtype, replica index) crash-loop damper: (strikes,
        # last strike monotonic, not-before monotonic).  Keyed per index so
        # one crash-looping replica never delays a healthy sibling's
        # replacement.  Written only by the worker holding the job's
        # workqueue key (same safety argument as _restart_deltas above).
        self._restart_backoff: Dict[Tuple[str, str, int], Tuple[int, float, float]] = {}
        # elastic-resize duration anchors (job key -> monotonic staging
        # start).  Best-effort observability only: the durable anchor is
        # status.resize.startedAt; this one just keeps the duration metric
        # off the wall clock.  Same single-writer-per-key safety argument.
        self._resize_started_mono: Dict[str, float] = {}
        # workload telemetry plane: per-job progress-heartbeat state ingested
        # from the informer-cached pod annotations (never an extra API read)
        # + the stall watchdog's monotonic deadline clocks.  Reconstructed,
        # not durable — a restarted controller (or a rebalanced-in shard
        # owner) re-seeds from the annotations still on the cluster and
        # grants one full stall deadline, the damper-rebuild stance.
        self.telemetry = ProgressTracker()
        # goodput accounting plane: the per-job phase ledger attributing
        # every second of a job's life to one of the ten phases, from
        # signals this sync already derived (conditions, annotations, pods,
        # heartbeat events) — controller-monotonic-anchored, reconstructed
        # not durable (a cold start / rebalanced-in owner re-seeds the
        # pre-history coarsely from the condition timestamps), and dropped
        # with the telemetry state across the shard drain barrier so
        # exactly one member ever accounts for a job.
        self.goodput = gp.GoodputLedger()
        # the status snapshot THIS sync was computed from, stashed for the
        # write path's diff (job key -> JobStatus; same single-writer-per-
        # key safety as _restart_deltas).  The patch diff must use the
        # sync-start base, never a write-time cache re-read — see
        # _patch_job_status.
        self._sync_status_base: Dict[str, Any] = {}

    # ------------------------------------------------------------------
    # cold-start recovery (crash-only controller semantics)
    # ------------------------------------------------------------------

    def on_caches_synced(self) -> None:
        """Reconstruct in-memory ledgers from durable state after a (re)start.

        The crash-loop damper (`_restart_backoff`) dies with the process; a
        fresh controller starting at zero would prompt-restart every
        crash-looping replica at full speed — a restart storm each time the
        CONTROLLER itself crash-loops.  Rebuild it conservatively from
        ``status.replicaStatuses[].restarts`` (durable, cumulative) anchored
        at the newest condition transition timestamp.  Over-delaying is safe:
        the damper only gates REPLACEMENT of missing pods, so a healthy
        running replica is never touched.
        """
        seeded = self._rebuild_restart_backoff()
        if seeded:
            from tpujob.obs.recorder import CONTROLLER_TIMELINE_KEY

            self.flight.record(
                CONTROLLER_TIMELINE_KEY, "coldstart",
                f"restart-backoff damper reconstructed from status for "
                f"{seeded} replica type(s)",
                {"stage": "damper_rebuild", "seeded": seeded})

    def prepare_shard(self, shard: int) -> None:
        """Shard-acquisition hook (pre-activation): rebuild the crash-loop
        damper for the shard's jobs from durable status BEFORE any worker
        can sync them.  A rebalanced-in shard must not prompt-restart a
        crash-looping job it just inherited — the previous owner's damper
        died with its ownership, exactly as a cold-started controller's
        damper dies with its process, and the cold-start rebuild only ran
        for the shards owned back then."""
        seeded = self._rebuild_restart_backoff(shard=shard)
        if seeded:
            from tpujob.obs.recorder import CONTROLLER_TIMELINE_KEY

            self.flight.record(
                CONTROLLER_TIMELINE_KEY, "shard",
                f"shard {shard}: restart-backoff damper reconstructed from "
                f"status for {seeded} replica type(s)",
                {"shard": shard, "seeded": seeded})

    def on_shard_acquired(self, shard: int) -> None:
        """One combined post-activation pass over the inherited shard's
        jobs (a rebalance storm acquires many shards back to back, and each
        extra full-store scan rides the coordinator thread that also
        heartbeats under a sub-second soak lease): enqueue the replay AND
        re-arm the ActiveDeadlineSeconds requeues — the add_after the
        previous owner scheduled at job creation died with it, and at the
        production 12h resync a deadline could otherwise slip by hours
        before the next event surfaces it."""
        enqueued = 0
        for obj in self.job_informer.store.list():
            if self._shard_of_obj(obj) != shard:
                continue
            self.enqueue_job(self.job_key_of(obj))
            enqueued += 1
            try:
                job = TPUJob.from_dict(obj)
                set_defaults_tpujob(job)
            except (TypeError, ValueError):
                continue  # malformed CR: the enqueue replay's sync reports it
            if st.is_finished(job.status):
                continue
            ads = job.spec.run_policy.active_deadline_seconds
            if ads is None or ads < 0:
                continue
            started = _parse_time(job.status.start_time)
            # wall-vs-persisted-timestamp math like _past_active_deadline
            # (the two baselined TPL004 sites): status.startTime was written
            # by another process's wall clock, so monotonic cannot compare
            remaining = (float(ads) if started is None
                         else max(0.0, started + float(ads) - time.time()))  # noqa: TPL004
            self.queue.add_after(job.key, remaining)
        from tpujob.obs.recorder import CONTROLLER_TIMELINE_KEY

        self.flight.record(
            CONTROLLER_TIMELINE_KEY, "shard",
            f"shard {shard} acquired: {enqueued} cached job(s) enqueued",
            {"shard": shard, "jobs": enqueued})

    def _rebuild_restart_backoff(self, shard: Optional[int] = None) -> int:
        base = self.config.restart_backoff_seconds
        if base <= 0:
            return 0
        max_delay = self.config.restart_backoff_max_seconds
        now_mono, now_wall = time.monotonic(), time.time()
        seeded = 0
        for obj in self.job_informer.store.list():
            if shard is not None and self._shard_of_obj(obj) != shard:
                continue  # per-shard rebuild: only the acquired shard's jobs
            try:
                job = TPUJob.from_dict(obj)
                set_defaults_tpujob(job)
            except (TypeError, ValueError):
                continue  # malformed CR: the sync path reports it
            if st.is_finished(job.status):
                continue
            # anchor at the newest condition transition — the closest durable
            # proxy for "when the last counted restart happened"
            last_wall = max(
                (t for t in (_parse_time(cond.last_transition_time)
                             for cond in job.status.conditions) if t is not None),
                default=None,
            )
            for rtype, rspec in job.spec.tpu_replica_specs.items():
                if rspec.restart_policy != c.RESTART_POLICY_EXIT_CODE:
                    continue
                rs = job.status.replica_statuses.get(rtype)
                restarts = rs.restarts if rs is not None else 0
                if restarts <= 0:
                    continue
                strikes = min(restarts, 32)
                delay = 0.0 if strikes == 1 else min(
                    base * (2 ** min(strikes - 2, 30)), max_delay)
                # condition times are wall clock; the damper runs on the
                # monotonic clock — translate, clamping to "just now" if the
                # timestamp is in the future (clock skew)
                last_mono = (now_mono if last_wall is None
                             else now_mono - max(0.0, now_wall - last_wall))
                not_before = last_mono + delay
                # restarts are per-type, not per-index: seed every index
                # (conservative — only replacements of MISSING pods wait)
                replicas = rspec.replicas if rspec.replicas is not None else 1
                for index in range(replicas):
                    self._restart_backoff[(job.key, rtype, index)] = (
                        strikes, last_mono, not_before)
                seeded += 1
                self.flight.record(
                    job.key, "backoff",
                    f"cold start: damper reconstructed for {rtype} from "
                    f"status ({restarts} counted restart(s) -> strikes="
                    f"{strikes}, replacement delay {delay:.2f}s)",
                    {"rtype": rtype, "restarts": restarts, "strikes": strikes,
                     "delay_s": round(delay, 3)})
        return seeded

    # ------------------------------------------------------------------
    # job event handlers (job.go:35-149)
    # ------------------------------------------------------------------

    def _on_job_add(self, obj: Dict) -> None:
        key = self.job_key_of(obj)
        shard = self._shard_of_obj(obj)
        if (self.sharder is not None and shard is not None
                and not self.sharder.is_active(shard)):
            # another member's shard: its owner enqueues, schedules the
            # deadline requeue, and reports malformation — doing any of it
            # here would double the work (and the writes) fleet-wide
            return
        try:
            job = TPUJob.from_dict(obj)
            set_defaults_tpujob(job)
            errs = validate_tpujob_spec(job.spec, strict_topology=True)
        except (TypeError, ValueError) as e:
            errs = [str(e)]
            job = None
        if errs:
            # malformed CR: write a Failed condition back instead of crashing
            # (job.go:60-111 / informer.go:83-104 tolerance semantics).  The
            # shard context fences the write on this shard's lease.
            with self._shard_call_context(shard):
                self._fail_malformed(obj, errs)
            return
        metrics.jobs_created.inc()
        self.enqueue_job(key)
        # ActiveDeadlineSeconds: re-enqueue at the deadline (job.go:133-149)
        ads = job.spec.run_policy.active_deadline_seconds
        if ads is not None and ads >= 0:
            self.queue.add_after(key, float(ads))

    def _on_job_update(self, old: Dict, new: Dict) -> None:
        if (old.get("metadata") or {}).get("resourceVersion") == (
            (new.get("metadata") or {}).get("resourceVersion")
        ):
            return  # periodic resync replay, nothing changed
        key = self.job_key_of(new)
        old_gen = int((old.get("metadata") or {}).get("generation") or 0)
        new_gen = int((new.get("metadata") or {}).get("generation") or 0)
        if new_gen and new_gen != old_gen:
            # spec change (generation bump): enqueue IMMEDIATELY, bypassing
            # the settle window — a resize must not ride an already-
            # scheduled coalesced sync's latency, and the timeline event
            # lets the flight recorder distinguish spec changes from the
            # status churn that dominates MODIFIED traffic
            if self._owns_key(key):  # sharded: only the owner's timeline
                self.flight.record(
                    key, "spec",
                    f"spec generation {old_gen} -> {new_gen} "
                    "(replicas/runPolicy changed)",
                    {"from": old_gen, "to": new_gen})
            self.enqueue_job(key)
            return
        # coalesced: most job MODIFIED events are the echo of our own status
        # writes, and they burst together with the pod events of the same
        # reconcile round — one settled sync covers them all
        self.enqueue_job_event(key)

    def _on_job_delete(self, obj: Dict) -> None:
        metrics.jobs_deleted.inc()
        key = self.job_key_of(obj)
        self._restart_deltas.pop(key, None)  # no leak; no carry-over to a
        # future job recreated under the same namespace/name
        self._resize_started_mono.pop(key, None)  # same hygiene
        self.telemetry.forget(key)  # drops the tpujob_job_* series too
        self.goodput.forget(key)  # drops the goodput series too
        for rtype in (c.REPLICA_TYPE_MASTER, c.REPLICA_TYPE_WORKER):
            self.expectations.delete(expectation_key(key, rtype, "pods"))
            self.expectations.delete(expectation_key(key, rtype, "services"))
        # pop in place (like _restart_deltas above) rather than rebinding a
        # rebuilt dict: a rebind would silently drop a concurrent worker
        # thread's _note_restart write for an unrelated job.  The snapshot
        # is list(dict) — a single C-level op that cannot interleave with a
        # worker's insert the way per-item comprehension iteration can.
        for k in list(self._restart_backoff):
            if k[0] == key:
                self._restart_backoff.pop(k, None)

    def _fail_malformed(self, obj: Dict, errs: List[str]) -> None:
        meta = obj.get("metadata") or {}
        ns, name = meta.get("namespace") or "default", meta.get("name")
        logger_for_unstructured(log, obj).warning("invalid TPUJob: %s", errs)
        # write back through the raw transport: the typed client would choke
        # on the very malformation we are reporting (job.go:60-111 uses the
        # raw CRD REST client for the same reason)
        from tpujob.api.types import JobStatus

        status = JobStatus.from_dict(obj.get("status") if isinstance(obj.get("status"), dict) else {})
        message = f"TPUJob {name} is invalid: " + "; ".join(errs)
        existing = st.get_condition(status, c.JOB_FAILED)
        if existing is not None and existing.status == "True" and existing.message == message:
            return  # already reported: avoid a write->watch->sync busy loop
        st.update_job_conditions(status, c.JOB_FAILED, st.REASON_JOB_FAILED, message)
        try:
            self.clients.server.update_status(
                RESOURCE_TPUJOBS,
                {"metadata": {"namespace": ns, "name": name}, "status": status.to_dict()},
            )
        except NotFoundError:
            return
        metrics.jobs_failed.inc()

    # ------------------------------------------------------------------
    # sync (controller.go:290-332)
    # ------------------------------------------------------------------

    def sync_handler(self, key: str) -> bool:
        with TRACER.span("phase", phase="cache_get"):
            ns, _, name = key.partition("/")
            cached = self.job_informer.store.get(ns, name)
            if cached is None:
                logger_for_key(log, key).info("job no longer exists")
                return True
            try:
                job = TPUJob.from_dict(cached)
                set_defaults_tpujob(job)
                # strict topology: a replicas-vs-slice mismatch cannot be
                # env-injected coherently, so it must fail visibly instead
                # of looping
                errs = validate_tpujob_spec(job.spec, strict_topology=True)
            except (TypeError, ValueError) as e:
                job, errs = None, [str(e)]
        if errs:
            self._fail_malformed(cached, errs)
            return True
        if not self.satisfied_expectations(job):
            # informer cache stale; a watch event will re-enqueue
            self.flight.record(
                key, "expectation",
                "sync gated: informer cache still awaiting our own writes")
            return True
        forget = self.reconcile_tpujobs(job)
        # one observation point for the whole sync's condition churn: every
        # path through reconcile (incl. _fail_job) mutates job.status in
        # place before returning here
        self.flight.note_conditions(key, job.status.conditions)
        return forget

    # ------------------------------------------------------------------
    # reconcile (controller.go:336-492)
    # ------------------------------------------------------------------

    def reconcile_tpujobs(self, job: TPUJob) -> bool:
        key = job.key
        old_status = job.status.deepcopy()
        # Deltas re-stashed by a failed status write count recreations the
        # cached status doesn't know about yet: fold them in up front (after
        # the old_status snapshot, so the fold alone registers as a change
        # to write) and put them back on the ledger — they stay unpersisted
        # until a status write lands.  At-least-once accounting: bounded
        # churn prefers the rare overcount of a lost-response write over
        # silently undercounting.
        carried = self._restart_deltas.pop(key, None) or {}
        if carried:
            for rtype, d in carried.items():
                rs = job.status.replica_statuses.setdefault(rtype, ReplicaStatus())
                rs.restarts += d
            self._restart_deltas[key] = dict(carried)
        with TRACER.span("phase", phase="claim"):
            pods = self.get_pods_for_job(job)
            services = self.get_services_for_job(job)

        # terminal: clean up and freeze (controller.go:362-389)
        if st.is_finished(job.status):
            job.status.resize = None  # a finished job has no in-flight resize
            # a finished job stops exporting telemetry: its heartbeat age
            # only grows, and the terminal transition already flipped any
            # Stalled condition False (status.set_condition semantics)
            self.telemetry.forget(key)
            self.goodput.forget(key)  # a finished job accounts no phases
            self._delete_pods_and_services(job, pods, services)
            self._cleanup_ttl(job)
            if self.config.enable_gang_scheduling:
                self._delete_pod_group(job)
            self._persist_status(job, old_status)
            return True

        # federation gate: in a federated member (cluster_name set), a job
        # whose durable cluster annotation names ANOTHER cluster is held
        # dark before any local policy can touch it — running it here
        # would duplicate the gang, and failing it by a local deadline
        # would break the owner's accounting
        if self.config.cluster_name:
            gated = self._reconcile_federation(job, old_status, pods)
            if gated is not None:
                return gated

        # backoff limit (controller.go:391-453, 520-556)
        exceeded, reason = self._past_backoff_limit(job, pods)
        if exceeded:
            return self._fail_job(job, old_status, pods, services,
                                  self._backoff_message(job, reason))
        if self._past_active_deadline(job):
            return self._fail_job(job, old_status, pods, services,
                                  f"TPUJob {job.metadata.name} has failed because it was "
                                  "active longer than specified deadline")

        if self.config.enable_gang_scheduling:
            self._sync_pod_group(job)

        if not st.get_condition(job.status, c.JOB_CREATED):
            # Created is the job's durable history marker (kubeflow
            # semantics): it records that the object was admitted and is
            # MEANT to stay True after Succeeded/Failed, so it is waived
            # from the terminal flip-False tuple rather than added to it.
            st.update_job_conditions(  # noqa: TPL202
                job.status, c.JOB_CREATED, st.REASON_JOB_CREATED,
                f"TPUJob {job.metadata.name} is created.",
            )

        # gang-admission gate (native scheduler): a job whose gang the
        # scheduler has not admitted holds NO pods — all-or-nothing means
        # the reconciler never creates a partial gang, and a revoked
        # admission (preemption) evicts the pods without failure strikes.
        # The gate runs before the resize pre-pass: an unadmitted job has
        # nothing to resize.
        if self.scheduler is not None:
            with TRACER.span("phase", phase="admission"):
                gated = self._reconcile_admission(job, old_status, pods,
                                                  services)
            if gated is not None:
                return gated

        # flex staging gate (elastic capacity): a scheduler-published
        # ``flex-slices`` annotation clamps the Worker replica count IN
        # MEMORY to the flexed world, so the ordinary resize pre-pass below
        # stages the shrink/restore as a checkpoint-barriered drain/join.
        # The SPEC stays the user's truth — the clamp is recomputed from the
        # annotation every sync, and the scheduler clears the annotation
        # (never this code) when the gang grows back or releases.
        self._apply_flex(job)

        # elastic resize pre-pass: a spec.replicas change is a STAGED
        # drain/join transition, not a teardown.  Pods being drained are
        # excluded from the normal per-type reconcile below — they must not
        # be counted, restarted, or warned about as out-of-range.
        with TRACER.span("phase", phase="resize"):
            draining = self._reconcile_resize(job, pods)
        drain_names = {p.metadata.name for p in draining}

        coord_rtype = tpu_env.coordinator_replica(job)
        for rtype, rspec in job.spec.tpu_replica_specs.items():
            typed_pods = self.filter_by_replica_type(pods, rtype)
            if drain_names and rtype == c.REPLICA_TYPE_WORKER:
                typed_pods = [p for p in typed_pods
                              if p.metadata.name not in drain_names]
            with TRACER.span("phase", phase="pod_diff", rtype=rtype):
                restarting = self._reconcile_pods(job, typed_pods, rtype, rspec, pods)
            if rtype == coord_rtype:
                # coordinator-only headless service (controller.go:474-477;
                # worker-0 coordinates master-less jobs)
                typed_svcs = self.filter_by_replica_type(services, rtype)
                with TRACER.span("phase", phase="service_diff", rtype=rtype):
                    self._reconcile_services(job, typed_svcs, rtype, rspec)
            self._update_status_single(job, rtype, rspec, restarting)

        # re-check the backoff limit with the counts updated THIS sync:
        # the entry check reads the informer-cached status, which can trail
        # the restart just counted — without this, event-ordering (pod
        # DELETED seen before the job's status MODIFIED) lets one extra
        # pod incarnation launch beyond the configured limit.  Guarded on
        # is_finished: a job whose completion-bearing replica succeeded
        # this very sync must not also be flipped to Failed.
        if not st.is_finished(job.status):
            exceeded, reason = self._past_backoff_limit(job, pods)
            if exceeded:
                return self._fail_job(job, old_status, pods, services,
                                      self._backoff_message(job, reason))

        # workload telemetry: ingest the job's progress heartbeat from the
        # pods already claimed this sync and run the stall watchdog.  After
        # the status machine (so exemption checks see THIS sync's
        # conditions), before persistence (so a Stalled flip rides the same
        # status write).  A pure heartbeat tick changes no status field and
        # stays a suppressed write.
        with TRACER.span("phase", phase="telemetry"):
            state, events = self._reconcile_telemetry(job, pods)

        # goodput accounting: attribute the seconds since the last
        # observation to the phase this sync derived.  After telemetry (the
        # ingest events distinguish training from checkpointing), before
        # persistence (phase derivation reads THIS sync's conditions).
        with TRACER.span("phase", phase="goodput"):
            self._reconcile_goodput(job, pods, state, events)

        self._persist_status(job, old_status)
        return True

    # ------------------------------------------------------------------
    # pods (pod.go:49-232)
    # ------------------------------------------------------------------

    def _reconcile_pods(self, job: TPUJob, pods: List[Pod], rtype: str, rspec,
                        all_pods: Optional[List[Pod]] = None) -> bool:
        replicas = rspec.replicas if rspec.replicas is not None else 1
        st.initialize_replica_statuses(job.status, rtype)
        slices = self.get_slices(pods, replicas)
        restarting = False
        missing: List[int] = []
        for index in range(replicas):
            pod_slice = slices[index]
            if len(pod_slice) > 1:
                logger_for_replica(log, job, rtype).warning(
                    "%d pods share index %d", len(pod_slice), index)
                continue
            if not pod_slice:
                missing.append(index)
                continue
            pod = pod_slice[0]
            # ExitCode restart policy (pod.go:91-109)
            if pod.status.phase == "Failed" and rspec.restart_policy == c.RESTART_POLICY_EXIT_CODE:
                code = self._managed_exit_code(pod)
                if code is not None and is_retryable_exit_code(code):
                    restarting = True
                    # deletion_timestamp guard: a pod stuck Terminating past
                    # the expectations TTL (finalizer, dead node) must stay
                    # in Restarting without being re-deleted and re-counted
                    # every sync — that would spuriously trip backoffLimit
                    if not pod.metadata.deletion_timestamp:
                        # count the restart decision in status: a recreated
                        # pod has restartCount 0, so without this a
                        # preemption loop is invisible and unbounded (vs
                        # controller.go:520-556 which only sees kubelet
                        # in-place restarts)
                        job.status.replica_statuses[rtype].restarts += 1
                        exceeded, _ = self._past_backoff_limit(
                            job, all_pods if all_pods is not None else pods)
                        if exceeded:
                            # this restart trips the limit: keep the final
                            # failed pod in place (its logs/events are the
                            # debugging evidence; cleanPodPolicy decides its
                            # fate at failure time) — the post-loop check
                            # fails the job this same sync.  NOT put on the
                            # delta ledger: the evidence pod survives, so a
                            # 409'd fail-write re-derives this count from
                            # the fresh cache instead of a rebase
                            # double-applying it every lagged sync
                            logger_for_pod(log, pod, job).info(
                                "retryable exit %d reaches the backoff "
                                "limit; failing job", code)
                        else:
                            logger_for_pod(log, pod, job).info(
                                "exited with retryable code %d; restarting",
                                code)
                            ekey = expectation_key(job.key, rtype, "pods")
                            self.expectations.expect(ekey, adds=0, dels=1)
                            self.flight.record(
                                job.key, "expectation",
                                f"raise +1 pod-delete expectation [{rtype}/{index}] "
                                f"(retryable exit {code})",
                                {"rtype": rtype, "index": index, "dels": 1})
                            try:
                                self.pod_control.delete_pod(
                                    pod.metadata.namespace,
                                    pod.metadata.name, job,
                                )
                            except NotFoundError:
                                # already gone (raced with node GC or a
                                # concurrent sync's delete): the intended
                                # outcome happened, so KEEP the count — but
                                # clear our expectation, whose DELETED
                                # event may have been observed before we
                                # registered it (it would otherwise gate
                                # syncs until the TTL)
                                self.expectations.observe_del(ekey)
                            except ServerTimeoutError:
                                # ambiguous 504: the delete may or may not
                                # have executed (lost response).  Rolling
                                # back would make an executed delete an
                                # UNCOUNTED, undamped restart — so keep the
                                # count, at-least-once style: if the pod
                                # survives, the retry sync re-deletes it and
                                # overcounts by one occurrence; if it is
                                # gone, the count is exactly right.  Clear
                                # our expectation either way (no DELETED
                                # event is guaranteed to arrive).
                                self.expectations.observe_del(ekey)
                            except Exception:
                                # the restart did not happen: roll back the
                                # count and the expectation so the retry
                                # sync re-derives exactly one restart from
                                # the still-present Failed pod
                                job.status.replica_statuses[rtype].restarts -= 1
                                self.expectations.observe_del(ekey)
                                raise
                            # ledger entry only after the delete executed:
                            # the delta survives a failed STATUS write, and
                            # the delete is what destroys the evidence pod
                            deltas = self._restart_deltas.setdefault(job.key, {})
                            deltas[rtype] = deltas.get(rtype, 0) + 1
                            self._note_restart(job.key, rtype, index)
                    # fall through: the failure still counts this sync, so the
                    # status machine emits Restarting (reference pod.go:91-109
                    # deletes async and the pod is still counted)
            st.update_replica_statuses(job.status, rtype, pod)
        if missing:
            waits = {i: self._restart_backoff_remaining(job.key, rtype, i)
                     for i in missing}
            delayed = [i for i in missing if waits[i] > 0]
            ready = [i for i in missing if waits[i] <= 0]
            if delayed:
                # crash-loop damper: only the striking replica waits out its
                # decayed exponential delay instead of relaunching at full
                # controller speed until backoffLimit; healthy siblings (the
                # `ready` set) are untouched
                wait = min(waits[i] for i in delayed)
                logger_for_replica(log, job, rtype).info(
                    "restart backoff: delaying replacement pod(s) %s for %.2fs",
                    delayed, wait)
                self.flight.record(
                    job.key, "backoff",
                    f"delaying replacement pod(s) {delayed} [{rtype}] "
                    f"for {wait:.2f}s",
                    {"rtype": rtype, "indices": delayed,
                     "wait_s": round(wait, 3)})
                self.queue.add_after(job.key, wait)
            if ready and self.scheduler is not None:
                # host-health gate: a replacement must never be BORN onto a
                # NotReady/cordoned/dead host.  The index's bound host comes
                # from the committed assignment; an excluded host's index
                # waits (requeued) for the scheduler's migration to re-place
                # the gang on healthy hardware.
                gated = [i for i in ready if self.scheduler.node_excluded(
                    self.scheduler.node_for(job, rtype, i))]
                if gated:
                    ready = [i for i in ready if i not in gated]
                    self.flight.record(
                        job.key, "sched",
                        f"holding replacement pod(s) {gated} [{rtype}]: "
                        "bound host is NotReady/cordoned (awaiting "
                        "migration)",
                        {"kind": "node-gate", "rtype": rtype,
                         "indices": gated})
                    self.queue.add_after(job.key, 0.2)
            if ready:
                # all unthrottled missing replicas of this type launch
                # concurrently (a v4-32 job's 8 hosts cost ~1 API round
                # trip, not 8 sequential ones)
                self._create_pods_batch(job, rtype, rspec, ready)
        return restarting

    def _note_restart(self, key: str, rtype: str, index: int) -> None:
        """Record a counted ExitCode restart in the crash-loop damper.

        First strike carries no delay (a single transient failure restarts
        promptly); each further strike doubles the wait, capped at the max.
        A replica that ran healthy well past its previous window decays back
        to a clean slate (the kubelet's CrashLoopBackOff resets the same
        way after a long enough run)."""
        base = self.config.restart_backoff_seconds
        if base <= 0:
            return
        max_delay = self.config.restart_backoff_max_seconds
        now = time.monotonic()
        strikes, last, _ = self._restart_backoff.get(
            (key, rtype, index), (0, 0.0, 0.0))
        # the healthy-run threshold is fixed (~2x the backoff cap; ~10 min at
        # the defaults, the kubelet's CrashLoopBackOff reset), NOT a multiple
        # of the previous strike's tiny delay — early strikes carry 0-delay
        # windows that any real crash cycle (schedule + start + crash) would
        # outlast, and the damper would never escalate
        if strikes and now - last > 2 * max_delay + base:
            strikes = 0
        strikes += 1
        delay = 0.0 if strikes == 1 else min(
            base * (2 ** min(strikes - 2, 30)), max_delay)
        self._restart_backoff[(key, rtype, index)] = (strikes, now, now + delay)
        self.flight.record(
            key, "backoff",
            f"restart strike {strikes} for {rtype}[{index}]: "
            f"next replacement delayed {delay:.2f}s",
            {"rtype": rtype, "index": index, "strikes": strikes,
             "delay_s": round(delay, 3)})

    def _restart_backoff_remaining(self, key: str, rtype: str, index: int) -> float:
        entry = self._restart_backoff.get((key, rtype, index))
        if entry is None:
            return 0.0
        return max(0.0, entry[2] - time.monotonic())

    def _create_pods_batch(self, job: TPUJob, rtype: str, rspec, indices: List[int]) -> None:
        """Slow-start parallel create with reference expectation bookkeeping
        (controller.go:430-470): raise for every intended create up front,
        lower for every create that did not happen — failed or skipped after
        a failing batch — then surface the first error to the workqueue."""
        ekey = expectation_key(job.key, rtype, "pods")
        pods = [self._build_pod(job, rtype, rspec, index) for index in indices]
        self.expectations.expect(ekey, adds=len(pods), dels=0)
        self.flight.record(
            job.key, "expectation",
            f"raise +{len(pods)} pod-create expectation(s) [{rtype}]",
            {"rtype": rtype, "adds": len(pods), "indices": list(indices)})
        with TRACER.span("phase", phase="slow_start_create", kind="pods",
                         count=len(pods)):
            created, err = self.pod_control.create_pods(
                job.metadata.namespace or "default", pods, job)
        for _ in range(len(pods) - created):
            self.expectations.observe_add(ekey)
        if err is not None:
            self.flight.record(
                job.key, "expectation",
                f"lower {len(pods) - created} unmet pod-create "
                f"expectation(s) [{rtype}]: {type(err).__name__}",
                {"rtype": rtype, "created": created, "intended": len(pods)})
            raise err

    @staticmethod
    def _managed_exit_code(pod: Pod) -> Optional[int]:
        for cs in pod.status.container_statuses:
            if cs.name == c.DEFAULT_CONTAINER_NAME and cs.state and cs.state.terminated:
                return cs.state.terminated.exit_code
        return None

    def _build_pod(self, job: TPUJob, rtype: str, rspec, index: int) -> Pod:
        """Render the pod for one replica index (no API writes)."""
        name = gen_general_name(job.metadata.name, rtype, index)
        template = rspec.template.deepcopy()
        labels = gen_labels(job.metadata.name)
        labels[c.LABEL_REPLICA_TYPE] = rtype.lower()
        labels[c.LABEL_REPLICA_INDEX] = str(index)
        template.metadata.labels.update(labels)
        pod = Pod(metadata=template.metadata, spec=template.spec)
        pod.metadata.name = name
        pod.metadata.namespace = job.metadata.namespace or "default"

        port = get_port_from_job(job, c.REPLICA_TYPE_MASTER
                                 if c.REPLICA_TYPE_MASTER in job.spec.tpu_replica_specs
                                 else rtype)
        tpu_env.set_cluster_spec(pod, job, rtype, index, port)
        self._set_restart_policy(pod, rspec)
        self._apply_tpu_scheduling(pod, rspec, job)
        if self.scheduler is not None:
            # host binding from the gang's committed assignment: the
            # pod->Node edge host-failure-domain faults (and the "no pod
            # born onto a NotReady/cordoned host" invariant) hang off
            node = self.scheduler.node_for(job, rtype, index)
            if node is not None:
                pod.spec.node_name = node

        # non-coordinator pods wait for the coordinator DNS
        # (pod.go:189-198, util.go:61-87); in master-less jobs worker-0 is
        # the coordinator and must not gate on itself
        is_coordinator = rtype == tpu_env.coordinator_replica(job) and index == 0
        if rtype == c.REPLICA_TYPE_WORKER and not is_coordinator:
            rendered = render_init_containers(
                tpu_env.coordinator_dns(job), self.config.init_container_image
            )
            pod.spec.init_containers.extend(Container.from_dict(d) for d in rendered)

        if self.config.enable_gang_scheduling:
            # scheduler name + PodGroup annotation (pod.go:200-216)
            if pod.spec.scheduler_name and pod.spec.scheduler_name != self.config.gang_scheduler_name:
                logger_for_replica(log, job, rtype).warning(
                    "pod %s scheduler %s overridden by gang scheduler %s",
                    name, pod.spec.scheduler_name, self.config.gang_scheduler_name)
            pod.spec.scheduler_name = self.config.gang_scheduler_name
            pod.metadata.annotations[c.POD_GROUP_ANNOTATION] = gen_pod_group_name(job.metadata.name)
        return pod

    @staticmethod
    def _set_restart_policy(pod: Pod, rspec) -> None:
        """ExitCode forces pod RestartPolicy Never so the controller, not the
        kubelet, owns the restart decision (pod.go:283-289)."""
        if rspec.restart_policy == c.RESTART_POLICY_EXIT_CODE:
            pod.spec.restart_policy = "Never"
        elif rspec.restart_policy:
            pod.spec.restart_policy = rspec.restart_policy

    @staticmethod
    def _apply_tpu_scheduling(pod: Pod, rspec, job: TPUJob) -> None:
        """TPU-first scheduling: google.com/tpu resource requests + GKE node
        selectors derived from the slice spec (the reference's GPU resource
        request analog, e.g. examples/.../pytorch_job_mnist_nccl.yaml:20-21)."""
        tpu = rspec.tpu
        if tpu is None or not tpu.accelerator:
            for other in job.spec.tpu_replica_specs.values():
                if other.tpu and other.tpu.accelerator:
                    tpu = other.tpu
                    break
        if tpu is None or not tpu.accelerator:
            return
        topo = tpu.resolve()
        pod.spec.node_selector.setdefault(c.TPU_ACCELERATOR_NODE_SELECTOR, topo.accelerator)
        pod.spec.node_selector.setdefault(c.TPU_TOPOLOGY_NODE_SELECTOR, topo.topology)
        for container in pod.spec.containers:
            if container.name != c.DEFAULT_CONTAINER_NAME:
                continue
            if container.resources is None:
                container.resources = ResourceRequirements()
            container.resources.limits.setdefault(c.TPU_RESOURCE, topo.chips_per_host)

    # ------------------------------------------------------------------
    # elastic resize (staged drain/join; ROADMAP item 3)
    # ------------------------------------------------------------------

    def _apply_flex(self, job: TPUJob) -> None:
        """Clamp the Worker replica count to the scheduler's flexed slice
        target (``tpujob.dev/flex-slices``) — in memory only, this sync.

        The scheduler shrinks a multislice gang under pressure by publishing
        the flex annotation instead of editing the user's spec; this gate
        translates it into the replica count the staged-resize machinery
        understands (``flex * hosts_per_slice - masters``), so the shrink
        rides the same publish-target -> checkpoint-barrier -> drain ladder
        as a user resize: highest-index replicas (== highest slices) drain
        with zero failure strikes, and the world republishes only when they
        are provably gone.  Stateless: the clamp re-derives from the durable
        annotation every sync, so a crash or shard handoff resumes the flex
        exactly where the annotations say it is.  Runs AFTER strict spec
        validation (sync_handler) — the spec the user wrote is what gets
        validated — and only for admitted jobs (the caller's admission gate
        already returned for anything unadmitted)."""
        if self.scheduler is None:
            return
        ann = job.metadata.annotations or {}
        if ann.get(c.ANNOTATION_SCHED_ASSIGNMENT) is None:
            return
        raw = ann.get(c.ANNOTATION_FLEX_SLICES)
        if raw is None:
            return
        try:
            flex = int(raw)
        except (TypeError, ValueError):
            logger_for_job(job).warning(
                "ignoring unparseable %s=%r", c.ANNOTATION_FLEX_SLICES, raw)
            return
        rspec = job.spec.tpu_replica_specs.get(c.REPLICA_TYPE_WORKER)
        if rspec is None:
            return
        try:
            req = gang_request(job)
        except TopologyError:
            return  # never-placeable specs get their verdict elsewhere
        if not 1 <= flex < req.num_slices:
            return  # out-of-range flex (or full shape): spec replicas stand
        masters = sum(
            (r.replicas if r.replicas is not None else 1)
            for t, r in job.spec.tpu_replica_specs.items()
            if t == c.REPLICA_TYPE_MASTER)
        workers = flex * req.hosts_per_slice - masters
        if workers < 1:
            return  # degenerate clamp: keep the spec shape
        rspec.replicas = workers

    def _reconcile_resize(self, job: TPUJob, pods: List[Pod]) -> List[Pod]:
        """Stage a mid-flight ``spec.replicas`` change on the Worker type as
        a drain/join transition instead of a teardown.

        Scale-up (*Joining*): the normal reconcile creates the missing
        replicas; the new world size publishes (``tpujob.dev/world-size``)
        only once every in-range replica is Running, so survivors keep
        training at the old world until the joiners can actually rendezvous.

        Scale-down (*Draining*): the target publishes FIRST
        (``tpujob.dev/target-world-size``) so the workload can hit a
        checkpoint barrier; after the ack (or the bounded drain grace) the
        highest-index replicas are deleted — surviving pods are never
        touched, and the deletions are not failure strikes.  The shrunk
        world publishes when the drained pods are gone.

        All staging intent is durable in ``status.resize``; everything else
        re-derives from live cluster state, so a restarted controller (or a
        rebalanced-in shard owner, PR 8) resumes a half-finished resize from
        status.  Every write rides the sync's shard/fencing context like any
        other reconcile write.

        Returns the pods currently being drained — the caller excludes them
        from the normal per-type reconcile (no out-of-range warnings, no
        ExitCode restarts of a pod that is leaving anyway).
        """
        rtype = c.REPLICA_TYPE_WORKER
        rspec = job.spec.tpu_replica_specs.get(rtype)
        if rspec is None:
            return []
        replicas = rspec.replicas if rspec.replicas is not None else 1
        desired_world = get_total_replicas(job)
        typed = self.filter_by_replica_type(pods, rtype)
        over = []
        for p in typed:
            index = _replica_index(p)
            if index is not None and index >= replicas:
                over.append(p)
        published = self._published_world(job, typed)

        if not over and (published is None or published == desired_world):
            ann = job.metadata.annotations or {}
            if (ann.get(c.ANNOTATION_TARGET_WORLD_SIZE) is not None
                    or ann.get(c.ANNOTATION_CHECKPOINT_ACK) is not None):
                # a rolled-back drain can leave its target — and the ack the
                # workload already wrote for it — behind without ever
                # publishing a new world: clear BOTH, or the workload would
                # see a phantom pending drain, and a LATER genuine shrink to
                # the same target would ride the stale ack past its
                # checkpoint barrier
                self._patch_job_annotations(
                    job, {c.ANNOTATION_TARGET_WORLD_SIZE: None,
                          c.ANNOTATION_CHECKPOINT_ACK: None})
            resize = job.status.resize
            if resize is not None:
                # the republish landed but a crash/conflict left the staging
                # record behind (target == current replicas: completed) — or
                # a flap returned to the origin before any pod moved
                # (target abandoned, from == current replicas: a rollback)
                self._finish_resize(
                    job, desired_world,
                    rolled_back=(resize.target_replicas != replicas
                                 and resize.from_replicas == replicas))
            return []

        # -- a resize is in flight -------------------------------------------
        direction = "down" if over else (
            "down" if published is not None and published > desired_world else "up")
        masters = desired_world - replicas
        from_workers = (published - masters if published is not None
                        else len(typed) - len(over))
        resize = job.status.resize
        if resize is None or resize.target_replicas != replicas:
            self._begin_resize(job, rtype, replicas, from_workers, direction,
                               superseded=resize)
            resize = job.status.resize
        st.update_job_conditions(
            job.status, c.JOB_RESIZING, st.REASON_JOB_RESIZING,
            f"TPUJob {job.metadata.name} is resizing {rtype} "
            f"{resize.from_replicas} -> {replicas}.",
        )

        if direction == "up":
            resize.phase = "Joining"
            # join staging: _reconcile_pods creates the missing replicas;
            # republish only when the full new replica set is Running —
            # survivors keep the old world until the joiners can rendezvous
            ready = (len(typed) == replicas
                     and all(_replica_index(p) is not None
                             and _replica_index(p) < replicas for p in typed)
                     and all(p.status.phase == "Running"
                             and not p.metadata.deletion_timestamp for p in typed))
            if ready:
                self._publish_world(job, desired_world)
                self._finish_resize(job, desired_world)
            return []

        # -- scale-down ------------------------------------------------------
        resize.phase = "Draining"
        if over:
            # checkpoint barrier: the target publishes BEFORE any deletion.
            # Skipped when the published world ALREADY equals the target —
            # then the out-of-range pods are never-rendezvoused joiners of
            # an abandoned grow (a flap), the survivors hold no state at
            # risk, and a target==world signal could never make the
            # workload ack anyway (drain_pending would be False)
            if published is not None and published != desired_world:
                self._publish_target(job, desired_world)
                if not self._drain_barrier_passed(job, desired_world):
                    grace = self.config.resize_drain_grace_s
                    self.queue.add_after(job.key,
                                         max(0.01, min(grace / 4, 1.0)))
                    return over
            self._delete_drained_pods(job, rtype, replicas, over)
            return over
        # every drained pod is gone: republish the shrunk world
        self._publish_world(job, desired_world)
        self._finish_resize(job, desired_world)
        return []

    def _published_world(self, job: TPUJob, typed: List[Pod]) -> Optional[int]:
        """The world size the job's live replicas currently operate at: the
        controller-published annotation when present, else the smallest
        world any live worker was born into (mid-join pods already carry
        the larger new world; the survivors' env names the old one), else
        None — no live workers means there is nothing to drain or join,
        and the next bring-up simply uses the spec."""
        ann = (job.metadata.annotations or {}).get(c.ANNOTATION_WORLD_SIZE)
        if ann:
            try:
                return int(ann)
            except ValueError:
                _time_warner.warning(
                    log, ("bad-world-annotation", job.key, ann),
                    "unparseable %s annotation %r on %s; ignoring",
                    c.ANNOTATION_WORLD_SIZE, ann, job.key)
        worlds = [w for w in (_pod_env_world(p) for p in typed) if w]
        return min(worlds) if worlds else None

    def _begin_resize(self, job: TPUJob, rtype: str, target: int,
                      from_workers: int, direction: str,
                      superseded: Optional[ResizeStatus]) -> None:
        """Open (or restage) the durable resize record and count it."""
        if superseded is not None and superseded.from_replicas == target:
            # flap back to the origin: the staged resize is abandoned — a
            # rollback, not a second resize in the same direction
            metrics.resize_rollbacks.inc()
            self.recorder.event(
                job, "Normal", st.REASON_RESIZE_ROLLED_BACK,
                f"TPUJob {job.metadata.name} resize to "
                f"{superseded.target_replicas} {rtype} replica(s) rolled "
                f"back to {target}.")
            self.flight.record(
                job.key, "resize",
                f"resize to {superseded.target_replicas} superseded: rolling "
                f"back to the origin ({target})",
                {"rtype": rtype, "abandoned": superseded.target_replicas,
                 "target": target})
        job.status.resize = ResizeStatus(
            replica_type=rtype,
            from_replicas=from_workers,
            target_replicas=target,
            phase="Draining" if direction == "down" else "Joining",
            started_at=st.now_iso(),
        )
        self._resize_started_mono[job.key] = time.monotonic()
        metrics.resize_total.labels(direction=direction).inc()
        self.recorder.event(
            job, "Normal", st.REASON_JOB_RESIZING,
            f"TPUJob {job.metadata.name} is resizing {rtype} "
            f"{from_workers} -> {target} ({direction}).")
        self.flight.record(
            job.key, "resize",
            f"resize staged: {rtype} {from_workers} -> {target} ({direction})",
            {"rtype": rtype, "from": from_workers, "to": target,
             "direction": direction})

    def _drain_barrier_passed(self, job: TPUJob, target_world: int) -> bool:
        """Scale-down checkpoint barrier: wait for the workload's ack (the
        checkpoint-ack annotation naming the target world) or the bounded
        drain grace.  The shared ladder (controller/barrier.py): per-
        incarnation monotonic anchor — a controller that RESUMED a half-
        finished drain grants the workload up to one more grace — floored
        by the durable ``status.resize.started_at`` so a drain already
        pending longer than the grace across incarnations proceeds
        immediately; fails open on a corrupt anchor."""
        ack = (job.metadata.annotations or {}).get(c.ANNOTATION_CHECKPOINT_ACK)
        resize = job.status.resize
        started = _parse_time(resize.started_at if resize is not None else None)
        return barrier.barrier_passed(
            self._resize_started_mono, job.key,
            self.config.resize_drain_grace_s,
            acked=ack == str(target_world),
            published_wall=started,
            now_mono=time.monotonic(), now_wall=time.time())

    def _delete_pod_no_strike(self, job: TPUJob, pod: Pod,
                              rtype: str) -> None:
        """The shared "delete a pod that is NOT failing" ladder (resize
        drains, scheduler evictions, watchdog restarts): the expectation is
        raised up front and cleared on every path where no DELETED event is
        guaranteed to arrive — already-gone 404 (the event may have
        preceded the registration), ambiguous 504 (lost response: the
        retry sync re-derives the remaining set from live state), and a
        genuinely failed delete, which alone surfaces its error."""
        ekey = expectation_key(job.key, rtype, "pods")
        self.expectations.expect(ekey, adds=0, dels=1)
        try:
            self.pod_control.delete_pod(
                pod.metadata.namespace, pod.metadata.name, job)
        except NotFoundError:
            self.expectations.observe_del(ekey)
        except ServerTimeoutError:
            self.expectations.observe_del(ekey)
        except Exception:
            self.expectations.observe_del(ekey)
            raise

    def _delete_drained_pods(self, job: TPUJob, rtype: str, replicas: int,
                             over: List[Pod]) -> None:
        """Delete the drained (highest-index-first) replicas with the usual
        expectation bookkeeping.  Resize-driven deletions are NOT failure
        strikes: no ``restarts`` increment, no Restarting condition, and the
        crash-loop damper entry for the index is dropped so a shrink
        followed by an immediate grow recreates the index promptly."""
        for pod in sorted(over, key=lambda p: _replica_index(p) or 0,
                          reverse=True):
            index = _replica_index(pod)
            if index is not None:
                self._restart_backoff.pop((job.key, rtype, index), None)
            if pod.metadata.deletion_timestamp:
                continue  # already terminating: don't re-delete or re-expect
            self.flight.record(
                job.key, "resize",
                f"drain: deleting {pod.metadata.name} "
                f"(index {index} >= target {replicas})",
                {"rtype": rtype, "index": index, "pod": pod.metadata.name})
            self._delete_pod_no_strike(job, pod, rtype)

    def _patch_job_annotations(self, job: TPUJob,
                               annotations: Dict[str, Optional[str]]) -> None:
        """Merge-patch job annotations (``None`` deletes a key), through the
        sync's fenced/traced transport.  The world-size publication channel:
        a real pod reads these through a downward-API mount, the in-process
        harness through the job object."""
        ns = job.metadata.namespace or "default"
        try:
            self.clients.server.patch(
                RESOURCE_TPUJOBS, ns, job.metadata.name,
                {"metadata": {"annotations": dict(annotations)}})
        except NotFoundError:
            return
        # keep the in-memory object coherent for the rest of this sync
        for k, v in annotations.items():
            if v is None:
                job.metadata.annotations.pop(k, None)
            else:
                job.metadata.annotations[k] = v

    def _publish_target(self, job: TPUJob, target_world: int) -> None:
        """Idempotently publish the PENDING world size so the workload can
        checkpoint before the drain deletes anything."""
        ann = job.metadata.annotations or {}
        if ann.get(c.ANNOTATION_TARGET_WORLD_SIZE) == str(target_world):
            return
        # the shared builder nulls the ack in the same patch (TPL200
        # consume-at-publish): a NEW target invalidates any ack standing
        # from a previous drain, so the barrier can never read last
        # epoch's ack as this one's.  (The idempotence guard above means a
        # mid-drain resync — same target, possibly a fresh valid ack —
        # never repatches.)
        self._patch_job_annotations(
            job, barrier.resize_target_patch(target_world))

    def _publish_world(self, job: TPUJob, world: int) -> None:
        """Republish the world size: the resize's commit point.  Survivors
        re-rendezvous at this size; the pending target clears; the resize
        generation bumps as the workload's cheap change detector."""
        ann = job.metadata.annotations or {}
        if ann.get(c.ANNOTATION_WORLD_SIZE) == str(world) and \
                ann.get(c.ANNOTATION_TARGET_WORLD_SIZE) is None:
            return
        gen = 0
        try:
            gen = int(ann.get(c.ANNOTATION_RESIZE_GENERATION) or 0)
        except ValueError:
            pass
        self._patch_job_annotations(job, {
            c.ANNOTATION_WORLD_SIZE: str(world),
            c.ANNOTATION_RESIZE_GENERATION: str(gen + 1),
            c.ANNOTATION_TARGET_WORLD_SIZE: None,
            # the ack is per-drain and now consumed: a future shrink to the
            # same target must run its own checkpoint barrier, not ride a
            # stale ack from this one
            c.ANNOTATION_CHECKPOINT_ACK: None,
        })

    def _finish_resize(self, job: TPUJob, world: int,
                       rolled_back: bool = False) -> None:
        """Close the staging record: condition flips; a completed resize
        observes its duration, an abandoned one (flap back to the origin
        before any pod moved) counts a rollback instead."""
        resize = job.status.resize
        job.status.resize = None
        started = self._resize_started_mono.pop(job.key, None)
        target = resize.target_replicas if resize is not None else None
        rtype = resize.replica_type if resize is not None else ""
        if rolled_back:
            metrics.resize_rollbacks.inc()
            reason = st.REASON_RESIZE_ROLLED_BACK
            message = (f"TPUJob {job.metadata.name} resize to {target} "
                       f"{rtype} replica(s) rolled back "
                       f"(world size stays {world}).")
        else:
            if started is not None:
                metrics.resize_duration.observe(time.monotonic() - started)
            reason = st.REASON_RESIZE_COMPLETED
            message = (f"TPUJob {job.metadata.name} resize to {target} "
                       f"{rtype} replica(s) complete (world size {world}).")
        st.mark_condition_false(job.status, c.JOB_RESIZING, reason, message)
        self.recorder.event(job, "Normal", reason, message)
        self.flight.record(
            job.key, "resize",
            (f"resize to {target} rolled back (world size stays {world})"
             if rolled_back else
             f"resize complete: world size {world} published"),
            {"world": world, "target": target, "rolled_back": rolled_back})

    # ------------------------------------------------------------------
    # gang-admission gate (native scheduler)
    # ------------------------------------------------------------------

    def _reconcile_admission(self, job: TPUJob, old_status,
                             pods: List[Pod],
                             services: List[Service]) -> Optional[bool]:
        """The reconciler half of all-or-nothing gang admission.

        Admission state is the scheduler's durable annotation pair:
        *admitted* = ``sched-assignment`` present without the ``sched-
        evicted`` marker.  An admitted job proceeds to the normal reconcile
        (returns None); anything else is held — Queued condition set, every
        pod evicted (NOT a failure strike: no ``restarts`` increment, no
        Restarting condition, damper entries popped so a re-admission
        recreates promptly), status persisted, sync done.  A job the
        scheduler ruled never-placeable gets a durable Failed condition
        (``TPUJobUnschedulable``) so an impossible shape cannot wedge the
        queue."""
        key = job.key
        # judged against THIS sync's job object (a pure function of the
        # modeled pools + the spec): a verdict can never be stale against a
        # just-fixed spec (Failed is irreversible), and in a sharded fleet
        # every member fails its own shards' never-placeable jobs without
        # waiting on the shard-0 decision loop
        errs = self.scheduler.placement_errors(job)
        if errs:
            return self._fail_unschedulable(job, old_status, pods, services,
                                            errs)
        ann = job.metadata.annotations or {}
        admitted = (ann.get(c.ANNOTATION_SCHED_ASSIGNMENT) is not None
                    and ann.get(c.ANNOTATION_SCHED_EVICTED) is None)
        if admitted:
            if st.has_condition(job.status, c.JOB_QUEUED):
                message = (f"TPUJob {job.metadata.name} admitted: gang "
                           "placed all-or-nothing "
                           f"({self.scheduler.request_summary(job)}).")
                st.mark_condition_false(job.status, c.JOB_QUEUED,
                                        st.REASON_JOB_ADMITTED, message)
                self.recorder.event(job, "Normal", st.REASON_JOB_ADMITTED,
                                    message)
                self.flight.record(key, "sched", "admitted: gate opened",
                                   {"kind": "admitted"})
            return None
        # -- queued (or being evicted): no pods may run ---------------------
        preempted = (ann.get(c.ANNOTATION_SCHED_EVICTED) is not None
                     or bool(pods))
        migrated = ann.get(c.ANNOTATION_MIGRATED_FROM)
        if preempted and migrated and migrated.startswith("defrag:"):
            # a torus-defragmentation compaction move, not a capacity
            # preemption or hardware repair: the queue history must say so
            reason = st.REASON_JOB_MIGRATED
            message = (f"TPUJob {job.metadata.name} is migrating off "
                       f"fragmented host(s) {migrated[len('defrag:'):]} to "
                       "compact free capacity; re-queued for contiguous "
                       "re-admission.")
        elif preempted and migrated:
            # a scheduled migration off a dead/cordoned host, not a
            # capacity preemption: the queue history must say which
            reason = st.REASON_JOB_MIGRATED
            message = (f"TPUJob {job.metadata.name} is migrating off "
                       f"unavailable host(s) {migrated}; re-queued for "
                       "admission on healthy hardware.")
        elif preempted:
            reason = st.REASON_JOB_PREEMPTED
            message = (f"TPUJob {job.metadata.name} was preempted; "
                       "re-queued for admission.")
        else:
            reason = st.REASON_JOB_QUEUED
            message = (f"TPUJob {job.metadata.name} is queued: waiting for "
                       f"all-or-nothing admission of "
                       f"{self.scheduler.request_summary(job)}.")
        existing = st.get_condition(job.status, c.JOB_QUEUED)
        newly = existing is None or existing.status != "True"
        if newly or (preempted
                     and existing.reason not in (st.REASON_JOB_PREEMPTED,
                                                 st.REASON_JOB_MIGRATED)):
            # Preempted is sticky for this queued life: once the eviction
            # markers clear (pods gone, capacity released) the gate must
            # not downgrade the reason back to plain Queued — the queue
            # history IS the observability
            st.update_job_conditions(job.status, c.JOB_QUEUED, reason,
                                     message)
        if newly:
            self.recorder.event(
                job, "Warning" if preempted else "Normal", reason, message)
            self.flight.record(key, "sched", message, {"kind": reason})
        if st.has_condition(job.status, c.JOB_RUNNING):
            # a preempted job is not running; Queued<->Running exclusion
            st.mark_condition_false(job.status, c.JOB_RUNNING, reason,
                                    message)
        self._evict_pods(job, pods)
        # a queued job is not RUNNING: its activeDeadlineSeconds clock must
        # not accrue while it waits for (re-)admission — otherwise a
        # scheduler eviction converts into a deadline Failure, exactly the
        # eviction-is-not-a-failure contract this gate exists to keep.
        # Clearing startTime suspends the clock (the Kueue suspension
        # semantics); _update_status_single re-stamps it when the admitted
        # job's pods reconcile, so the deadline counts running time per
        # admission stint.
        if job.status.start_time is not None:
            job.status.start_time = None
        # a queued job has no heartbeats BY DESIGN: the stall deadline
        # re-arms every gated sync, so the watchdog can never flip a
        # Pending-phase job Stalled (it gets one full deadline after
        # re-admission brings the publisher back)
        self.telemetry.exempt(key)
        # goodput: a gated job accounts badput by its STICKY queue reason
        # (the requeue wait after an eviction is part of the preemption's
        # cost, not generic queueing — exactly what the scheduler's
        # projected-loss view charges a repeat victim for)
        cond = st.get_condition(job.status, c.JOB_QUEUED)
        creason = cond.reason if cond is not None else reason
        self._observe_goodput(job, gp.QUEUE_REASON_PHASES.get(
            creason, gp.PHASE_QUEUED))
        # a deep-queued job may see no events for hours: arm the metrics-
        # refresh tick here too (same one-live-chain contract as the
        # normal path) or its queue-badput series freeze between syncs
        if self.config.enable_goodput:
            interval = self.config.stall_check_interval()
            if self.goodput.arm_tick(key, interval):
                self.queue.add_after(key, interval)
        self._persist_status(job, old_status)
        return True

    def _reconcile_federation(self, job: TPUJob, old_status,
                              pods: List[Pod]) -> Optional[bool]:
        """The reconciler half of cluster-level job ownership.

        Ownership is the durable ``tpujob.dev/cluster`` annotation written
        once by the federation duty owner.  A job the annotation homes
        HERE (or has not homed yet — placement is optimistic-local-start)
        proceeds to the normal reconcile (returns None); one homed on
        another cluster is held dark: every pod evicted WITHOUT a failure
        strike (the admission gate's eviction mechanics — the named
        cluster runs the gang, a copy here is a transfer source or a
        revival zombie awaiting the federation sweep), telemetry exempt,
        clocks suspended, sync done.  No status conditions are written:
        the owning cluster's copy carries the job's visible history."""
        ann = job.metadata.annotations or {}
        owner = ann.get(c.ANNOTATION_CLUSTER)
        if owner is None or owner == self.config.cluster_name:
            return None
        key = job.key
        self.flight.record(
            key, "federation",
            f"held: cluster {owner} owns this job "
            f"(we are {self.config.cluster_name})",
            {"kind": "federation-hold", "owner": owner})
        self._evict_pods(job, pods)
        # a held job is not running: its activeDeadlineSeconds clock must
        # not accrue, and the stall watchdog must never judge it (same
        # suspension semantics as the admission gate)
        if job.status.start_time is not None:
            job.status.start_time = None
        self.telemetry.exempt(key)
        self._persist_status(job, old_status)
        return True

    def _evict_pods(self, job: TPUJob, pods: List[Pod]) -> None:
        """Delete an unadmitted job's pods with the usual expectation
        bookkeeping.  Scheduler evictions are NOT failure strikes — the
        drain-deletion stance of the elastic resize applied to whole
        gangs."""
        for pod in pods:
            if pod.metadata.deletion_timestamp:
                continue  # already terminating: don't re-delete or re-expect
            label = pod.metadata.labels.get(c.LABEL_REPLICA_TYPE) or ""
            rtype = next((t for t in job.spec.tpu_replica_specs
                          if t.lower() == label), label)
            index = _replica_index(pod)
            if index is not None and rtype:
                self._restart_backoff.pop((job.key, rtype, index), None)
            self.flight.record(
                job.key, "sched",
                f"evict: deleting {pod.metadata.name} (gang not admitted)",
                {"kind": "evict", "pod": pod.metadata.name})
            self._delete_pod_no_strike(job, pod, rtype)

    def _fail_unschedulable(self, job: TPUJob, old_status, pods, services,
                            errs: List[str]) -> bool:
        """Durable verdict for a never-placeable gang: the job can NEVER
        run on the modeled fleet, so it fails visibly at admission instead
        of wedging the queue head forever (the malformed-CR stance applied
        to capacity shapes)."""
        message = (f"TPUJob {job.metadata.name} is unschedulable: "
                   + "; ".join(errs))
        logger_for_job(log, job).info(message)
        self._delete_pods_and_services(job, pods, services)
        self.recorder.event(job, "Warning", st.REASON_JOB_UNSCHEDULABLE,
                            message)
        if job.status.completion_time is None:
            job.status.completion_time = st.now_iso()
        st.update_job_conditions(job.status, c.JOB_FAILED,
                                 st.REASON_JOB_UNSCHEDULABLE, message)
        metrics.jobs_failed.inc()
        self._persist_status(job, old_status)
        return True

    # ------------------------------------------------------------------
    # workload telemetry: heartbeat ingestion + the stall watchdog
    # ------------------------------------------------------------------

    def _reconcile_telemetry(
        self, job: TPUJob, pods: List[Pod]
    ) -> Tuple[Optional[JobProgress], List[str]]:
        """Ingest the job's workload progress heartbeat and run the
        Stalled-job watchdog.  Returns ``(state, ingest events)`` for the
        goodput phase derivation downstream (``(None, [])`` when the plane
        is off, the job publishes nothing, or this member does not own it).

        Ingestion reads the ``tpujob.dev/progress`` annotation off the pods
        this sync already claimed from the informer cache — zero extra API
        reads, and an annotation-only pod MODIFIED event reaches here
        through the normal settle-window coalescer like any other pod
        event.  A job that never publishes a heartbeat costs nothing and
        never arms the watchdog.

        The watchdog flips a ``Stalled`` condition when the reported step
        has not advanced for ``stall_timeout_s`` on the controller's
        monotonic clock.  Chaos-safe: heartbeat gaps during windows where
        a gap proves nothing — a resize staging, a counted restart, replica
        churn from preemption — re-arm the deadline instead of counting
        toward it, so the soak's fault schedule cannot mint false stalls.
        Recovery (the step advances again) clears the condition.  The tick
        is requeued like ActiveDeadline; across a crash or shard handoff
        the durable condition survives in status while the deadline clock
        conservatively restarts from re-ingestion.
        """
        if not self.config.enable_telemetry:
            return None, []
        key = job.key
        if st.is_finished(job.status):
            # the job went terminal THIS sync: the terminal transition just
            # flipped any Stalled condition False (set_condition semantics)
            # and the lost-write repair below must not read that flip as a
            # lost stall write and resurrect it onto a finished job
            self.telemetry.forget(key)
            self.goodput.forget(key)
            return None, []
        if self.sharder is not None and not self._owns_key(key):
            # a draining shard's wedged sync must not resurrect state
            return None, []
        best: Optional[Tuple] = None
        best_pod: Optional[Pod] = None
        best_raw = ""
        for p in pods:
            raw = (p.metadata.annotations or {}).get(c.ANNOTATION_PROGRESS)
            if not raw:
                continue
            prog = parse_progress(raw)
            if prog is None:
                _time_warner.warning(
                    log, ("bad-progress", key, raw),
                    "unparseable %s annotation %r on pod %s; ignoring",
                    c.ANNOTATION_PROGRESS, raw, p.metadata.name)
                continue
            rank = (prog.resize_generation, prog.step,
                    prog.published_at or 0.0, p.metadata.name)
            if best is None or rank > best[0]:
                best = (rank, prog)
                best_pod, best_raw = p, raw
        events: List[str] = []
        if best is not None:
            ns = job.metadata.namespace or "default"
            shard = None
            if self.sharder is not None and job.metadata.uid:
                shard = self.sharder.shard_of_uid(job.metadata.uid)
            state, events = self.telemetry.ingest(
                key, ns, job.metadata.name,
                str(shard) if shard is not None else "-",
                best_pod.metadata.name, best_raw, best[1],
                stalled_in_status=st.has_condition(job.status, c.JOB_STALLED),
            )
        else:
            state = self.telemetry.get(key)
            if state is None:
                return None, []  # not a telemetry-publishing job
        if EVENT_FIRST in events:
            self.flight.record(
                key, "progress",
                f"heartbeat channel established by {state.pod} "
                f"(step {state.progress.step})",
                {"pod": state.pod, "step": state.progress.step,
                 "stalled_in_status": state.stalled})
        if EVENT_CHECKPOINT in events:
            self.flight.record(
                key, "progress",
                f"checkpoint advanced to step {state.progress.checkpoint_step}",
                {"checkpoint_step": state.progress.checkpoint_step,
                 "step": state.progress.step})
        exempt = self._telemetry_exempt(job, pods)
        if exempt is not None:
            # the gap proves nothing during this window: re-arm the deadline
            # so the workload gets one full stall_timeout after it closes
            self.telemetry.exempt(key)
        timeout = self.config.stall_timeout_s
        if timeout > 0:
            if state.stalled:
                if EVENT_ADVANCE in events:
                    self._clear_stalled(job, state)
                elif not st.has_condition(job.status, c.JOB_STALLED):
                    # the flip's status write was lost (conflict/transport
                    # error after the in-memory transition): unlike every
                    # other condition, Stalled is not re-derived from pods
                    # each sync, so it must repair itself here — quietly,
                    # with no second event/count for the same episode
                    st.update_job_conditions(
                        job.status, c.JOB_STALLED, st.REASON_JOB_STALLED,
                        f"TPUJob {job.metadata.name} has stalled: no "
                        f"training progress (last step "
                        f"{state.progress.step} from {state.pod}).")
            elif st.has_condition(job.status, c.JOB_STALLED):
                # the clear's status write was lost: re-clear quietly
                st.mark_condition_false(
                    job.status, c.JOB_STALLED, st.REASON_PROGRESS_RESUMED,
                    f"TPUJob {job.metadata.name} resumed progress at step "
                    f"{state.progress.step}.")
            elif exempt is None:
                age = self.telemetry.stall_age(key)
                if age is not None and age >= timeout:
                    self._flip_stalled(job, state, age)
            if (state.stalled and self.config.stall_policy == "restart"
                    and not state.restart_fired and exempt is None):
                # attempted while stalled on every tick until it lands once:
                # a transient delete failure (or a mid-recreation window,
                # which reads as churn-exempt) must not silently degrade
                # the restart policy to event-only for the whole episode
                self._restart_stuck_replica(job, state, pods)
        # the telemetry tick: requeued like ActiveDeadline so a stall is
        # detected within ~one check interval of its deadline even when no
        # event ever surfaces the job again — and armed with the watchdog
        # DISABLED too, at a slower cadence, so the age gauges keep moving
        # after a dead publisher stops producing pod events (the
        # metrics-still-flow contract).  arm_tick keeps exactly one live
        # tick chain per job — the delayed queue does not dedupe, so
        # scheduling unconditionally would leak a timer chain per
        # heartbeat event
        interval = self.config.stall_check_interval()
        if self.telemetry.arm_tick(key, interval):
            self.queue.add_after(key, interval)
        self.telemetry.export(key)
        return state, events

    def _telemetry_exempt(self, job: TPUJob, pods: List[Pod]) -> Optional[str]:
        """Why a heartbeat gap is currently unaccountable (None = it counts):
        resize staging in flight, a counted restart in progress, replica
        churn (missing/non-Running pods — preemption, node loss, a watchdog
        restart itself), or the job sitting unadmitted in the gang
        scheduler's queue (a queued job has no heartbeats by design; it
        must never flip Stalled)."""
        ann = job.metadata.annotations or {}
        if self.scheduler is not None and (
                ann.get(c.ANNOTATION_SCHED_ASSIGNMENT) is None
                or ann.get(c.ANNOTATION_SCHED_EVICTED) is not None):
            return "queued"
        if ann.get(c.ANNOTATION_PREEMPT_TARGET) is not None:
            # paused at the preemption checkpoint barrier: the step is
            # frozen BY DESIGN until the eviction lands
            return "preempt"
        if (job.status.resize is not None
                or ann.get(c.ANNOTATION_TARGET_WORLD_SIZE) is not None
                or st.has_condition(job.status, c.JOB_RESIZING)):
            return "resize"
        if st.has_condition(job.status, c.JOB_RESTARTING):
            return "restart"
        expected = get_total_replicas(job)
        running = sum(1 for p in pods
                      if p.status.phase == "Running"
                      and not p.metadata.deletion_timestamp)
        if running < expected:
            return "replica-churn"
        return None

    def _flip_stalled(self, job: TPUJob, state: JobProgress, age: float) -> None:
        timeout = self.config.stall_timeout_s
        message = (f"TPUJob {job.metadata.name} has stalled: no training "
                   f"progress for {age:.1f}s (deadline {timeout:g}s; last "
                   f"step {state.progress.step} from {state.pod}).")
        st.update_job_conditions(job.status, c.JOB_STALLED,
                                 st.REASON_JOB_STALLED, message)
        self.telemetry.mark_stalled(job.key, True)
        metrics.jobs_stalled.inc()
        self.recorder.event(job, "Warning", st.REASON_JOB_STALLED, message)
        self.flight.record(
            job.key, "progress",
            f"STALLED: no step advance for {age:.1f}s "
            f"(deadline {timeout:g}s, last step {state.progress.step})",
            {"age_s": round(age, 3), "deadline_s": timeout,
             "step": state.progress.step, "pod": state.pod,
             "policy": self.config.stall_policy})

    def _clear_stalled(self, job: TPUJob, state: JobProgress) -> None:
        message = (f"TPUJob {job.metadata.name} resumed progress at step "
                   f"{state.progress.step}.")
        st.mark_condition_false(job.status, c.JOB_STALLED,
                                st.REASON_PROGRESS_RESUMED, message)
        self.telemetry.mark_stalled(job.key, False)
        self.recorder.event(job, "Normal", st.REASON_PROGRESS_RESUMED, message)
        self.flight.record(
            job.key, "progress",
            f"recovered: progress resumed at step {state.progress.step}",
            {"step": state.progress.step, "pod": state.pod})

    def _restart_stuck_replica(self, job: TPUJob, state: JobProgress,
                               pods: List[Pod]) -> None:
        """The restart policy: delete the heartbeat-publishing replica once
        per stall episode; the normal reconcile recreates the missing index.
        NOT a failure strike — no ``restarts`` increment, no Restarting
        condition (the pod was Running, just silent), and the recreated
        pod's churn window is itself a watchdog exemption."""
        pod = next((p for p in pods if p.metadata.name == state.pod), None)
        if pod is None or pod.metadata.deletion_timestamp:
            return
        rtype = pod.metadata.labels.get(c.LABEL_REPLICA_TYPE) or ""
        self.flight.record(
            job.key, "progress",
            f"watchdog restart: deleting stuck replica {pod.metadata.name}",
            {"pod": pod.metadata.name, "rtype": rtype})
        # the shared no-strike ladder: an ambiguous 504 still counts the
        # episode as acted (idempotent — restart_fired is set below only
        # when the ladder did not raise); a genuinely failed delete raises
        # and leaves restart_fired unset so the next tick retries
        self._delete_pod_no_strike(job, pod, rtype)
        self.telemetry.note_restart_fired(job.key)
        metrics.watchdog_restarts.inc()
        self.recorder.event(
            job, "Warning", st.REASON_JOB_STALLED,
            f"Progress watchdog deleted stuck replica {pod.metadata.name} "
            f"of TPUJob {job.metadata.name}.")

    # ------------------------------------------------------------------
    # goodput accounting: the phase ledger (tpujob/obs/goodput)
    # ------------------------------------------------------------------

    def _goodput_shard_label(self, job: TPUJob) -> str:
        if self.sharder is not None and job.metadata.uid:
            shard = self.sharder.shard_of_uid(job.metadata.uid)
            if shard is not None:
                return str(shard)
        return "-"

    def _observe_goodput(self, job: TPUJob, phase: str,
                         step: Optional[float] = None) -> None:
        """Fold one derived phase into the job's ledger and refresh its
        series.  Conditions ride along so a FRESH entry (cold start, shard
        handoff, first sync) seeds the job's pre-history from the durable
        timestamps instead of opening a gap."""
        if not self.config.enable_goodput:
            return
        key = job.key
        if self.sharder is not None and not self._owns_key(key):
            return  # the owner accounts; a draining shard must not resurrect
        event = self.goodput.observe(
            key, job.metadata.namespace or "default", job.metadata.name,
            self._goodput_shard_label(job), phase, step=step,
            conditions=job.status.conditions)
        if event == gp.EVENT_TRANSITION:
            self.flight.record(
                key, "goodput", f"phase -> {phase}", {"phase": phase})
        self.goodput.export(key)

    def _reconcile_goodput(self, job: TPUJob, pods: List[Pod],
                           state: Optional[JobProgress],
                           events: List[str]) -> None:
        """The normal-path half of goodput accounting (the admission gate
        observes its queued/preempted/migrating phases before returning).
        Also arms the metrics-refresh tick for ledger-only jobs — a job
        that never publishes heartbeats never arms the telemetry tick, and
        its ratio gauge would otherwise freeze between pod events."""
        if not self.config.enable_goodput or st.is_finished(job.status):
            return
        phase = self._goodput_phase(job, pods, state, events)
        step = float(state.progress.step) if state is not None else None
        self._observe_goodput(job, phase, step=step)
        if state is None:
            interval = self.config.stall_check_interval()
            if self.goodput.arm_tick(job.key, interval):
                self.queue.add_after(job.key, interval)

    def _goodput_phase(self, job: TPUJob, pods: List[Pod],
                       state: Optional[JobProgress],
                       events: List[str]) -> str:
        """Attribute this instant to one ledger phase, highest-signal
        first: an in-flight preemption/migration outranks resize, resize
        outranks restart, restart outranks stall, and only a gang that is
        fully Running with an advancing step clock counts as training.
        Everything here is a signal the sync already holds — conditions,
        annotations, the claimed pods, the heartbeat ingest events."""
        ann = job.metadata.annotations or {}
        if (ann.get(c.ANNOTATION_PREEMPT_TARGET) is not None
                or ann.get(c.ANNOTATION_SCHED_EVICTED) is not None):
            return (gp.PHASE_MIGRATING
                    if ann.get(c.ANNOTATION_MIGRATED_FROM)
                    else gp.PHASE_PREEMPTED)
        if (job.status.resize is not None
                or ann.get(c.ANNOTATION_TARGET_WORLD_SIZE) is not None
                or st.has_condition(job.status, c.JOB_RESIZING)):
            return gp.PHASE_RESIZING
        if st.has_condition(job.status, c.JOB_RESTARTING):
            return gp.PHASE_RESTARTING
        if st.has_condition(job.status, c.JOB_STALLED):
            return gp.PHASE_STALLED
        expected = get_total_replicas(job)
        live = [p for p in pods if not p.metadata.deletion_timestamp]
        running = sum(1 for p in live if p.status.phase == "Running")
        if len(live) < expected:
            # the gang's pod objects are not all there yet: with a native
            # scheduler that window is placement echo / bring-up
            # (scheduling); without one it is plain initialization
            return (gp.PHASE_SCHEDULING if self.scheduler is not None
                    else gp.PHASE_INITIALIZING)
        if running < expected:
            return gp.PHASE_INITIALIZING
        if state is not None:
            if state.progress.step <= 0:
                # heartbeats flow but the step clock has not started:
                # rendezvous / compile / restore — initialization
                return gp.PHASE_INITIALIZING
            if (EVENT_CHECKPOINT in events
                    and EVENT_ADVANCE not in events):
                return gp.PHASE_CHECKPOINTING
        return gp.PHASE_TRAINING

    def on_shard_drained(self, shard: int) -> None:
        """Shard handoff: drop the handed-off shard's telemetry state and
        metric series — the new owner re-seeds from the pod annotations,
        and two members exporting the same job would break the scrape-merge
        partition invariant."""
        dropped = self.telemetry.forget_shard(str(shard))
        self.goodput.forget_shard(str(shard))
        if dropped:
            from tpujob.obs.recorder import CONTROLLER_TIMELINE_KEY

            self.flight.record(
                CONTROLLER_TIMELINE_KEY, "shard",
                f"shard {shard} drained: telemetry for {len(dropped)} "
                f"job(s) dropped",
                {"shard": shard, "jobs": len(dropped)})

    # ------------------------------------------------------------------
    # debug introspection (the /debug/fleet and /debug/jobs payload halves
    # owned by the controller rather than the flight recorder)
    # ------------------------------------------------------------------

    def fleet_snapshot(self) -> Dict[str, Any]:
        """The ``/debug/fleet`` payload: this instance's identity, the
        shards it currently owns, and one progress row per tracked job.
        Scrape-merge story: every member of a sharded fleet serves this
        endpoint; concatenating the ``jobs`` arrays (or the scraped
        ``tpujob_job_*`` series) across members yields the fleet view, and
        each job must appear under exactly one member — the same partition
        invariant ``shard_ownership`` makes checkable in promql."""
        identity = "single-controller"
        shards: Optional[List[int]] = None
        if self.sharder is not None:
            identity = getattr(self.sharder, "identity", identity)
            owned = getattr(self.sharder, "owned_shards", None)
            if callable(owned):
                shards = sorted(owned())
        out = {
            "identity": identity,
            "shards": shards,
            "stall_timeout_s": self.config.stall_timeout_s,
            "stall_policy": self.config.stall_policy,
            "jobs": self.telemetry.snapshot(),
            # member-local goodput rollup + the badput-breakdown table
            # (top contributors first); fleet-wide truth is the scrape-
            # merge of the per-job series, like the telemetry rows above
            "goodput": self.goodput.fleet(),
        }
        if self.scheduler is not None:
            # queue positions + admission decisions + capacity utilization:
            # the scrape-merge twin of the tpujob_scheduler_* series
            out["scheduler"] = self.scheduler.debug_snapshot()
        if self.sharder is not None:
            # the observatory's orphan check needs the DECLARED shard space,
            # not just this member's slice of it
            out["shard_count"] = getattr(self.sharder, "num_shards", None)
        return out

    def explain_job(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        """The ``/debug/why/<ns>/<name>`` payload: the scheduler's verdict
        + decision ring for the job, joined with the live progress and
        goodput rows this member holds.  None = no scheduler, or neither
        the scheduler nor the telemetry plane has seen the job (404)."""
        ns = namespace or "default"
        key = f"{ns}/{name}"
        out = (self.scheduler.explain(ns, name)
               if self.scheduler is not None else None)
        row = self.telemetry.row(key)
        if out is None and row is None:
            return None
        if out is None:
            out = {"job": key, "state": "unscheduled",
                   "verdict": None, "ring": []}
        out["progress"] = row
        out["goodput"] = self.goodput.row(key)
        return out

    def debug_job_state(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        """Controller-owned state merged into ``/debug/jobs/<ns>/<name>``:
        the durable resize staging record, the observed spec generation,
        and the live progress row — the fields the timeline alone cannot
        show."""
        ns = namespace or "default"
        obj = self.job_informer.store.get(ns, name)
        row = self.telemetry.row(f"{ns}/{name}")
        if obj is None and row is None:
            return None
        out: Dict[str, Any] = {"progress": row,
                               "goodput": self.goodput.row(f"{ns}/{name}")}
        if obj is not None:
            status = obj.get("status")
            status = status if isinstance(status, dict) else {}
            out["resize"] = status.get("resize")
            out["observedGeneration"] = status.get("observedGeneration")
        return out

    # ------------------------------------------------------------------
    # services (service.go:36-153)
    # ------------------------------------------------------------------

    def _reconcile_services(self, job: TPUJob, services: List[Service], rtype: str, rspec) -> None:
        replicas = 1  # master-only
        slices = self.get_slices(services, replicas)
        missing = [index for index in range(replicas) if not slices[index]]
        if missing:
            self._create_services_batch(job, rtype, missing)

    def _create_services_batch(self, job: TPUJob, rtype: str, indices: List[int]) -> None:
        """Mirror of _create_pods_batch for the headless service(s)."""
        ekey = expectation_key(job.key, rtype, "services")
        services = [self._build_service(job, rtype, index) for index in indices]
        self.expectations.expect(ekey, adds=len(services), dels=0)
        self.flight.record(
            job.key, "expectation",
            f"raise +{len(services)} service-create expectation(s) [{rtype}]",
            {"rtype": rtype, "adds": len(services)})
        with TRACER.span("phase", phase="slow_start_create", kind="services",
                         count=len(services)):
            created, err = self.service_control.create_services(
                job.metadata.namespace or "default", services, job)
        for _ in range(len(services) - created):
            self.expectations.observe_add(ekey)
        if err is not None:
            raise err

    def _build_service(self, job: TPUJob, rtype: str, index: int) -> Service:
        """Render the headless rendezvous service (no API writes)."""
        port = get_port_from_job(job, rtype)
        labels = gen_labels(job.metadata.name)
        labels[c.LABEL_REPLICA_TYPE] = rtype.lower()
        labels[c.LABEL_REPLICA_INDEX] = str(index)
        ports = [ServicePort(name=c.DEFAULT_PORT_NAME, port=port)]
        if tpu_env.is_multislice(job):
            # multislice: the DCN coordinator rides the same headless
            # service — declare its port by name so the injected
            # MEGASCALE_COORDINATOR_ADDRESS (host:MEGASCALE_PORT) matches
            # a named ServicePort (tpu_env.py contract)
            ports.append(ServicePort(name="megascale", port=tpu_env.MEGASCALE_PORT))
        return Service(
            metadata=ObjectMeta(
                name=gen_general_name(job.metadata.name, rtype, index),
                namespace=job.metadata.namespace or "default",
                labels=dict(labels),
            ),
            spec=ServiceSpec(
                cluster_ip="None",  # headless: DNS resolves to the pod IP
                selector=dict(labels),
                ports=ports,
            ),
        )

    # ------------------------------------------------------------------
    # status convergence (status.go:63-152)
    # ------------------------------------------------------------------

    def _update_status_single(self, job: TPUJob, rtype: str, rspec, restarting: bool) -> None:
        replicas = rspec.replicas if rspec.replicas is not None else 1
        rs = job.status.replica_statuses.get(rtype)
        if rs is None:
            return
        expected = replicas - rs.succeeded
        if job.status.start_time is None:
            job.status.start_time = st.now_iso()

        has_master = c.REPLICA_TYPE_MASTER in job.spec.tpu_replica_specs
        completion_bearing = (
            rtype == c.REPLICA_TYPE_MASTER
            or (not has_master and rtype == c.REPLICA_TYPE_WORKER)
        )
        if completion_bearing:
            if rs.active > 0:
                st.update_job_conditions(
                    job.status, c.JOB_RUNNING, st.REASON_JOB_RUNNING,
                    f"TPUJob {job.metadata.name} is running.",
                )
            if expected == 0:
                # master-completion semantics (status.go:99-112)
                self.recorder.event(job, "Normal", st.REASON_JOB_SUCCEEDED,
                                    f"TPUJob {job.metadata.name} successfully completed.")
                st.update_job_conditions(
                    job.status, c.JOB_SUCCEEDED, st.REASON_JOB_SUCCEEDED,
                    f"TPUJob {job.metadata.name} successfully completed.",
                )
                if job.status.completion_time is None:
                    job.status.completion_time = st.now_iso()
                metrics.jobs_successful.inc()
                return
        if rs.failed > 0:
            if restarting:
                # event + metric only on the TRANSITION into Restarting: a
                # pod stuck Terminating keeps restarting=True across many
                # syncs and must not spam events / inflate jobs_restarted
                newly_restarting = not st.has_condition(job.status, c.JOB_RESTARTING)
                if newly_restarting:
                    self.recorder.event(job, "Warning", st.REASON_JOB_RESTARTING,
                                        f"TPUJob {job.metadata.name} is restarting because "
                                        f"{rs.failed} {rtype} replica(s) failed.")
                st.update_job_conditions(
                    job.status, c.JOB_RESTARTING, st.REASON_JOB_RESTARTING,
                    f"TPUJob {job.metadata.name} is restarting because "
                    f"{rs.failed} {rtype} replica(s) failed.",
                )
                if newly_restarting:
                    metrics.jobs_restarted.inc()
            else:
                self.recorder.event(job, "Warning", st.REASON_JOB_FAILED,
                                    f"TPUJob {job.metadata.name} has failed because "
                                    f"{rs.failed} {rtype} replica(s) failed.")
                st.update_job_conditions(
                    job.status, c.JOB_FAILED, st.REASON_JOB_FAILED,
                    f"TPUJob {job.metadata.name} has failed because "
                    f"{rs.failed} {rtype} replica(s) failed.",
                )
                if job.status.completion_time is None:
                    job.status.completion_time = st.now_iso()
                metrics.jobs_failed.inc()

    # ------------------------------------------------------------------
    # failure paths (controller.go:391-453, 520-568)
    # ------------------------------------------------------------------

    def _past_backoff_limit(self, job: TPUJob, pods: List[Pod]) -> Tuple[bool, str]:
        limit = job.spec.run_policy.backoff_limit
        if limit is None:
            return False, ""
        restarts = 0
        for rtype, rspec in job.spec.tpu_replica_specs.items():
            if rspec.restart_policy in (c.RESTART_POLICY_ON_FAILURE, c.RESTART_POLICY_ALWAYS):
                # kubelet in-place restarts (controller.go:527-533)
                for pod in self.filter_by_replica_type(pods, rtype):
                    for cs in pod.status.container_statuses:
                        restarts += cs.restart_count
            elif rspec.restart_policy == c.RESTART_POLICY_EXIT_CODE:
                # controller-driven recreations, accumulated in status —
                # bounds the TPU-preemption churn loop the reference
                # cannot see (it only counts restartCount, which is 0 on
                # every recreated pod)
                rs = job.status.replica_statuses.get(rtype)
                if rs is not None:
                    restarts += rs.restarts
        if restarts >= limit:
            return True, f"total restart count {restarts} >= backoffLimit {limit}"
        return False, ""

    @staticmethod
    def _backoff_message(job: TPUJob, reason: str) -> str:
        return (f"TPUJob {job.metadata.name} has failed because it has "
                f"reached the specified backoff limit ({reason})")

    def _past_active_deadline(self, job: TPUJob) -> bool:
        ads = job.spec.run_policy.active_deadline_seconds
        start = _parse_time(job.status.start_time)
        if ads is None or start is None:
            return False
        return time.time() - start >= ads

    def _fail_job(self, job: TPUJob, old_status, pods, services, message: str) -> bool:
        logger_for_job(log, job).info(message)
        self._delete_pods_and_services(job, pods, services)
        self.recorder.event(job, "Warning", st.REASON_JOB_FAILED, message)
        if job.status.completion_time is None:
            job.status.completion_time = st.now_iso()
        st.update_job_conditions(job.status, c.JOB_FAILED, st.REASON_JOB_FAILED, message)
        metrics.jobs_failed.inc()
        if self.config.enable_gang_scheduling:
            self._delete_pod_group(job)
        self._persist_status(job, old_status)
        return True

    # ------------------------------------------------------------------
    # cleanup (job.go:153-209)
    # ------------------------------------------------------------------

    def _delete_pods_and_services(self, job: TPUJob, pods: List[Pod], services: List[Service]) -> None:
        policy = job.spec.run_policy.clean_pod_policy or c.CLEAN_POD_POLICY_NONE
        if policy == c.CLEAN_POD_POLICY_NONE:
            return
        for pod in pods:
            # Running policy deletes only phase==Running pods (job.go:165 —
            # exact reference semantics: terminal AND Pending/Unknown pods
            # stay for debugging).  Beyond the reference: a pod already
            # carrying a deletionTimestamp is not re-deleted.
            if policy == c.CLEAN_POD_POLICY_RUNNING and (
                pod.status.phase != "Running" or pod.metadata.deletion_timestamp
            ):
                continue
            try:
                self.pod_control.delete_pod(pod.metadata.namespace, pod.metadata.name, job)
            except NotFoundError:
                pass
        for svc in services:
            try:
                self.service_control.delete_service(svc.metadata.namespace, svc.metadata.name, job)
            except NotFoundError:
                pass

    def _cleanup_ttl(self, job: TPUJob) -> None:
        ttl = job.spec.run_policy.ttl_seconds_after_finished
        if ttl is None:
            return
        finish = _parse_time(job.status.completion_time)
        if finish is None:
            if job.status.completion_time:
                # CORRUPTED completion_time: it can never be measured
                # against the TTL, but re-anchoring to the current time on
                # every sync would requeue every ttl seconds forever and
                # never collect the job.  Anchor at the server-set
                # creationTimestamp instead — collection stays guaranteed
                # and bounded without reaping a long TTL early on one bad
                # write.  If even that is garbage, the object is junk: reap.
                finish = _parse_time(job.metadata.creation_timestamp)
                if finish is None:
                    finish = float("-inf")
            else:
                # no timestamp landed yet: anchor at first observation
                finish = time.time()
        remaining = finish + ttl - time.time()
        if remaining <= 0:
            try:
                self.delete_job_handler(job)
            except NotFoundError:
                pass
        else:
            self.queue.add_after(job.key, remaining)

    # ------------------------------------------------------------------
    # gang scheduling (jobcontroller.go:224-278)
    # ------------------------------------------------------------------

    def _sync_pod_group(self, job: TPUJob) -> None:
        name = gen_pod_group_name(job.metadata.name)
        ns = job.metadata.namespace or "default"
        min_member = get_total_replicas(job)
        sp = job.spec.run_policy.scheduling_policy
        if sp and sp.min_available is not None:
            min_member = sp.min_available
        try:
            existing = self.clients.podgroups.get(ns, name)
            if existing.spec.min_member != min_member:
                existing.spec.min_member = min_member
                self.clients.podgroups.update(existing)
        except NotFoundError:
            pg = PodGroup(
                metadata=ObjectMeta(name=name, namespace=ns, labels=gen_labels(job.metadata.name)),
                spec=PodGroupSpec(min_member=min_member,
                                  queue=sp.queue if sp else None,
                                  priority_class_name=sp.priority_class if sp else None),
            )
            from tpujob.kube.control import gen_owner_reference

            pg.metadata.owner_references.append(gen_owner_reference(job))
            self.clients.podgroups.create(pg)

    def _delete_pod_group(self, job: TPUJob) -> None:
        try:
            self.clients.podgroups.delete(job.metadata.namespace or "default",
                                          gen_pod_group_name(job.metadata.name))
        except NotFoundError:
            pass

    # ------------------------------------------------------------------
    # write-back handlers (injectable for tests)
    # ------------------------------------------------------------------

    def _persist_status(self, job: TPUJob, old_status) -> None:
        """Persist the sync's recomputed status iff it changed.

        ``old_status`` is the informer-cached status snapshotted at sync
        start: when the recomputed object equals it field for field, the
        sync was a pure no-op and nothing is written (counted as
        suppressed).  Anything else goes through the injectable
        ``update_status_handler``, where the semantic diff decides between
        a merge-patch write and suppression of volatile-only refreshes.

        ``status.observedGeneration`` stamps here — the one choke point
        every persisted reconcile status flows through — so a generation
        bump alone (a spec change whose reconcile was otherwise a no-op)
        still registers as a change to write, and drift repair / the flight
        recorder can tell spec changes from status churn."""
        gen = job.metadata.generation
        if gen and job.status.observed_generation != gen:
            prev = job.status.observed_generation
            job.status.observed_generation = gen
            self.flight.record(
                job.key, "spec",
                f"spec generation {prev or 0} -> {gen} processed",
                {"from": prev or 0, "to": gen})
        if job.status == old_status:
            if self.config.suppress_noop_status:
                metrics.status_writes.labels(result="suppressed").inc()
            return
        self._sync_status_base[job.key] = old_status
        try:
            self.update_status_handler(job)
        finally:
            self._sync_status_base.pop(job.key, None)

    def _update_job_status(self, job: TPUJob) -> None:
        with TRACER.span("phase", phase="status_update"):
            self._write_job_status(job)

    def _write_job_status(self, job: TPUJob) -> None:
        deltas = self._restart_deltas.pop(job.key, None)
        if self.config.status_patch and hasattr(
            self.clients.tpujobs.server, "patch_status"
        ):
            self._patch_job_status(job, deltas)
        else:
            self._put_job_status(job, deltas)

    # -- merge-patch write path (the default) ---------------------------

    def _patch_job_status(self, job: TPUJob, deltas: Optional[Dict[str, int]]) -> None:
        """Ship the semantic diff between the recomputed status and the
        informer-cached one as a JSON-merge-patch of /status.

        Three write classes fall out of the diff:

        - **empty diff** — the sync re-derived exactly what the cache (and
          therefore, to our best knowledge, the server) already holds: skip
          the write entirely (``status_writes_total{result="suppressed"}``).
          Terminal transitions (Succeeded/Failed first landing) always
          write through, and a cache that drifted from the recomputed truth
          (a resync repairing a foreign/corrupt status write) diffs nonzero
          by construction — suppression can never swallow either.
        - **derived-fields-only diff** — conditions, phase counters,
          timestamps: patched WITHOUT a resourceVersion precondition.
          Last-writer-wins per key is safe (every such field is recomputed
          from live pods each sync), and the patch no longer 409s against
          concurrent spec/metadata writers the way the full-object PUT did —
          that conflict/refetch/retry loop was pure overhead.
        - **cumulative-counter diff** (``restarts``) — history, not derived
          state: patched WITH the cached resourceVersion.  On conflict the
          executed deletions are rebased onto the freshly read object via a
          restarts-only RV-checked patch (client-go RetryOnConflict
          discipline), never a blind full-object write that could resurrect
          this sync's stale view of everything else.
        """
        ns = job.metadata.namespace or "default"
        name = job.metadata.name
        cached = self.job_informer.store.get(ns, name)
        if not self._same_incarnation(cached, job):
            # the cache now holds a DIFFERENT incarnation of ns/name (the
            # job was deleted and recreated mid-sync): this sync's status —
            # terminal conditions, restart counts — belongs to the dead
            # object and must not be born onto the new one.  The full-object
            # PUT got this protection for free (it carried the dead
            # incarnation's resourceVersion and 409/404'd); the patch path
            # must check identity itself.  The deltas die with the old
            # incarnation, exactly like the NotFound path.
            logger_for_job(log, job).info(
                "job was recreated mid-sync; dropping the stale status write")
            return
        # The diff base MUST be the snapshot this sync was computed FROM
        # (stashed by _persist_status), never a write-time cache re-read:
        # the cache can advance mid-sync — most commonly with the echo of
        # the PREVIOUS sync's own landed write — and diffing the stale
        # recomputation against the fresh base emits explicit null deletes
        # for keys the recomputation never knew about (a just-landed
        # cumulative restarts counter), silently erasing them server-side.
        # The restarts RV guard cannot catch that case: it would assert the
        # very resourceVersion the advanced cache just handed us.  The
        # re-read above serves ONLY the incarnation (uid) check.
        base = self._sync_status_base.get(job.key)
        if base is not None:
            old = base.to_dict()
            base_rv = job.metadata.resource_version
        else:
            # handler invoked directly (tests, custom injectors): fall back
            # to the cache as both diff base and RV source
            old = (cached or {}).get("status")
            old = old if isinstance(old, dict) else {}
            base_rv = ((cached or {}).get("metadata") or {}).get(
                "resourceVersion")
        patch = st.status_merge_patch(old, job.status.to_dict())
        if patch is None:
            # a semantically empty diff can never hide a condition
            # transition (terminal ones included): is_finished depends only
            # on condition type/status, which the volatile strip preserves —
            # equality here implies the cache already shows the same
            # terminal/non-terminal state
            if self.config.suppress_noop_status:
                # the cached status already reflects everything this sync
                # computed — including any carried restart deltas, which are
                # therefore persisted; dropping them here is what retires a
                # delta whose lost-response write actually landed
                metrics.status_writes.labels(result="suppressed").inc()
                return
            # suppression disabled: write the volatile-only drift too, so
            # the cache converges the way it did under a full PUT — a
            # stamp-only patch would leave the refreshed condition
            # timestamps un-persisted and the object-equality gate upstream
            # dirty on every subsequent sync
            patch = st.raw_status_merge_patch(old, job.status.to_dict())
        job.status.last_reconcile_time = st.now_iso()
        patch["lastReconcileTime"] = job.status.last_reconcile_time
        rv = None
        if st.patch_touches_restarts(patch):
            # guard with the RV of the DIFF BASE: a restarts-bearing patch
            # is only valid against the state it was derived from
            rv = base_rv
        try:
            self.clients.tpujobs.patch_status(ns, name, patch, resource_version=rv)
        except NotFoundError:
            return
        except ConflictError:
            logger_for_job(log, job).info(
                "status patch conflicted (stale cache); requeueing")
        except Exception:
            # transient transport failure: the recreations of this sync are
            # already executed — re-stash their deltas so the next sync
            # folds them in instead of silently undercounting
            self._restash_deltas(job, deltas)
            raise
        else:
            self._count_patch_write(patch, job.status.to_dict())
            return
        if deltas:
            self._rebase_restart_deltas(job, deltas)
        # rate-limited, not immediate: the cache stays stale for the whole
        # watch-latency window after the conflicting write, so an immediate
        # requeue would spin patch-409 against the apiserver (client-go
        # RetryOnConflict backs off the same way)
        self.queue.add_rate_limited(job.key)

    @staticmethod
    def _same_incarnation(cached: Optional[Dict], job: TPUJob) -> bool:
        """Whether ``cached`` (the informer's current ns/name entry) is the
        same object incarnation the sync was computed for.  A store miss
        passes — the server's 404 resolves it; missing uids (hand-built test
        objects) pass open."""
        if cached is None:
            return True
        cached_uid = (cached.get("metadata") or {}).get("uid")
        return (not cached_uid or not job.metadata.uid
                or cached_uid == job.metadata.uid)

    @staticmethod
    def _count_patch_write(patch: Dict[str, Any], full: Dict[str, Any]) -> None:
        metrics.status_writes.labels(result="written").inc()
        metrics.status_patch_bytes.inc(
            len(json.dumps(patch, separators=(",", ":"))))
        metrics.status_full_bytes.inc(
            len(json.dumps(full, separators=(",", ":"))))

    def _rebase_restart_deltas(self, job: TPUJob, deltas: Dict[str, int]) -> None:
        """A conflicted restarts write: refetch the fresh object, fold the
        executed deletions onto ITS counters, and ship a restarts-only
        RV-checked patch.  Every other status field is recomputed from pods
        on the requeued sync anyway — writing it from this sync's stale base
        would resurrect exactly the stale fields the 409 protected."""
        ns = job.metadata.namespace or "default"
        name = job.metadata.name
        try:
            for _ in range(3):
                try:
                    fresh = self.clients.tpujobs.get(ns, name)
                except NotFoundError:
                    deltas = None  # job gone: nothing left to count
                    return
                if (job.metadata.uid and fresh.metadata.uid
                        and fresh.metadata.uid != job.metadata.uid):
                    # ns/name was deleted and recreated: the counted
                    # restarts belong to the dead incarnation — folding them
                    # onto the newborn would trip its backoffLimit early
                    deltas = None
                    return
                rebase: Dict[str, Any] = {"replicaStatuses": {}}
                for rtype, d in deltas.items():
                    rs = fresh.status.replica_statuses.get(rtype)
                    base = rs.restarts if rs is not None else 0
                    rebase["replicaStatuses"][rtype] = {"restarts": base + d}
                try:
                    self.clients.tpujobs.patch_status(
                        ns, name, rebase,
                        resource_version=fresh.metadata.resource_version)
                    self._count_patch_write(rebase, fresh.status.to_dict())
                    deltas = None
                    return
                except NotFoundError:
                    deltas = None
                    return
                except ConflictError:
                    continue
        finally:
            # rebase exhausted or died mid-flight (transient transport
            # error): keep the ledger for the next sync
            self._restash_deltas(job, deltas)

    # -- full-object PUT path (status_patch=False, and transports without
    #    the patch verb) --------------------------------------------------

    def _put_job_status(self, job: TPUJob, deltas: Optional[Dict[str, int]]) -> None:
        job.status.last_reconcile_time = st.now_iso()
        try:
            self.clients.tpujobs.update_status(job)
            metrics.status_writes.labels(result="written").inc()
            return
        except NotFoundError:
            return
        except ConflictError:
            # stale informer cache (409 via the RV the status write carries):
            # do NOT clobber the newer status — but the restart increments
            # of THIS sync count real pod deletions that already executed,
            # so rebase them onto the fresh object before requeueing
            # (client-go RetryOnConflict discipline); everything else is
            # recomputed from pods on the requeued sync anyway
            logger_for_job(log, job).info(
                "status write conflicted (stale cache); requeueing")
        except Exception:
            # transient transport failure: the recreations of this sync are
            # already executed — re-stash their deltas so the next sync
            # folds them in instead of silently undercounting
            self._restash_deltas(job, deltas)
            raise
        if deltas:
            try:
                for _ in range(3):
                    try:
                        fresh = self.clients.tpujobs.get(
                            job.metadata.namespace or "default", job.metadata.name)
                    except NotFoundError:
                        deltas = None  # job gone: nothing left to count
                        return
                    if (job.metadata.uid and fresh.metadata.uid
                            and fresh.metadata.uid != job.metadata.uid):
                        deltas = None  # recreated under the same name
                        return
                    for rtype, d in deltas.items():
                        rs = fresh.status.replica_statuses.setdefault(rtype, ReplicaStatus())
                        rs.restarts += d
                    try:
                        self.clients.tpujobs.update_status(fresh)
                        metrics.status_writes.labels(result="written").inc()
                        deltas = None
                        break
                    except NotFoundError:
                        deltas = None
                        return
                    except ConflictError:
                        continue
            finally:
                # rebase exhausted or died mid-flight (transient transport
                # error): keep the ledger for the next sync
                self._restash_deltas(job, deltas)
        # rate-limited, not immediate: the cache stays stale for the whole
        # watch-latency window after the conflicting write, so an immediate
        # requeue would spin PUT-409 against the apiserver (client-go
        # RetryOnConflict backs off the same way)
        self.queue.add_rate_limited(job.key)

    def _restash_deltas(self, job: TPUJob, deltas: Optional[Dict[str, int]]) -> None:
        """Put unpersisted restart deltas back on the ledger — unless the job
        is gone from the informer cache: racing _on_job_delete's cleanup
        would leave a phantom entry that poisons a future job recreated
        under the same namespace/name."""
        if not deltas:
            return
        if self.job_informer.store.get(
                job.metadata.namespace or "default", job.metadata.name) is None:
            return
        self._restart_deltas[job.key] = deltas

    def _delete_job(self, job: TPUJob) -> None:
        self.clients.tpujobs.delete(job.metadata.namespace or "default", job.metadata.name)
        self.recorder.event(job, "Normal", "SuccessfulDeleteJob",
                            f"Deleted job: {job.metadata.name}")
