"""Worker init-container template.

Mirrors reference ``pkg/common/config/config.go:9-30``: a busybox DNS-wait
loop that gates worker startup until the coordinator service resolves, with
a file-based override.
"""
from __future__ import annotations

import copy
import os
import string
from functools import lru_cache
from typing import Dict, List

DEFAULT_INIT_CONTAINER_TEMPLATE = """\
- name: init-tpujob
  image: ${init_image}
  command: ['sh', '-c', 'err=1; for i in $$(seq 100); do if nslookup ${master_addr}; then err=0 && break; fi; echo waiting for ${master_addr}; sleep 2; done; exit $$err']
  resources:
    limits:
      cpu: 100m
      memory: 20Mi
    requests:
      cpu: 50m
      memory: 10Mi
"""

CONFIG_OVERRIDE_PATH = "/etc/config/initContainer.yaml"
DEFAULT_INIT_IMAGE = "alpine:3.10"


def get_init_container_template(override_path: str = CONFIG_OVERRIDE_PATH) -> str:
    if os.path.exists(override_path):
        with open(override_path) as f:
            return f.read()
    return DEFAULT_INIT_CONTAINER_TEMPLATE


@lru_cache(maxsize=1024)
def _render_cached(master_addr: str, init_image: str, template: str):
    import yaml

    tpl = string.Template(template)
    rendered = tpl.safe_substitute(master_addr=master_addr, init_image=init_image)
    return yaml.safe_load(rendered)


def render_init_containers(master_addr: str, init_image: str, template: str | None = None) -> List[Dict]:
    """Render the init-container template (util.go:61-87 equivalent).

    The YAML parse is memoized per (addr, image, template) — it sat on the
    reconcile hot path at ~5 ms per pod build; a template-file override still
    takes effect because the template text is part of the cache key.  The
    result is deep-copied so callers can mutate it freely.
    """
    parsed = _render_cached(master_addr, init_image,
                            template or get_init_container_template())
    return copy.deepcopy(parsed)
