"""Worker init-container template.

Mirrors reference ``pkg/common/config/config.go:9-30``: a busybox DNS-wait
loop that gates worker startup until the coordinator service resolves, with
a file-based override.
"""
from __future__ import annotations

import os
import string
from typing import Dict, List

DEFAULT_INIT_CONTAINER_TEMPLATE = """\
- name: init-tpujob
  image: ${init_image}
  command: ['sh', '-c', 'err=1; for i in $$(seq 100); do if nslookup ${master_addr}; then err=0 && break; fi; echo waiting for ${master_addr}; sleep 2; done; exit $$err']
  resources:
    limits:
      cpu: 100m
      memory: 20Mi
    requests:
      cpu: 50m
      memory: 10Mi
"""

CONFIG_OVERRIDE_PATH = "/etc/config/initContainer.yaml"
DEFAULT_INIT_IMAGE = "alpine:3.10"


def get_init_container_template(override_path: str = CONFIG_OVERRIDE_PATH) -> str:
    if os.path.exists(override_path):
        with open(override_path) as f:
            return f.read()
    return DEFAULT_INIT_CONTAINER_TEMPLATE


def render_init_containers(master_addr: str, init_image: str, template: str | None = None) -> List[Dict]:
    """Render the init-container template (util.go:61-87 equivalent)."""
    import yaml

    tpl = string.Template(template or get_init_container_template())
    rendered = tpl.safe_substitute(master_addr=master_addr, init_image=init_image)
    return yaml.safe_load(rendered)
