"""Per-job workload-progress state: heartbeat ingestion + the stall clock.

The in-memory half of the telemetry plane.  The reconciler feeds each sync's
informer-cached pods through :meth:`ProgressTracker.ingest` (zero extra API
reads) and this module keeps, per job:

- the latest parsed :class:`~tpujob.api.progress.Progress` record and which
  pod published it;
- monotonic anchors for the three ages the watchdog and the ``tpujob_job_*``
  metric families need: last heartbeat *change*, last *step advance*, last
  *checkpoint advance*.  All controller-clock: a heartbeat "arrives" when
  its annotation string changes in the cache, so workload clock skew can
  neither fake nor mask a stall;
- the stall episode state (condition currently True, restart already fired).

Everything here is reconstructed, not durable: a cold-started controller (or
a rebalanced-in shard owner) re-seeds from the pod annotations still on the
cluster and grants the workload one full stall deadline from the moment it
first observes them — exactly the conservative stance of the crash-loop
damper rebuild.  The *Stalled condition* itself is durable in job status;
:meth:`ingest` seeds the episode state from it so a restart never re-fires
the flip (or the restart policy) for a stall already on record.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Tuple

from tpujob.analysis import lockgraph
from tpujob.api.progress import Progress
from tpujob.server import metrics

# ingestion events (returned by ProgressTracker.ingest)
EVENT_FIRST = "first"  # first heartbeat this tracker has seen for the job
EVENT_HEARTBEAT = "heartbeat"  # the annotation string changed
EVENT_ADVANCE = "advance"  # the reported step moved forward
EVENT_CHECKPOINT = "checkpoint"  # the reported checkpoint step advanced


@dataclasses.dataclass
class JobProgress:
    """One job's telemetry state (mutated only under the tracker lock)."""

    namespace: str
    name: str
    shard_label: str  # owning shard at ingest time ('-' when unsharded)
    pod: str  # the pod whose annotation the newest heartbeat came from
    raw: str  # last annotation value (change detector)
    progress: Progress
    first_mono: float
    last_heartbeat_mono: float
    last_advance_mono: float
    last_checkpoint_mono: float
    stalled: bool = False
    restart_fired: bool = False  # restart policy acted this stall episode
    tick_due_mono: Optional[float] = None  # in-flight watchdog tick's due time


class ProgressTracker:
    def __init__(self):
        self._lock = lockgraph.new_lock("progress-tracker")
        self._jobs: Dict[str, JobProgress] = {}  # guarded by self._lock

    # ------------------------------------------------------------------
    # ingestion
    # ------------------------------------------------------------------

    def ingest(
        self,
        key: str,
        namespace: str,
        name: str,
        shard_label: str,
        pod: str,
        raw: str,
        progress: Progress,
        stalled_in_status: bool = False,
        now: Optional[float] = None,
    ) -> Tuple[JobProgress, List[str]]:
        """Fold one observed heartbeat into the job's state and return
        ``(state, events)``.  ``stalled_in_status`` seeds a fresh entry's
        episode state from the durable condition (crash/handoff resume)."""
        now = time.monotonic() if now is None else now
        events: List[str] = []
        with self._lock:
            state = self._jobs.get(key)
            if state is None:
                state = JobProgress(
                    namespace=namespace, name=name, shard_label=shard_label,
                    pod=pod, raw=raw, progress=progress,
                    first_mono=now, last_heartbeat_mono=now,
                    last_advance_mono=now, last_checkpoint_mono=now,
                    stalled=stalled_in_status,
                    # a stall already on record resumes as already-acted:
                    # the restart policy is once per EPISODE, and a
                    # controller restart / shard handoff mid-episode must
                    # not buy the stuck job another pod deletion
                    restart_fired=stalled_in_status,
                )
                self._jobs[key] = state
                return state, [EVENT_FIRST, EVENT_HEARTBEAT]
            state.shard_label = shard_label
            if raw == state.raw:
                return state, events
            prev = state.progress
            events.append(EVENT_HEARTBEAT)
            state.last_heartbeat_mono = now
            if progress.step > prev.step or (
                progress.resize_generation > prev.resize_generation
            ):
                # a new resize epoch counts as progress even when the step
                # regressed to the restore point: the workload just moved
                # through a re-rendezvous, which is the opposite of stuck
                events.append(EVENT_ADVANCE)
                state.last_advance_mono = now
            if (progress.checkpoint_step or 0) > (prev.checkpoint_step or 0):
                events.append(EVENT_CHECKPOINT)
                state.last_checkpoint_mono = now
            state.pod = pod
            state.raw = raw
            state.progress = progress
            return state, events

    def exempt(self, key: str, now: Optional[float] = None) -> None:
        """Push the job's stall deadline: the sync observed an exemption
        window (resize staging, restart, replica churn) during which a
        heartbeat gap proves nothing.  Re-anchoring the advance clock grants
        one full deadline after the window closes."""
        now = time.monotonic() if now is None else now
        with self._lock:
            state = self._jobs.get(key)
            if state is not None:
                state.last_advance_mono = now

    # ------------------------------------------------------------------
    # watchdog state
    # ------------------------------------------------------------------

    def get(self, key: str) -> Optional[JobProgress]:
        with self._lock:
            return self._jobs.get(key)

    def stall_age(self, key: str, now: Optional[float] = None) -> Optional[float]:
        """Seconds since the job's step last advanced (None = no telemetry)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            state = self._jobs.get(key)
            if state is None:
                return None
            return max(0.0, now - state.last_advance_mono)

    def mark_stalled(self, key: str, stalled: bool) -> None:
        with self._lock:
            state = self._jobs.get(key)
            if state is None:
                return
            state.stalled = stalled
            if not stalled:
                state.restart_fired = False

    def note_restart_fired(self, key: str) -> None:
        with self._lock:
            state = self._jobs.get(key)
            if state is not None:
                state.restart_fired = True

    def arm_tick(self, key: str, interval: float,
                 now: Optional[float] = None) -> bool:
        """Claim the job's watchdog tick: True = the caller should schedule
        one requeue ``interval`` out.  At most ONE tick chain lives per job
        — the workqueue's delayed heap does not dedupe pending entries, so
        an unconditional per-sync requeue would spawn a new immortal timer
        chain per heartbeat event and self-amplify the sync rate without
        bound.  A tick is re-armable only once its due time passed (the
        sync the timer itself fired, or a later one)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            state = self._jobs.get(key)
            if state is None:
                return False
            if state.tick_due_mono is not None and now < state.tick_due_mono:
                return False  # a live tick already covers this window
            state.tick_due_mono = now + interval
            return True

    # ------------------------------------------------------------------
    # lifecycle / export
    # ------------------------------------------------------------------

    def forget(self, key: str) -> Optional[JobProgress]:
        """Drop one job's state (finished/deleted job) and its metric
        series; returns the dropped state."""
        with self._lock:
            state = self._jobs.pop(key, None)
        if state is not None:
            clear_job_series(state)
        return state

    def forget_shard(self, shard_label: str) -> List[JobProgress]:
        """Drop every job of a handed-off shard (and its series): the new
        owner re-seeds from the annotations, and two members must never
        export the same job — that is the scrape-merge partition invariant."""
        with self._lock:
            keys = [k for k, s in self._jobs.items()
                    if s.shard_label == shard_label]
            dropped = [self._jobs.pop(k) for k in keys]
        for state in dropped:
            clear_job_series(state)
        return dropped

    def export(self, key: str, now: Optional[float] = None) -> None:
        """Refresh the job's ``tpujob_job_*`` gauge children.

        The sets run UNDER the tracker lock: ``labels()`` re-creates a
        removed child on demand, so a set racing ``forget``/``forget_shard``
        (whose pop also holds this lock) could otherwise resurrect a
        just-cleared series — a permanently stale export, and on shard
        handoff a violation of the one-exporter-per-job partition
        invariant.  Lock order tracker -> family is one-way (nothing under
        the family locks ever takes the tracker lock)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            state = self._jobs.get(key)
            if state is None:
                return
            labels = dict(namespace=state.namespace, job=state.name,
                          shard=state.shard_label)
            prog = state.progress
            metrics.job_steps.labels(**labels).set(float(prog.step))
            metrics.job_samples_per_second.labels(**labels).set(
                float(prog.samples_per_sec or 0.0))
            metrics.job_heartbeat_age.labels(**labels).set(
                round(max(0.0, now - state.last_heartbeat_mono), 3))
            metrics.job_checkpoint_age.labels(**labels).set(
                round(max(0.0, now - state.last_checkpoint_mono), 3))
            metrics.job_stalled.labels(**labels).set(
                1.0 if state.stalled else 0.0)

    @staticmethod
    def _row(key: str, s: JobProgress, now: float) -> Dict[str, Any]:  # caller holds self._lock
        return {
            "job": key,
            "shard": s.shard_label,
            "pod": s.pod,
            "step": s.progress.step,
            "samples_per_sec": s.progress.samples_per_sec,
            "checkpoint_step": s.progress.checkpoint_step,
            "resize_generation": s.progress.resize_generation,
            "heartbeat_age_s": round(
                max(0.0, now - s.last_heartbeat_mono), 3),
            "advance_age_s": round(
                max(0.0, now - s.last_advance_mono), 3),
            "stalled": s.stalled,
        }

    def row(self, key: str, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """One job's progress row (the /debug/jobs status-block half) —
        O(1), not a full-fleet snapshot under the sync path's lock."""
        now = time.monotonic() if now is None else now
        with self._lock:
            state = self._jobs.get(key)
            if state is None:
                return None
            return self._row(key, state, now)

    def snapshot(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """The ``/debug/fleet`` rows: one dict per tracked job."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return [self._row(key, s, now)
                    for key, s in sorted(self._jobs.items())]


def clear_job_series(state: JobProgress) -> None:
    """Remove the job's children from every ``tpujob_job_*`` family."""
    labels = dict(namespace=state.namespace, job=state.name,
                  shard=state.shard_label)
    for family in (metrics.job_steps, metrics.job_samples_per_second,
                   metrics.job_checkpoint_age, metrics.job_heartbeat_age,
                   metrics.job_stalled):
        family.remove(**labels)
