"""The TPUJob reconciler and its supporting machinery."""
