"""Shared checkpoint-barrier ladder for every staged vacate protocol.

Five protocols drive a workload through the same publish -> checkpoint
barrier -> act ladder: the elastic-resize drain (PR 9), the scheduler's
capacity preemption (PR 11), node-repair gang migration (PR 12), and the
elastic-capacity optimizer's num_slices flex + torus-defrag moves.  Each
used to hand-roll the same three pieces; this module is the one copy:

- **Patch builders** (:func:`resize_target_patch`,
  :func:`preempt_target_patch`): publish the target AND consume any stale
  ack in the SAME merge-patch dict (the TPL200 consume-at-publish rule) —
  an ack left behind by a previous episode must never let THIS episode's
  barrier pass before the workload checkpoints.
- **The barrier judge** (:func:`barrier_passed`): ack wins immediately;
  otherwise a per-incarnation monotonic anchor grants the workload up to
  one grace period from when THIS incarnation first looked, floored by the
  durable published-at wall timestamp so a barrier already pending across
  a crash/handoff proceeds at once instead of re-granting a fresh grace.
  Fails open on a corrupt durable anchor — every barrier exists to bound
  progress loss, never to wedge its protocol.
- **The sent ledger** (:class:`SentLedger`): committed-but-not-yet-echoed
  write dedup (the ``_release_sent`` discipline generalized).  A tick that
  rebuilds from a cache trailing its own committed write must neither
  re-issue the patch (write amplification; worse, a re-published target
  wipes an ack the workload just wrote) nor treat the write as absent.

Callers keep their protocol-specific edges: the scheduler's preemption
barrier FAILS CLOSED until its publish echoes into the cache (the grace
clock starts at the echo), and treats telemetry whose checkpoint caught up
to the step as an implicit ack; the resize drain acks with the target
world size rather than a bare marker.  Both reduce to the same judge.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional

from tpujob.api import constants as c
from tpujob.controller import status as st


def resize_target_patch(target_world: int) -> Dict[str, Optional[str]]:
    """The drain barrier's publish: the pending world size the workload
    must checkpoint for, consuming any stale checkpoint-ack in the same
    patch (a later shrink to the SAME target must run its own barrier)."""
    return {
        c.ANNOTATION_TARGET_WORLD_SIZE: str(target_world),
        c.ANNOTATION_CHECKPOINT_ACK: None,
    }


def preempt_target_patch(
    extra: Optional[Dict[str, Optional[str]]] = None,
) -> Dict[str, Optional[str]]:
    """The eviction barrier's publish: preempt-target stamped now, the
    paired ack consumed in the same patch.  ``extra`` rides the same
    merge-patch (the migration's ``migrated-from`` record, the defrag
    move's marker) so the whole decision commits atomically."""
    patch: Dict[str, Optional[str]] = {
        c.ANNOTATION_PREEMPT_TARGET: st.now_iso(),
        c.ANNOTATION_PREEMPT_ACK: None,
    }
    if extra:
        patch.update(extra)
    return patch


def barrier_passed(
    anchors: Dict[str, float],
    key: str,
    grace_s: float,
    acked: bool,
    published_wall: Optional[float],
    now_mono: float,
    now_wall: float,
) -> bool:
    """One checkpoint-barrier verdict.

    ``anchors`` is the caller's per-incarnation monotonic anchor map
    (mutated: the first look at a pending barrier plants ``now_mono``);
    ``published_wall`` is the durable publish instant parsed from the
    annotation/status record (None = corrupt or absent — fail open, the
    barrier bounds loss).  The +1.0s on the wall floor covers the persisted
    timestamp's one-second granularity, exactly like the resize drain and
    active-deadline floors.
    """
    if grace_s <= 0:
        return True
    if acked:
        return True
    anchor = anchors.setdefault(key, now_mono)
    if now_mono - anchor >= grace_s:
        return True
    if published_wall is None:
        return True  # corrupt durable anchor: fail open, the barrier bounds loss
    return now_wall - published_wall >= grace_s + 1.0  # noqa: TPL004 - wall-vs-persisted timestamp math, the shared crash-resilient floor


class SentLedger:
    """Committed-but-unechoed write dedup, keyed by the value written.

    ``record`` after the patch commits; ``sent`` answers whether the SAME
    write is already in flight (so the tick neither re-issues it nor
    treats it as absent); ``retire`` when the cache echo lands (or shows
    the job gone).  ``prune`` keeps the map bounded to live keys — the
    PR-3 ledger-hygiene stance — and ``clear`` drops everything on duty
    handoff (another member owns the protocol now; the durable annotations
    are the truth a regained duty rebuilds from).
    """

    def __init__(self) -> None:
        self._sent: Dict[str, str] = {}

    def __len__(self) -> int:
        return len(self._sent)

    def __contains__(self, key: str) -> bool:
        return key in self._sent

    def sent(self, key: str, value: str = "") -> bool:
        return self._sent.get(key) == value

    def value(self, key: str) -> Optional[str]:
        """The in-flight value for ``key`` (None = nothing in flight):
        until the echo lands, the caller's view of the field is the value
        it committed, not the stale cache's."""
        return self._sent.get(key)

    def record(self, key: str, value: str = "") -> None:
        self._sent[key] = value

    def retire(self, key: str) -> None:
        self._sent.pop(key, None)

    def prune(self, live: Iterable[str]) -> None:
        keep = set(live)
        for key in [k for k in self._sent if k not in keep]:
            self._sent.pop(key, None)

    def clear(self) -> None:
        self._sent.clear()
