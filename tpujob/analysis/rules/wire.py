"""TPL200: annotation wire-protocol conformance.

The operator and its workloads talk through ``tpujob.dev/*`` annotations —
the resize channel (target-world-size / checkpoint-ack), the scheduler
channel (preempt-target / preempt-ack), node heartbeats, migration
markers.  Three invariants keep those channels honest:

1. **Paired ends.**  Every registered key has at least one publisher
   (a dict-literal write or subscript store with a real value) AND at
   least one consumer (a read) somewhere in the shipped tree, the e2e
   harnesses, or the benches.  A key with one end missing is a protocol
   half nobody answers — exactly how a deleted ack consumer ships.
2. **No raw spellings.**  ``tpujob.dev/...`` string literals outside the
   constants/API modules are violations: the workload and controller
   halves can only stay in agreement if both import the spelling from
   ``api/constants.py``.  Docstrings are prose, not wire traffic.
3. **Consume-at-publish.**  Ack keys (``checkpoint-ack``,
   ``preempt-ack``) must be nulled in the SAME patch dict that publishes
   their paired target.  Publishing a new target while a stale ack is
   still standing lets the controller read last epoch's ack as this
   epoch's answer (the bug class re-fixed in PRs 9 and 11).

All three run off the shared wire registry (one project-wide extraction
pass; see tpujob/analysis/registry.py).  ``tests/`` is out of scope —
fixtures legitimately spell raw strings and fake half-channels.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Tuple

from tpujob.analysis.engine import Finding, Project, Rule
from tpujob.analysis.registry import (
    CONSTANTS_MODULE, KEY_MODULES, in_wire_scope, wire_registry)

# paired target -> ack constant names (the consume-at-publish pairs)
ACK_PAIRS: Dict[str, str] = {
    "ANNOTATION_TARGET_WORLD_SIZE": "ANNOTATION_CHECKPOINT_ACK",
    "ANNOTATION_PREEMPT_TARGET": "ANNOTATION_PREEMPT_ACK",
}

# an exact wire key, not prose that merely mentions the group
_RAW_KEY_RE = re.compile(r"^tpujob\.dev(/[A-Za-z0-9_.\-]+)?$")


class AnnotationProtocolRule(Rule):
    id = "TPL200"
    name = "annotation-protocol-conformance"
    rationale = ("every wire key needs a publisher and a consumer; raw "
                 "tpujob.dev literals and un-nulled acks skew the "
                 "controller/workload protocol")

    def check_project(self, project: Project) -> Iterable[Finding]:
        reg = wire_registry(project)
        if not reg.annotations or project.context(CONSTANTS_MODULE) is None:
            return ()  # not this tree (fixture dirs, partial checkouts)
        out: List[Finding] = []
        self._check_pairing(project, reg, out)
        self._check_raw_literals(project, out)
        self._check_consume_at_publish(project, reg, out)
        return out

    # -- invariant 1: every key has both ends ------------------------------

    def _check_pairing(self, project, reg, out: List[Finding]) -> None:
        for rec in sorted(reg.annotations.values(), key=lambda a: a.const):
            if not rec.publishes:
                out.append(Finding(
                    self.id, rec.module, rec.line,
                    f"wire key {rec.key} ({rec.const}) has no publisher "
                    f"anywhere in the tree — dead protocol half "
                    f"(readers: {len(rec.reads)})"))
            if not rec.reads:
                out.append(Finding(
                    self.id, rec.module, rec.line,
                    f"wire key {rec.key} ({rec.const}) has no consumer "
                    f"anywhere in the tree — published into the void "
                    f"(publishers: {len(rec.publishes)})"))

    # -- invariant 2: no raw tpujob.dev spellings --------------------------

    def _check_raw_literals(self, project, out: List[Finding]) -> None:
        for ctx in project.contexts():
            if ctx.rel in KEY_MODULES or not in_wire_scope(ctx.rel):
                continue
            parents = ctx.parents()
            for node in ast.walk(ctx.tree):
                if not (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and _RAW_KEY_RE.match(node.value)):
                    continue
                # statement-level string constants are docstrings/prose
                if isinstance(parents.get(node), ast.Expr):
                    continue
                out.append(Finding(
                    self.id, ctx.rel, node.lineno,
                    f"raw wire-key literal {node.value!r} — import the "
                    f"spelling from tpujob.api.constants so both protocol "
                    f"halves share one source of truth"))

    # -- invariant 3: consume-at-publish on ack pairs ----------------------

    def _check_consume_at_publish(self, project, reg,
                                  out: List[Finding]) -> None:
        wanted = set(ACK_PAIRS) | set(ACK_PAIRS.values())
        for ctx in project.contexts():
            if ctx.rel in KEY_MODULES or not in_wire_scope(ctx.rel):
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Dict):
                    continue
                keys = self._const_keys(node, wanted)
                for target, ack in ACK_PAIRS.items():
                    if target not in keys:
                        continue
                    value = node.values[keys[target]]
                    if isinstance(value, ast.Constant) and value.value is None:
                        continue  # nulling the target is cleanup, not publish
                    if ack not in keys:
                        out.append(Finding(
                            self.id, ctx.rel, node.lineno,
                            f"publishes {target} without nulling {ack} in "
                            f"the same patch — a stale ack from the last "
                            f"epoch stays readable (consume-at-publish)"))
                        continue
                    ack_value = node.values[keys[ack]]
                    if not (isinstance(ack_value, ast.Constant)
                            and ack_value.value is None):
                        out.append(Finding(
                            self.id, ctx.rel, node.lineno,
                            f"writes {ack} alongside {target} but not to "
                            f"None — only the workload may publish acks; "
                            f"the controller's job is to null them"))

    @staticmethod
    def _const_keys(node: ast.Dict, wanted) -> Dict[str, int]:
        """Map of annotation-constant key name -> index in the dict literal."""
        found: Dict[str, int] = {}
        for i, key in enumerate(node.keys):
            if isinstance(key, ast.Attribute) and key.attr in wanted:
                found[key.attr] = i
            elif isinstance(key, ast.Name) and key.id in wanted:
                found[key.id] = i
        return found


RULES: Tuple[Rule, ...] = (AnnotationProtocolRule(),)
