"""TPL003: guarded-by discipline for lock-protected attributes.

Shared mutable state in this codebase is documented at its birth site::

    self._events = deque(maxlen=tail)  # guarded by self._lock

This rule makes the comment enforceable: within the declaring class, every
read/write of an annotated attribute must sit lexically inside a
``with self._lock:`` block naming the SAME lock.  This is the bug class of
PR 2's backoff-map rebind race and PR 3's timeline-seq fix — shared state
touched outside its lock, found by review instead of tooling.

Escapes (all greppable, all reviewed):

- ``__init__`` / ``__new__`` bodies are exempt: construction
  happens-before any concurrent access;
- a method named ``*_locked`` asserts "caller holds the lock"
  (``_emit_bookmarks_locked`` convention);
- a method whose ``def`` line carries ``# caller holds self._lock``
  asserts the same for helpers that predate the naming convention;
- ``# noqa: TPL003`` on the access line for individually-justified
  benign races (e.g. a double-checked fast-path read).

Lexical scoping is deliberate: a nested function defined inside a ``with``
block does NOT inherit the lock (it runs later, on whatever thread calls
it), so the checker resets held-locks when descending into nested defs.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from tpujob.analysis.engine import FileContext, Finding, Rule, dotted_name

_GUARDED_RE = re.compile(r"#\s*guarded by\s+(self\.[A-Za-z_][A-Za-z0-9_.]*)")
_CALLER_HOLDS_RE = re.compile(
    r"#\s*caller holds\s+(self\.[A-Za-z_][A-Za-z0-9_.]*)")


def _annotations(cls: ast.ClassDef, ctx: FileContext) -> Dict[str, str]:
    """attr name -> lock expr, from ``self.X = ...  # guarded by self.L``
    comments anchored at real assignment nodes (docstring text can't
    accidentally annotate)."""
    out: Dict[str, str] = {}
    for node in ast.walk(cls):
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        m = _GUARDED_RE.search(ctx.line(node.lineno))
        if not m:
            continue
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                out[t.attr] = m.group(1)
    return out


def _caller_holds(func: ast.AST, ctx: FileContext) -> Set[str]:
    """Lock exprs a ``# caller holds self.X`` waiver on the def line (or
    the line above it) grants to the whole method body."""
    out: Set[str] = set()
    for lineno in (func.lineno, func.lineno - 1):
        m = _CALLER_HOLDS_RE.search(ctx.line(lineno))
        if m:
            out.add(m.group(1))
    return out


class _MethodCheck:
    def __init__(self, rel: str, annotated: Dict[str, str],
                 assumed_held: Set[str]):
        self.rel = rel
        self.annotated = annotated
        self.assumed = assumed_held
        self.findings: List[Finding] = []

    def run(self, func: ast.AST) -> None:
        for stmt in getattr(func, "body", []):
            self._walk(stmt, set(self.assumed))

    def _walk(self, node: ast.AST, held: Set[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested function runs LATER: it does not inherit the lock
            for child in ast.iter_child_nodes(node):
                self._walk(child, set(self.assumed))
            return
        if isinstance(node, ast.With):
            for item in node.items:
                expr = dotted_name(item.context_expr)
                if expr is not None and expr in self.annotated.values():
                    held = held | {expr}
            for child in node.body:
                self._walk(child, held)
            for item in node.items:  # the lock exprs themselves
                self._walk(item.context_expr, held)
            return
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in self.annotated):
            lock = self.annotated[node.attr]
            if lock not in held:
                access = ("write" if isinstance(node.ctx, (ast.Store, ast.Del))
                          else "read")
                self.findings.append(Finding(
                    "TPL003", self.rel, node.lineno,
                    f"{access} of self.{node.attr} outside `with {lock}:` "
                    f"(annotated '# guarded by {lock}')"))
        for child in ast.iter_child_nodes(node):
            self._walk(child, held)


class GuardedByRule(Rule):
    id = "TPL003"
    name = "guarded-by"
    rationale = ("shared state touched outside its documented lock — the "
                 "PR 2 backoff-map rebind and PR 3 timeline-seq race class")
    scope = ("tpujob/",)

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for cls in [n for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef)]:
            annotated = _annotations(cls, ctx)
            if not annotated:
                continue
            for func in cls.body:
                if not isinstance(func, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if func.name in ("__init__", "__new__"):
                    continue  # construction happens-before sharing
                if func.name.endswith("_locked"):
                    continue  # caller-holds naming convention
                assumed = _caller_holds(func, ctx)
                check = _MethodCheck(ctx.rel, annotated, assumed)
                check.run(func)
                out.extend(check.findings)
        return out


RULES: Tuple[Rule, ...] = (GuardedByRule(),)
