"""TPL202: condition lifecycle — everything set True must terminally flip.

``status.set_condition`` owns the job condition machine: when a job
reaches Succeeded/Failed it flips every still-live condition False (the
terminal flip tuple) so no consumer ever observes ``Running=True`` on a
finished job.  That guarantee is only as good as the tuple's coverage:
a NEW condition constant set True anywhere in the controller that is
missing from the tuple outlives completion silently — kubectl waits hang,
the scheduler double-counts live gangs, dashboards show phantom state.

The rule reads the wire registry's condition pass: every ``JOB_*``
constant passed to ``update_job_conditions`` in the shipped tree must
either appear in the terminal flip tuple, be the terminal pair itself
(Succeeded/Failed), or carry an inline ``# noqa: TPL202`` waiver stating
WHY the condition legitimately outlives completion (the waiver text lives
next to the call, where the next editor will read it).
"""
from __future__ import annotations

from typing import Iterable, List, Tuple

from tpujob.analysis.engine import Finding, Project, Rule
from tpujob.analysis.registry import STATUS_MODULE, wire_registry

# the terminal pair is what CAUSES the flip; it cannot flip itself
_TERMINAL = frozenset({"JOB_SUCCEEDED", "JOB_FAILED"})


class ConditionLifecycleRule(Rule):
    id = "TPL202"
    name = "condition-lifecycle"
    rationale = ("a condition set True but missing from the terminal "
                 "flip-False tuple outlives job completion silently")

    def check_project(self, project: Project) -> Iterable[Finding]:
        reg = wire_registry(project)
        cond = reg.conditions
        if not cond.flip_line or project.context(STATUS_MODULE) is None:
            return ()  # not this tree (fixture dirs, partial checkouts)
        out: List[Finding] = []
        for const, sites in sorted(cond.set_true.items()):
            if const in _TERMINAL or const in cond.terminal_flip:
                continue
            for path, line in sites:
                out.append(Finding(
                    self.id, path, line,
                    f"condition {const} is set True here but missing from "
                    f"the terminal flip-False tuple "
                    f"({STATUS_MODULE}:{cond.flip_line}) — it will survive "
                    f"job completion; add it to the tuple or waive with "
                    f"`# noqa: TPL202` stating why it outlives the job"))
        return out


RULES: Tuple[Rule, ...] = (ConditionLifecycleRule(),)
