"""TPL203: expectation bookkeeping — pod churn goes through PodControl.

Every pod create/delete in ``tpujob/controller/`` must flow through the
``PodControl`` ladder (``self.pod_control.create_pod/create_pods/
delete_pod``) or the shared ``_delete_pod_no_strike`` wrapper, because
that ladder is where the informer-lag expectations are raised and cleared
(adds/dels accounting).  A raw transport call — a bare ``create_pod``
import, a generic ``client.create("pods", ...)`` — creates or deletes a
pod the expectation tracker never hears about, which is exactly the
double-create-under-informer-lag bug class fixed in PRs 1/2 and re-fixed
in PR 11.

The rule reads the wire registry's pod-call pass: every create/delete
call site in the controller package whose receiver is not a
``pod_control`` handle is a violation.  ``PodControl`` itself lives in
``tpujob/kube/control.py`` — outside the scanned package — so the
ladder's own transport calls are not self-flagging.
"""
from __future__ import annotations

from typing import Iterable, List, Tuple

from tpujob.analysis.engine import Finding, Project, Rule
from tpujob.analysis.registry import wire_registry


class ExpectationBookkeepingRule(Rule):
    id = "TPL203"
    name = "expectation-bookkeeping"
    rationale = ("pod create/delete outside the PodControl ladder skips "
                 "expectation accounting: the double-create bug class")

    def check_project(self, project: Project) -> Iterable[Finding]:
        reg = wire_registry(project)
        out: List[Finding] = []
        for site in reg.pod_calls:
            if site.receiver is not None \
                    and site.receiver.split(".")[-1] == "pod_control":
                continue
            out.append(Finding(
                self.id, site.path, site.line,
                f"raw pod churn: {site.receiver or '<bare>'}"
                f".{site.method} bypasses the PodControl expectation "
                f"ladder — route through self.pod_control (or "
                f"_delete_pod_no_strike for non-strike deletes) so "
                f"informer-lag accounting sees it"))
        return out


RULES: Tuple[Rule, ...] = (ExpectationBookkeepingRule(),)
