"""TPL002: transport-stack verb completeness (cross-module analysis).

Every API verb on the transport protocol must be handled by EVERY layer of
the transport stack — the bug class that needed late fixes twice
(``patch_status`` missing wrapper coverage in PR 5, ``list_page`` needing
late KillSwitch/RateLimited coverage in PR 6).  A verb added to one layer
and missing from another silently changes semantics: a severed transport
that still serves it, a rate limiter that doesn't charge it, a fence that
doesn't reject it, a chaos schedule that never faults it.

The verb UNIVERSE is computed, not hardcoded: the union of every layer's
handled verbs plus every ``self.server.<verb>()`` call the typed clients
(``tpujob/kube/client.py``) make, filtered by the verb grammar
``(create|get|list|update|patch|delete|watch)(_suffix)*``.  Adding a new
verb anywhere grows the universe and flags every other layer until it is
handled (or exempted here, with a rationale).

Layers and how "handled" is read off their AST:

- ``InMemoryAPIServer`` / ``KubeApiTransport`` / ``KillSwitchTransport`` /
  ``FencedTransport`` / ``TracingTransport`` / ``FaultInjectingAPIServer``
  — an explicitly defined method (``__getattr__`` passthrough does NOT
  count: KillSwitch must sever it, Fenced must classify it, Tracing must
  span it, chaos must schedule it);
- ``RateLimitedTransport`` — membership in its ``_LIMITED`` frozenset;
- chaos ``MUTATING_VERBS`` — the tuple must equal the universe minus the
  read verbs (``READ_VERBS`` below is the rule's read/mutate
  classification: a brand-new verb must be added either there, with
  review, or to ``MUTATING_VERBS``).

Documented exemptions: ``watch`` opens a stream — client-go exempts
long-running requests from rate limiting, and the REST transports span
watch traffic inside the stream instead of around the open.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from tpujob.analysis.engine import FileContext, Finding, Project, Rule

VERB_RE = re.compile(r"^(create|get|list|update|patch|delete|watch)(_[a-z0-9]+)*$")

# the rule's read/mutate classification; a new verb missing from both this
# set and chaos MUTATING_VERBS is reported until a human classifies it
READ_VERBS: FrozenSet[str] = frozenset({"get", "list", "list_page", "watch"})

# (module path, class name, extraction kind, exempt verbs)
LAYERS: Tuple[Tuple[str, str, str, FrozenSet[str]], ...] = (
    ("tpujob/kube/memserver.py", "InMemoryAPIServer", "methods", frozenset()),
    ("tpujob/kube/kubetransport.py", "KubeApiTransport", "methods", frozenset()),
    ("tpujob/kube/fencing.py", "KillSwitchTransport", "methods", frozenset()),
    ("tpujob/kube/fencing.py", "FencedTransport", "methods", frozenset()),
    # watches stream outside the token bucket (client-go exempts
    # long-running requests) and outside the per-call api span
    ("tpujob/kube/ratelimit.py", "RateLimitedTransport", "limited", frozenset({"watch"})),
    ("tpujob/obs/trace.py", "TracingTransport", "methods", frozenset({"watch"})),
    ("tpujob/kube/chaos.py", "FaultInjectingAPIServer", "methods", frozenset()),
)
CLIENT_MODULE = "tpujob/kube/client.py"
CHAOS_MODULE = "tpujob/kube/chaos.py"


def _find_class(ctx: FileContext, name: str) -> Optional[ast.ClassDef]:
    for node in ctx.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


def _verb_methods(cls: ast.ClassDef) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if VERB_RE.match(node.name):
                out[node.name] = node.lineno
    return out


def _limited_set(cls: ast.ClassDef) -> Tuple[Set[str], int]:
    """The string constants of the class's ``_LIMITED`` assignment."""
    for node in cls.body:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "_LIMITED":
                verbs = {c.value for c in ast.walk(node)
                         if isinstance(c, ast.Constant)
                         and isinstance(c.value, str)}
                return verbs, node.lineno
    return set(), cls.lineno


def _module_tuple(ctx: FileContext, name: str) -> Tuple[Set[str], int]:
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    verbs = {c.value for c in ast.walk(node.value)
                             if isinstance(c, ast.Constant)
                             and isinstance(c.value, str)}
                    return verbs, node.lineno
    return set(), 1


def _client_verbs(ctx: FileContext) -> Set[str]:
    """Every ``self.server.<verb>(...)`` / ``<x>.server.<verb>(...)`` call
    the typed clients make — the protocol as actually consumed."""
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Attribute)
                and func.value.attr == "server"
                and VERB_RE.match(func.attr)):
            out.add(func.attr)
    return out


class TransportCompletenessRule(Rule):
    id = "TPL002"
    name = "transport-stack-completeness"
    rationale = ("a verb handled by some wrapper layers but not others "
                 "silently bypasses severing/fencing/rate limiting/tracing/"
                 "chaos (PR 5 patch_status, PR 6 list_page)")

    def check_project(self, project: Project) -> Iterable[Finding]:
        layers: List[Tuple[str, str, Set[str], FrozenSet[str], int]] = []
        missing_modules = 0
        for rel, cls_name, kind, exempt in LAYERS:
            ctx = project.context(rel)
            if ctx is None:
                missing_modules += 1
                continue
            cls = _find_class(ctx, cls_name)
            if cls is None:
                yield Finding(self.id, rel, 1,
                              f"transport layer class {cls_name} not found")
                continue
            if kind == "limited":
                verbs, line = _limited_set(cls)
            else:
                methods = _verb_methods(cls)
                verbs, line = set(methods), cls.lineno
            layers.append((rel, cls_name, verbs, exempt, line))
        if missing_modules == len(LAYERS):
            return  # not this tree (fixture dirs, partial checkouts)

        universe: Set[str] = set()
        for _, _, verbs, _, _ in layers:
            universe |= verbs
        client_ctx = project.context(CLIENT_MODULE)
        if client_ctx is not None:
            universe |= _client_verbs(client_ctx)
        chaos_ctx = project.context(CHAOS_MODULE)
        mutating: Set[str] = set()
        mutating_line = 1
        if chaos_ctx is not None:
            mutating, mutating_line = _module_tuple(chaos_ctx, "MUTATING_VERBS")
            universe |= mutating

        for rel, cls_name, verbs, exempt, line in layers:
            for verb in sorted(universe - verbs - exempt):
                yield Finding(
                    self.id, rel, line,
                    f"{cls_name} does not handle transport verb {verb!r} "
                    f"(universe: {', '.join(sorted(universe))})")

        if chaos_ctx is not None:
            expected_mutating = universe - READ_VERBS
            for verb in sorted(expected_mutating - mutating):
                yield Finding(
                    self.id, CHAOS_MODULE, mutating_line,
                    f"MUTATING_VERBS is missing {verb!r} (every non-read "
                    "verb must be faultable; if it IS a read, add it to "
                    "READ_VERBS in this rule with review)")
            for verb in sorted(mutating & READ_VERBS):
                yield Finding(
                    self.id, CHAOS_MODULE, mutating_line,
                    f"MUTATING_VERBS contains read verb {verb!r}")


RULES: Tuple[Rule, ...] = (TransportCompletenessRule(),)
