"""tpulint rule plugins.

Every module in this package that defines a ``RULES`` list is auto-loaded
by :func:`tpujob.analysis.engine.load_rules`.  Adding a rule = dropping a
module here with a ``Rule`` subclass; no registry edits.
"""
