"""The legacy ``scripts/lint.py`` checks, ported as engine rules.

TPL100 unused-import (the bug class the round-1 advisor actually found) and
TPL101 whitespace hygiene.  Syntax errors are engine-level (TPL000): a file
that does not parse produces no AST for ANY rule, so the engine reports it
while building the project.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from tpujob.analysis.engine import FileContext, Finding, Rule


class UnusedImportRule(Rule):
    id = "TPL100"
    name = "unused-import"
    rationale = ("an import nobody references is dead weight and hides "
                 "real dependency drift; __init__.py re-export surfaces "
                 "are exempt")
    noqa_aliases = ("F401",)  # ruff/flake8 spelling, used across the repo

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.path.name == "__init__.py":
            return []  # re-export surface
        imported = {}  # local name -> (lineno, shown name)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.partition(".")[0]
                    imported[local] = (node.lineno, a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue  # compiler directive, not a binding
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    imported[local] = (node.lineno, a.name)

        used = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                base = node
                while isinstance(base, ast.Attribute):
                    base = base.value
                if isinstance(base, ast.Name):
                    used.add(base.id)
        # names referenced in __all__ strings or docstring doctests count
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                used.update(w for w in imported if w in node.value.split())

        out: List[Finding] = []
        for local, (lineno, shown) in sorted(
                imported.items(), key=lambda kv: kv[1][0]):
            if local in used:
                continue
            out.append(Finding(self.id, ctx.rel, lineno,
                               f"unused import {shown!r}"))
        return out


class WhitespaceRule(Rule):
    id = "TPL101"
    name = "whitespace"
    rationale = "tabs and trailing whitespace churn diffs and reviews"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for i, line in enumerate(ctx.lines, 1):
            if "\t" in line:
                out.append(Finding(self.id, ctx.rel, i, "tab character"))
            if line != line.rstrip():
                out.append(Finding(self.id, ctx.rel, i,
                                   "trailing whitespace"))
        return out


RULES: Tuple[Rule, ...] = (UnusedImportRule(), WhitespaceRule())
