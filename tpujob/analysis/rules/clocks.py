"""TPL004: wall-clock arithmetic where the monotonic clock is required.

``time.time()`` jumps — NTP steps, VM migration, leap smearing.  Any
duration or deadline computed from it can fire early, late, or never;
``time.monotonic()`` is the duration clock (the repo already uses it in
~27 places).  This rule flags ``time.time()`` appearing as an operand of
arithmetic (``BinOp``) or a comparison — the shapes deadlines are built
from — across the control-plane packages.

NOT flagged: bare ``time.time()`` reads stored or formatted as wall-clock
*timestamps* (trace span starts, RFC3339 lease times, flight-recorder
entries) — timestamps are the one legitimate wall-clock use.

Known-legitimate arithmetic — comparing wall-clock NOW against a
*persisted wall-clock timestamp* (job ``startTime`` vs
``activeDeadlineSeconds``, ``completionTime`` + TTL): monotonic cannot
measure against a wall timestamp another process wrote, so those sites are
carried in the committed baseline with this rationale (see
docs/analysis/README.md) rather than silenced inline.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from tpujob.analysis.engine import FileContext, Finding, Rule, dotted_name


class WallClockDurationRule(Rule):
    id = "TPL004"
    name = "wall-clock-for-durations"
    rationale = ("time.time() arithmetic makes deadlines NTP-step "
                 "sensitive; durations belong on time.monotonic()")
    scope = ("tpujob/controller/", "tpujob/kube/", "tpujob/server/",
             "tpujob/obs/")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        parents = ctx.parents()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or node.args or node.keywords:
                continue
            if dotted_name(node.func) != "time.time":
                continue
            parent = parents.get(node)
            if isinstance(parent, (ast.BinOp, ast.Compare, ast.UnaryOp)):
                out.append(Finding(
                    self.id, ctx.rel, node.lineno,
                    "time.time() used in arithmetic/comparison — use "
                    "time.monotonic() for durations and deadlines "
                    "(wall-vs-persisted-timestamp math belongs in the "
                    "baseline with a rationale)"))
        return out


RULES: Tuple[Rule, ...] = (WallClockDurationRule(),)
