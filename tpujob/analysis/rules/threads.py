"""TPL001: a ``threading.Thread`` must not be published before ``start()``.

The bug class fixed twice already (PR 4's ``LeaderElector.leading_thread``,
PR 5's informer run loop): assigning a freshly constructed Thread to an
attribute makes it visible to other threads — a concurrent ``stop()`` /
``hard_kill()`` can then ``join()`` a created-but-unstarted Thread, which
raises ``RuntimeError``.  The required shape is::

    t = threading.Thread(...)
    t.start()
    self._thread = t   # published only once join() is legal

Flagged, inside one function scope:

- ``self.attr = Thread(...)`` followed (lexically) by ``<attr>.start()``
  — the start-here pattern with the publish on the wrong side;
- ``self.attr = t`` where local ``t`` holds a Thread that has not yet seen
  ``t.start()``.

A Thread assigned to an attribute and never started in the same scope is
NOT flagged (construct-here/start-elsewhere is a different contract).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Tuple

from tpujob.analysis.engine import FileContext, Finding, Rule, dotted_name


def _is_thread_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    # exactly Thread / threading.Thread — ThreadPoolExecutor etc. are not
    # joinable thread handles
    return name is not None and (name == "Thread" or name.endswith(".Thread"))


class _ScopeScan:
    """Lexical single-pass over one function body (nested defs get their
    own scan — a closure runs later, ordering guarantees do not cross)."""

    def __init__(self, rel: str):
        self.rel = rel
        self.findings: List[Finding] = []
        # local name -> started?  (only names bound to a Thread call)
        self.locals: Dict[str, bool] = {}
        # published attr -> publish lineno, pending confirmation by .start()
        self.pending_attr: Dict[str, int] = {}

    def scan(self, body: List[ast.stmt]) -> None:
        events: List[ast.AST] = []
        for stmt in body:
            events.extend(self._walk_no_nested_defs(stmt))
        # lexical order: publish-vs-start is a statement-ordering property
        events.sort(key=lambda n: (n.lineno, n.col_offset))
        for node in events:
            self._visit(node)

    @staticmethod
    def _walk_no_nested_defs(root: ast.stmt) -> List[ast.AST]:
        out: List[ast.AST] = []
        stack: List[ast.AST] = [root]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # nested scope: runs later, gets its own scan
            if isinstance(node, (ast.Assign, ast.Call)):
                out.append(node)
            stack.extend(ast.iter_child_nodes(node))
        return out

    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name) and _is_thread_call(node.value):
                self.locals[target.id] = False  # born unstarted
            elif isinstance(target, ast.Attribute):
                attr = dotted_name(target)
                if attr is None:
                    return
                if _is_thread_call(node.value):
                    # publish of a just-constructed thread: a finding iff a
                    # later .start() in this scope proves start-here intent
                    self.pending_attr[attr] = node.lineno
                elif (isinstance(node.value, ast.Name)
                      and self.locals.get(node.value.id) is False):
                    self.findings.append(Finding(
                        "TPL001", self.rel, node.lineno,
                        f"thread published to {attr} before start(): "
                        f"local {node.value.id!r} is not started yet "
                        "(start first, then publish)"))
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr == "start":
                owner = dotted_name(func.value)
                if owner is None:
                    return
                if owner in self.locals:
                    self.locals[owner] = True
                elif owner in self.pending_attr:
                    self.findings.append(Finding(
                        "TPL001", self.rel, self.pending_attr.pop(owner),
                        f"thread published to {owner} before start(): "
                        f"{owner}.start() happens after the attribute "
                        "assignment (start a local first, then publish)"))


class ThreadPublishRule(Rule):
    id = "TPL001"
    name = "thread-publish-before-start"
    rationale = ("a published-but-unstarted Thread lets a concurrent "
                 "stop()/hard_kill() join() it -> RuntimeError (fixed in "
                 "PR 4's elector and again in PR 5's informer loop)")
    scope = ("tpujob/", "e2e/")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan = _ScopeScan(ctx.rel)
                scan.scan(node.body)
                out.extend(scan.findings)
        return out


RULES: Tuple[Rule, ...] = (ThreadPublishRule(),)
