"""TPL201: metric/docs parity and per-job series hygiene.

Three invariants over the metric families registered in
``tpujob/server/metrics.py`` (extracted once into the wire registry):

1. **Docs parity, both directions.**  Every family registered in code has
   a table row in ``docs/monitoring/README.md``, and every family named in
   a docs table row exists in code.  Dashboards are built from the docs;
   a family on one side only is either invisible or a 404 panel.
2. **Suffix/type discipline.**  ``_total`` ⇔ counter: a gauge named
   ``*_total`` lies to every rate() query, and a counter without the
   suffix hides from the convention scrapers rely on.  A legacy exception
   is expressible ONLY as a committed baseline entry with a rationale
   (the ``tpujob_job_steps_total`` wart lived and died this way).
3. **Per-job families must be droppable.**  Any family labeled by
   (namespace, job) holds one series per job forever unless something
   calls ``remove``/``remove_matching``/``forget`` on it — the
   resurrected-series/leaked-cardinality bug class from the shard-handoff
   work.  A per-job family with no reachable remove site anywhere in the
   tree cannot participate in the handoff-drop discipline.

Remove-site detection is deliberately coarse: a family counts as covered
when some function outside ``tests/`` references it AND calls one of the
drop methods (this matches both the ``clear_job_series`` loop-over-tuple
shape and goodput's direct ``metrics.x.remove(...)`` calls).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from tpujob.analysis.engine import Finding, Project, Rule
from tpujob.analysis.registry import (
    METRICS_MODULE, in_wire_scope, wire_registry)

DOCS_PATH = "docs/monitoring/README.md"

# a docs table row: `| `tpujob_foo{label=}` | gauge (`state`) | ... |`
_DOC_ROW_RE = re.compile(r"^\|\s*`(?P<name>tpujob_[a-z0-9_]+)[^`]*`\s*\|"
                         r"\s*(?P<type>[a-z]+)")
_DROP_METHODS = ("remove", "remove_matching", "forget")


def _documented_families(project: Project) -> Dict[str, Tuple[str, int]]:
    """family name -> (documented type, docs line)."""
    path = project.root / DOCS_PATH
    if not path.exists():
        return {}
    out: Dict[str, Tuple[str, int]] = {}
    for i, line in enumerate(path.read_text().splitlines(), 1):
        m = _DOC_ROW_RE.match(line.strip())
        if m:
            out.setdefault(m.group("name"), (m.group("type"), i))
    return out


def _removable_vars(project: Project) -> Set[str]:
    """Every name referenced inside a function that calls a drop method."""
    out: Set[str] = set()
    for ctx in project.contexts():
        if ctx.rel == METRICS_MODULE or not in_wire_scope(ctx.rel):
            continue
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            drops = False
            names: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr in _DROP_METHODS:
                    drops = True
                if isinstance(node, ast.Attribute):
                    names.add(node.attr)
                elif isinstance(node, ast.Name):
                    names.add(node.id)
            if drops:
                out |= names
    return out


class MetricDocsParityRule(Rule):
    id = "TPL201"
    name = "metric-docs-parity"
    rationale = ("metric families must match docs/monitoring, _total must "
                 "mean counter, and per-job families need a remove site")

    def check_project(self, project: Project) -> Iterable[Finding]:
        reg = wire_registry(project)
        if not reg.metrics or project.context(METRICS_MODULE) is None:
            return ()  # not this tree (fixture dirs, partial checkouts)
        out: List[Finding] = []
        documented = _documented_families(project)

        for fam in sorted(reg.metrics.values(), key=lambda m: m.name):
            if fam.name not in documented:
                out.append(Finding(
                    self.id, METRICS_MODULE, fam.line,
                    f"family {fam.name} is registered in code but has no "
                    f"table row in {DOCS_PATH} — dashboards are built from "
                    f"the docs"))
            else:
                doc_type = documented[fam.name][0]
                if doc_type != fam.kind:
                    out.append(Finding(
                        self.id, METRICS_MODULE, fam.line,
                        f"family {fam.name} is a {fam.kind} in code but "
                        f"documented as {doc_type} in {DOCS_PATH}"))
            is_total = fam.name.endswith("_total")
            if is_total and fam.kind != "counter":
                out.append(Finding(
                    self.id, METRICS_MODULE, fam.line,
                    f"family {fam.name} carries the _total suffix but is a "
                    f"{fam.kind} — _total promises counter semantics to "
                    f"every rate() query (legacy exceptions live in the "
                    f"baseline, never inline)"))
            elif fam.kind == "counter" and not is_total:
                out.append(Finding(
                    self.id, METRICS_MODULE, fam.line,
                    f"counter family {fam.name} lacks the _total suffix — "
                    f"scrapers key counter semantics off the name"))

        for name, (_type, line) in sorted(documented.items()):
            if name not in reg.metrics:
                out.append(Finding(
                    self.id, DOCS_PATH, line,
                    f"{DOCS_PATH} documents family {name} which is not "
                    f"registered in {METRICS_MODULE} — stale row or typo"))

        removable = _removable_vars(project)
        for fam in sorted(reg.metrics.values(), key=lambda m: m.name):
            if not {"namespace", "job"} <= set(fam.labels):
                continue
            if fam.var not in removable:
                out.append(Finding(
                    self.id, METRICS_MODULE, fam.line,
                    f"per-job family {fam.name} (labels {fam.labels}) has "
                    f"no reachable remove/remove_matching/forget site — its "
                    f"series outlive every job (handoff-drop discipline)"))
        return out


RULES: Tuple[Rule, ...] = (MetricDocsParityRule(),)
