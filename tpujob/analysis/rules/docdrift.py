"""TPL204: future-promise prose drift in the docs tree.

The documented failure mode: a doc paragraph defers to work that has not
happened ("... until fleet-wide ledger sharing lands") and nothing ever
walks it back when the work DOES happen — the prose silently inverts from
a roadmap note into a false claim about the current system (the
`docs/failure-handling` sharded-victim-pricing paragraph survived two PRs
past its own fix exactly this way).  Code drift has TPL200/TPL201 and the
wire registry; prose promises have no registry to diff against, so the
rule bans the *shape*: sentences in ``docs/`` that predicate current
behavior on unlanded future work.

What counts as a promise (case-insensitive; matched across hard line
wraps, since markdown prose wraps mid-sentence — the original offender
broke between "sharing" and "lands"; fenced code blocks are skipped):

- deferral to a landing: "until/once/when <something> lands|ships|is
  implemented|is wired up";
- scheduled-future phrasing: "will be added/implemented/supported/fixed
  later|soon|eventually", or a bare "in a future PR/release";
- placeholder admissions: "not yet implemented/supported/wired", "coming
  soon", a "TBD" token.

A promise that must stay (it is tracked work, not drift) carries the
inline waiver ``tpulint: allow-promise`` in an HTML comment on the line
where the promise starts, pointing at where it is tracked — the same
stance as ``# noqa`` with a why.  ROADMAP.md is exempt wholesale (and
outside ``docs/`` anyway): it is the one file whose JOB is future
promises.
"""
from __future__ import annotations

import re
from typing import Iterable, List, Tuple

from tpujob.analysis.engine import Finding, Project, Rule

_WAIVER = "tpulint: allow-promise"

# each pattern must key on a promise VERB, not on temporal words alone:
# "until the lease expires" is runtime semantics, not a roadmap note.
# [^.?!]{0,80} spans newlines on purpose — wrapped sentences still match
_PROMISE_RES: Tuple[re.Pattern, ...] = (
    re.compile(r"\b(?:until|once|when|after)\b[^.?!]{0,80}?"
               r"\b(?:lands|ships|is\s+(?:implemented|wired(?:\s+up)?)"
               r"|gets\s+(?:implemented|built|wired))\b", re.I),
    re.compile(r"\b(?:will|to)\s+be\s+"
               r"(?:added|implemented|wired|supported|fixed|built)\b"
               r"[^.?!]{0,40}?\b(?:later|soon|eventually)\b", re.I),
    re.compile(r"\bin\s+a\s+(?:future|later)\s+"
               r"(?:PR|release|change|version)\b", re.I),
    re.compile(r"\bnot\s+yet\s+(?:implemented|supported|wired|built)\b",
               re.I),
    re.compile(r"\bcoming\s+soon\b", re.I),
    re.compile(r"\bTBD\b"),
)


def _prose(path) -> Tuple[str, List[int]]:
    """The file's prose as ONE newline-joined string (fenced code blocks
    replaced by empty lines so offsets stay line-aligned), plus the
    0-based character offset where each line starts."""
    kept: List[str] = []
    in_fence = False
    for line in path.read_text().splitlines():
        if line.lstrip().startswith("```"):
            in_fence = not in_fence
            kept.append("")
            continue
        kept.append("" if in_fence else line)
    text = "\n".join(kept)
    starts, pos = [], 0
    for line in kept:
        starts.append(pos)
        pos += len(line) + 1
    return text, starts


def _line_of(starts: List[int], offset: int) -> int:
    lo, hi = 0, len(starts) - 1
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if starts[mid] <= offset:
            lo = mid
        else:
            hi = mid - 1
    return lo + 1  # 1-based


class DocDriftRule(Rule):
    id = "TPL204"
    name = "future-promise-prose"
    rationale = ("docs prose that predicates current behavior on unlanded "
                 "future work goes stale silently when the work lands — "
                 "the claim inverts and nothing diffs it; track promises "
                 "in ROADMAP.md or waive with a pointer to where they are "
                 "tracked")

    def check_project(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        root = project.root / "docs"
        if not root.is_dir():
            return findings
        for path in sorted(root.rglob("*.md")):
            rel = path.relative_to(project.root).as_posix()
            text, starts = _prose(path)
            lines = text.split("\n")
            flagged = set()
            for pattern in _PROMISE_RES:
                for m in pattern.finditer(text):
                    lineno = _line_of(starts, m.start())
                    if lineno in flagged:
                        continue
                    if _WAIVER in lines[lineno - 1]:
                        continue
                    flagged.add(lineno)
                    promise = re.sub(r"\s+", " ", m.group(0)).strip()
                    findings.append(Finding(
                        self.id, rel, lineno,
                        f"future-promise prose ({promise!r}): docs must "
                        f"describe the system as it is — move the promise "
                        f"to ROADMAP.md, or waive with "
                        f"`<!-- {_WAIVER}: <where tracked> -->`"))
        findings.sort(key=lambda f: (f.path, f.line))
        return findings


RULES: Tuple[Rule, ...] = (DocDriftRule(),)
