"""TPL005: swallowed broad exceptions.

A bare ``except:`` or ``except Exception/BaseException:`` whose body
neither re-raises, logs, nor even touches the bound exception is a
diagnostic black hole: the failure it ate surfaces later as an unrelated
symptom (a silently dead events pipeline, a watch that never heals).  The
repo's own history funded this rule — PR 2 counted the EventRecorder's
swallowed create failure, PR 3 formalized the "observers are best-effort"
contract with log.exception at every sink.

A handler passes when its body (nested defs excluded) contains any of:

- a ``raise`` (re-raise or translate);
- a logging-ish call — attribute call named ``debug/info/warning/warn/
  error/exception/critical/fatal/log/print_exc``;
- a reference to the bound exception name (``except Exception as e`` with
  ``e`` consumed — stashed on a ledger, appended to an error list, ...).

Intentional swallows — the observer contract (sinks/formatters/teardown
paths that must NEVER raise into the reconcile or logging path) — carry an
explicit inline waiver: ``# noqa: TPL005`` on the ``except`` line, next to
the comment explaining the contract.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from tpujob.analysis.engine import FileContext, Finding, Rule

_BROAD = {"Exception", "BaseException"}
_LOGGING_ATTRS = {"debug", "info", "warning", "warn", "error", "exception",
                  "critical", "fatal", "log", "print_exc"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        if isinstance(n, ast.Name) and n.id in _BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _BROAD:
            return True
    return False


def _body_nodes(handler: ast.ExceptHandler):
    stack: List[ast.AST] = list(handler.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _handled(handler: ast.ExceptHandler) -> bool:
    bound = handler.name
    for node in _body_nodes(handler):
        if isinstance(node, ast.Raise):
            return True
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _LOGGING_ATTRS):
            return True
        if bound and isinstance(node, ast.Name) and node.id == bound:
            return True
    return False


class SwallowedExceptionRule(Rule):
    id = "TPL005"
    name = "swallowed-exception"
    rationale = ("a broad except that neither logs, re-raises, nor uses the "
                 "exception hides the failure until it resurfaces as an "
                 "unrelated symptom; intentional observer-contract swallows "
                 "carry an inline `# noqa: TPL005` waiver")
    scope = ("tpujob/", "e2e/")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _handled(node):
                continue
            out.append(Finding(
                self.id, ctx.rel, node.lineno,
                "broad except swallows the exception (no raise/log/use); "
                "log it, narrow it, or waive the observer contract with "
                "`# noqa: TPL005`"))
        return out


RULES: Tuple[Rule, ...] = (SwallowedExceptionRule(),)
