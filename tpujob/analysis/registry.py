"""The wire registry: one project-wide extraction pass for the TPL2xx rules.

The operator's cross-module *protocol* invariants (consume-at-publish on
ack annotations, metric/docs parity, terminal condition flips, expectation
bookkeeping around pod churn) all need the same project-wide facts:

- every ``ANNOTATION_*`` / ``GROUP_NAME``-derived wire key defined in the
  API modules, plus every site that publishes, nulls, or reads it;
- every metric family registered in ``tpujob/server/metrics.py`` with its
  exposition type and label names;
- every ``JOB_*`` condition constant set True anywhere, and the terminal
  flip-False tuple inside ``status.set_condition``;
- every pod create/delete call site in ``tpujob/controller/``.

This module extracts them ONCE per :class:`~tpujob.analysis.engine.Project`
(memoized on the project instance) so four rule families share a single
walk instead of re-deriving the world per rule — `make lint` wall time
stays flat as the TPL2xx catalog grows.  The registry is also a debugging
surface: ``python scripts/lint.py --registry-dump`` prints it as JSON.

Scope note: ``tests/`` is OUTSIDE the wire-reference scope.  Test fixtures
legitimately spell raw downward-API text and set conditions into contrived
states; the protocol's real publishers and consumers live in the shipped
tree plus the e2e harnesses and benches (the workload half of several
channels is exercised only there).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from tpujob.analysis.engine import FileContext, Project, dotted_name

# the modules that DEFINE wire keys (and may carry raw group literals)
KEY_MODULES = ("tpujob/api/constants.py", "tpujob/api/progress.py",
               "tpujob/api/nodes.py")
CONSTANTS_MODULE = "tpujob/api/constants.py"
METRICS_MODULE = "tpujob/server/metrics.py"
STATUS_MODULE = "tpujob/controller/status.py"
CONTROLLER_DIR = "tpujob/controller/"

# metric constructor -> exposition kind, as metrics.py's kind() reports it
_METRIC_CTORS = {
    "Counter": "counter",
    "Gauge": "gauge",
    "Histogram": "histogram",
    "LabeledCounter": "counter",
    "LabeledGauge": "gauge",
    # counter-TYPED, set-driven (the ledger accumulates; see metrics.py)
    "LabeledSettableCounter": "counter",
    "LabeledHistogram": "histogram",
}

Site = Tuple[str, int]  # (repo-relative path, line)


@dataclass
class AnnotationKey:
    const: str                   # constant name, e.g. ANNOTATION_WORLD_SIZE
    key: str                     # wire spelling, e.g. tpujob.dev/world-size
    module: str                  # defining module (repo-relative)
    line: int                    # definition line
    publishes: List[Site] = field(default_factory=list)
    null_writes: List[Site] = field(default_factory=list)
    reads: List[Site] = field(default_factory=list)


@dataclass
class MetricFamily:
    var: str                     # module-level variable name
    name: str                    # exposition family name
    kind: str                    # counter | gauge | histogram
    labels: Tuple[str, ...]      # label names ((), for unlabeled)
    line: int


@dataclass
class ConditionInfo:
    set_true: Dict[str, List[Site]]  # JOB_* const -> set-True call sites
    terminal_flip: Set[str]          # consts in the terminal flip tuple
    flip_line: int                   # line of the flip tuple (0 = not found)


@dataclass
class PodCallSite:
    path: str
    line: int
    method: str                  # create_pod | create_pods | delete_pod
    receiver: Optional[str]      # dotted receiver, e.g. self.pod_control


@dataclass
class WireRegistry:
    annotations: Dict[str, AnnotationKey]
    metrics: Dict[str, MetricFamily]   # keyed by family (exposition) name
    conditions: ConditionInfo
    pod_calls: List[PodCallSite]

    def to_json(self) -> Dict[str, Any]:
        return {
            "annotations": {
                a.key: {
                    "const": a.const,
                    "defined": f"{a.module}:{a.line}",
                    "publishes": [f"{p}:{l}" for p, l in a.publishes],
                    "null_writes": [f"{p}:{l}" for p, l in a.null_writes],
                    "reads": [f"{p}:{l}" for p, l in a.reads],
                }
                for a in sorted(self.annotations.values(),
                                key=lambda a: a.key)
            },
            "metrics": {
                m.name: {"var": m.var, "kind": m.kind,
                         "labels": list(m.labels),
                         "defined": f"{METRICS_MODULE}:{m.line}"}
                for m in sorted(self.metrics.values(), key=lambda m: m.name)
            },
            "conditions": {
                "set_true": {
                    const: [f"{p}:{l}" for p, l in sites]
                    for const, sites in sorted(
                        self.conditions.set_true.items())
                },
                "terminal_flip": sorted(self.conditions.terminal_flip),
            },
            "pod_calls": [
                {"site": f"{s.path}:{s.line}", "method": s.method,
                 "receiver": s.receiver}
                for s in self.pod_calls
            ],
        }


def in_wire_scope(rel: str) -> bool:
    """Whether a path counts as a wire-protocol reference site (the shipped
    tree + e2e harnesses + scripts + top-level benches; NOT tests/)."""
    return not rel.startswith("tests/")


# ---------------------------------------------------------------------------
# extraction passes
# ---------------------------------------------------------------------------


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _module_constants(ctx: FileContext) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments."""
    out: Dict[str, str] = {}
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            s = _const_str(node.value)
            if s is not None:
                out[node.targets[0].id] = s
    return out


def _extract_annotation_keys(project: Project) -> Dict[str, AnnotationKey]:
    """GROUP_NAME-derived f-string constants in the key modules, resolved to
    their wire spelling.  Only metadata keys (``ANNOTATION_*`` / ``LABEL_*``)
    join the publish/consume protocol; ``API_VERSION``-style derivations are
    resolved but carry no conformance obligations."""
    out: Dict[str, AnnotationKey] = {}
    for mod in KEY_MODULES:
        ctx = project.context(mod)
        if ctx is None:
            continue
        literals = _module_constants(ctx)
        for node in ctx.tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.JoinedStr)):
                continue
            name = node.targets[0].id
            if not (name.startswith("ANNOTATION_")
                    or name.startswith("LABEL_")):
                continue
            parts: List[str] = []
            ok = True
            for piece in node.value.values:
                if isinstance(piece, ast.Constant):
                    parts.append(str(piece.value))
                elif (isinstance(piece, ast.FormattedValue)
                      and isinstance(piece.value, ast.Name)
                      and piece.value.id in literals):
                    parts.append(literals[piece.value.id])
                else:
                    ok = False
                    break
            if ok:
                out[name] = AnnotationKey(
                    const=name, key="".join(parts), module=ctx.rel,
                    line=node.lineno)
    return out


def _classify_annotation_refs(project: Project,
                              keys: Dict[str, AnnotationKey]) -> None:
    """Find every ``c.ANNOTATION_X`` / bare-name reference outside the
    defining modules and classify it: dict-literal key with a non-None value
    (or a subscript store) = publish; dict key with a literal ``None`` value
    (or a del) = null-write (ack consumption); everything else = read."""
    if not keys:
        return
    wanted = set(keys)
    for ctx in project.contexts():
        # only a key's own DEFINING module is skipped (per-key, below) —
        # the other API modules are real protocol participants
        # (api/nodes.py both reads the heartbeat key and publishes the
        # synthesized label)
        if not in_wire_scope(ctx.rel):
            continue
        parents = None
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                name = node.attr
            elif isinstance(node, ast.Name):
                name = node.id
            else:
                continue
            if name not in wanted or ctx.rel == keys[name].module:
                continue
            if isinstance(node, ast.Attribute) and not isinstance(
                    node.value, ast.Name):
                continue  # x.y.ANNOTATION_FOO: not a constants alias
            if parents is None:
                parents = ctx.parents()
            # skip the inner Name of an Attribute match (walk visits both)
            parent = parents.get(node)
            if isinstance(node, ast.Name) and isinstance(parent, ast.Attribute):
                continue
            site = (ctx.rel, node.lineno)
            rec = keys[name]
            if isinstance(parent, ast.Dict) and node in parent.keys:
                value = parent.values[parent.keys.index(node)]
                if isinstance(value, ast.Constant) and value.value is None:
                    rec.null_writes.append(site)
                else:
                    rec.publishes.append(site)
            elif isinstance(parent, ast.Subscript) and parent.slice is node:
                if isinstance(parent.ctx, ast.Store):
                    rec.publishes.append(site)
                elif isinstance(parent.ctx, ast.Del):
                    rec.null_writes.append(site)
                else:
                    rec.reads.append(site)
            else:
                rec.reads.append(site)


def _resolve_labels(node: ast.AST,
                    tuples: Dict[str, Tuple[str, ...]]) -> Tuple[str, ...]:
    """A labelnames argument as a tuple of strings: a literal tuple, a
    module-level tuple constant by name, or a ``NAME + (...)`` concatenation."""
    if isinstance(node, ast.Tuple):
        return tuple(v for v in (_const_str(e) for e in node.elts)
                     if v is not None)
    if isinstance(node, ast.Name):
        return tuples.get(node.id, ())
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return (_resolve_labels(node.left, tuples)
                + _resolve_labels(node.right, tuples))
    return ()


def _extract_metric_families(project: Project) -> Dict[str, MetricFamily]:
    ctx = project.context(METRICS_MODULE)
    if ctx is None:
        return {}
    # module-level tuple constants (_JOB_LABELS)
    tuples: Dict[str, Tuple[str, ...]] = {}
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Tuple):
            tuples[node.targets[0].id] = tuple(
                v for v in (_const_str(e) for e in node.value.elts)
                if v is not None)
    out: Dict[str, MetricFamily] = {}
    for node in ctx.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id in _METRIC_CTORS):
            continue
        call = node.value
        name = _const_str(call.args[0]) if call.args else None
        if name is None:
            continue
        labels: Tuple[str, ...] = ()
        ctor = call.func.id
        if ctor.startswith("Labeled"):
            # signature: (name, help, registry, labelnames, ...)
            if len(call.args) >= 4:
                labels = _resolve_labels(call.args[3], tuples)
            for kw in call.keywords:
                if kw.arg == "labelnames":
                    labels = _resolve_labels(kw.value, tuples)
        out[name] = MetricFamily(
            var=node.targets[0].id, name=name, kind=_METRIC_CTORS[ctor],
            labels=labels, line=node.lineno)
    return out


def _job_cond_names(node: ast.AST) -> Optional[str]:
    """``c.JOB_X`` / ``constants.JOB_X`` / bare ``JOB_X`` -> ``JOB_X``."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.attr.startswith("JOB_"):
        return node.attr
    if isinstance(node, ast.Name) and node.id.startswith("JOB_"):
        return node.id
    return None


def _extract_conditions(project: Project) -> ConditionInfo:
    set_true: Dict[str, List[Site]] = {}
    for ctx in project.contexts():
        if not ctx.rel.startswith("tpujob/"):
            continue  # fixtures in tests/e2e set contrived condition states
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = dotted_name(node.func)
            if func is None or not func.endswith("update_job_conditions"):
                continue
            if len(node.args) < 2:
                continue
            const = _job_cond_names(node.args[1])
            if const is not None:
                set_true.setdefault(const, []).append(
                    (ctx.rel, node.lineno))

    terminal_flip: Set[str] = set()
    flip_line = 0
    ctx = project.context(STATUS_MODULE)
    if ctx is not None:
        for fn in ast.walk(ctx.tree):
            if not (isinstance(fn, ast.FunctionDef)
                    and fn.name == "set_condition"):
                continue
            # the terminal branch: `condition.type in (JOB_SUCCEEDED,
            # JOB_FAILED)`; the flip tuple is the `cond.type in (...)`
            # compare inside its body
            for test in ast.walk(fn):
                if not (isinstance(test, ast.If)
                        and isinstance(test.test, ast.Compare)
                        and len(test.test.ops) == 1
                        and isinstance(test.test.ops[0], ast.In)
                        and isinstance(test.test.comparators[0], ast.Tuple)):
                    continue
                branch_consts = {
                    _job_cond_names(e)
                    for e in test.test.comparators[0].elts}
                if branch_consts != {"JOB_SUCCEEDED", "JOB_FAILED"}:
                    continue
                for inner in ast.walk(test):
                    if (isinstance(inner, ast.Compare)
                            and len(inner.ops) == 1
                            and isinstance(inner.ops[0], ast.In)
                            and isinstance(inner.comparators[0], ast.Tuple)
                            and inner is not test.test):
                        consts = {
                            c for c in (_job_cond_names(e) for e in
                                        inner.comparators[0].elts)
                            if c is not None}
                        if consts:
                            terminal_flip = consts
                            flip_line = inner.lineno
                            break
                break
    return ConditionInfo(set_true=set_true, terminal_flip=terminal_flip,
                         flip_line=flip_line)


_POD_METHODS = ("create_pod", "create_pods", "delete_pod")


def _extract_pod_calls(project: Project) -> List[PodCallSite]:
    out: List[PodCallSite] = []
    for ctx in project.contexts():
        if not ctx.rel.startswith(CONTROLLER_DIR):
            continue
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in _POD_METHODS:
                out.append(PodCallSite(
                    path=ctx.rel, line=node.lineno, method=func.attr,
                    receiver=dotted_name(func.value)))
            elif isinstance(func, ast.Name) and func.id in _POD_METHODS:
                out.append(PodCallSite(
                    path=ctx.rel, line=node.lineno, method=func.id,
                    receiver=None))
            elif (isinstance(func, ast.Attribute)
                  and func.attr in ("create", "delete") and node.args):
                resource = node.args[0]
                is_pods = (_const_str(resource) == "pods"
                           or (isinstance(resource, ast.Name)
                               and resource.id == "RESOURCE_PODS"))
                if is_pods:
                    out.append(PodCallSite(
                        path=ctx.rel, line=node.lineno,
                        method=f"{func.attr}(pods)",
                        receiver=dotted_name(func.value)))
    out.sort(key=lambda s: (s.path, s.line))
    return out


def wire_registry(project: Project) -> WireRegistry:
    """The project's wire registry, built once and memoized on the project
    instance so every TPL2xx rule shares one extraction pass."""
    cached = getattr(project, "_wire_registry", None)
    if cached is not None:
        return cached
    keys = _extract_annotation_keys(project)
    _classify_annotation_refs(project, keys)
    reg = WireRegistry(
        annotations=keys,
        metrics=_extract_metric_families(project),
        conditions=_extract_conditions(project),
        pod_calls=_extract_pod_calls(project),
    )
    project._wire_registry = reg  # type: ignore[attr-defined]
    return reg
