"""Runtime lock-order sentinel: the dynamic tier of the analysis package.

TPL003 proves an *annotated* attribute is only touched under its lock; it
cannot see the ORDER different locks are taken in across threads — the
classic AB/BA deadlock needs a runtime witness.  This module provides one:

- :func:`new_lock` / :func:`new_rlock` are drop-in factories the concurrency
  hot spots (memserver, informer stores, recorder, rate limiter, workqueue
  proxy) use instead of ``threading.Lock()``/``RLock()``.  **Disabled**
  (the default) they return the plain stdlib primitives — zero overhead,
  byte-for-byte the pre-sentinel behavior.  **Enabled** (the
  ``TPUJOB_LOCK_SENTINEL=1`` env flag, or :func:`enable` from a harness)
  they return instrumented wrappers that record, per thread, which locks
  were held when each lock was acquired.
- every ``(held -> acquired)`` pair becomes an edge in the process-global
  :data:`GRAPH`.  A cycle in that graph is a potential deadlock: two
  threads that ever interleave the cyclic orders wedge forever.
- holds longer than ``TPUJOB_LOCK_HOLD_WARN_S`` (default 100 ms) are kept
  in a bounded ring — the "who stalled the API server" ledger.

The chaos soaks (``e2e/chaos.py``) enable the sentinel for the duration of
every run and assert a cycle-free graph afterwards, so each soak doubles as
a race/deadlock audit; ``bench_controller --lock-sentinel`` does the same
for the throughput benches.

Locks are named per call site (usually per class); edges connect *names*,
so two instances of the same class share a node — lock-order discipline is
a property of the code, not of object identity.  Reentrant acquisition of
an :func:`new_rlock` lock by its owner is not an edge (it cannot deadlock);
re-acquiring a non-reentrant :func:`new_lock` lock on the same instance is
reported as an immediate self-deadlock *before* the thread wedges.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

_ENV_FLAG = "TPUJOB_LOCK_SENTINEL"


def _hold_warn_s() -> float:
    """The long-hold threshold; a malformed env value (e.g. "100ms") falls
    back to the default — a debug tuning knob must never be able to crash
    the operator at import time."""
    raw = os.environ.get("TPUJOB_LOCK_HOLD_WARN_S", "")
    try:
        return float(raw) if raw else 0.1
    except ValueError:
        return 0.1


HOLD_WARN_S = _hold_warn_s()

_enabled = os.environ.get(_ENV_FLAG, "") not in ("", "0", "false", "no")


def enabled() -> bool:
    """Whether factories currently mint instrumented locks."""
    return _enabled


def enable(on: bool = True) -> bool:
    """Flip the sentinel for locks created FROM NOW ON; returns the previous
    state so a harness can restore it.  Already-created locks keep whatever
    flavor they were born with (a plain Lock cannot be retrofitted)."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    return prev


class LockGraph:
    """Process-global acquisition-order graph fed by the sentinel locks."""

    def __init__(self, long_hold_s: float = HOLD_WARN_S):
        # the graph's own mutex is a PLAIN lock and never instrumented:
        # instrumenting it would recurse into itself
        self._mu = threading.Lock()
        self.long_hold_s = long_hold_s
        # (held name, acquired name) -> occurrence count
        self._edges: Dict[Tuple[str, str], int] = {}
        self._acquisitions: Dict[str, int] = {}  # per lock name
        self._long_holds: "deque[Tuple[str, float]]" = deque(maxlen=256)
        self._self_deadlocks: List[str] = []
        # cross-INSTANCE nesting of two locks sharing one name: names
        # cannot express an order against themselves, so such pairs are a
        # blind spot of the cycle check — surfaced in stats() so an audit
        # knows the class needs per-instance names (like the per-resource
        # informer stores) before its AB/BA orders become checkable
        self._same_name_nestings: Dict[str, int] = {}
        self._tls = threading.local()

    # -- per-thread hold stack ----------------------------------------------

    def _stack(self) -> List[Tuple[str, int, float]]:
        """This thread's held locks: (name, instance id, acquire stamp)."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def holds_instance(self, instance_id: int) -> bool:
        return any(i == instance_id for _, i, _ in self._stack())

    def note_self_deadlock(self, name: str) -> None:
        """A non-reentrant lock re-acquired by its holder: report before the
        thread wedges (the acquire below will block forever regardless)."""
        with self._mu:
            self._self_deadlocks.append(name)

    def note_acquired(self, name: str, instance_id: int) -> None:
        stack = self._stack()
        with self._mu:
            for held_name, held_id, _ in stack:
                if held_name != name:
                    edge = (held_name, name)
                    self._edges[edge] = self._edges.get(edge, 0) + 1
                elif held_id != instance_id:
                    # same name, different instance: unorderable by name —
                    # count the blind spot instead of minting a false cycle
                    self._same_name_nestings[name] = (
                        self._same_name_nestings.get(name, 0) + 1)
            self._acquisitions[name] = self._acquisitions.get(name, 0) + 1
        stack.append((name, instance_id, time.monotonic()))

    def note_released(self, name: str, instance_id: int) -> None:
        stack = self._stack()
        for idx in range(len(stack) - 1, -1, -1):
            if stack[idx][1] == instance_id:
                _, _, t0 = stack.pop(idx)
                held = time.monotonic() - t0
                if held >= self.long_hold_s:
                    with self._mu:
                        self._long_holds.append((name, held))
                return
        # release without a recorded acquire (lock created pre-reset or
        # acquired on another thread): nothing to unwind

    # -- introspection -------------------------------------------------------

    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._mu:
            return dict(self._edges)

    def long_holds(self) -> List[Tuple[str, float]]:
        with self._mu:
            return list(self._long_holds)

    def cycles(self) -> List[List[str]]:
        """Every lock-order cycle as a sorted node list: the strongly
        connected components of the edge graph with more than one node,
        plus any recorded same-instance self-deadlocks.  Deterministic
        (nodes visited in sorted order)."""
        with self._mu:
            adj: Dict[str, List[str]] = {}
            for (a, b) in self._edges:
                adj.setdefault(a, []).append(b)
                adj.setdefault(b, [])
            self_dead = sorted(set(self._self_deadlocks))
        for outs in adj.values():
            outs.sort()

        # Tarjan SCC, iterative (the graph is tiny but recursion-free keeps
        # it safe to call from instrumented code paths)
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        for root in sorted(adj):
            if root in index:
                continue
            work: List[Tuple[str, int]] = [(root, 0)]
            while work:
                node, child_i = work[-1]
                if child_i == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                advanced = False
                for i in range(child_i, len(adj[node])):
                    nxt = adj[node][i]
                    if nxt not in index:
                        work[-1] = (node, i + 1)
                        work.append((nxt, 0))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                if low[node] == index[node]:
                    comp = []
                    while True:
                        top = stack.pop()
                        on_stack.discard(top)
                        comp.append(top)
                        if top == node:
                            break
                    if len(comp) > 1:
                        sccs.append(sorted(comp))
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
        sccs.extend([name] for name in self_dead)
        return sorted(sccs)

    def stats(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "locks": len(self._acquisitions),
                "acquisitions": sum(self._acquisitions.values()),
                "edges": len(self._edges),
                "long_holds": len(self._long_holds),
                "max_hold_s": round(
                    max((h for _, h in self._long_holds), default=0.0), 6),
                "same_name_nestings": sum(self._same_name_nestings.values()),
            }

    def reset(self) -> None:
        """Drop every recorded edge/hold (per-thread stacks survive so a
        lock held ACROSS the reset still releases cleanly)."""
        with self._mu:
            self._edges.clear()
            self._acquisitions.clear()
            self._long_holds.clear()
            self._self_deadlocks.clear()
            self._same_name_nestings.clear()


GRAPH = LockGraph()


class SentinelLock:
    """Instrumented ``threading.Lock`` recording acquisition-order edges."""

    __slots__ = ("name", "_lock", "graph")

    def __init__(self, name: str, graph: Optional[LockGraph] = None):
        self.name = name
        self._lock = threading.Lock()
        self.graph = graph if graph is not None else GRAPH

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        graph = self.graph
        if blocking and graph.holds_instance(id(self)):
            # would wedge this thread forever: make the deadlock visible
            # in the graph before the acquire below blocks
            graph.note_self_deadlock(self.name)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            graph.note_acquired(self.name, id(self))
        return ok

    def release(self) -> None:
        self.graph.note_released(self.name, id(self))
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()


class SentinelRLock:
    """Instrumented ``threading.RLock``: only the OUTERMOST acquire/release
    of each thread touches the graph — reentrant nesting is not an order."""

    __slots__ = ("name", "_lock", "_tls", "graph")

    def __init__(self, name: str, graph: Optional[LockGraph] = None):
        self.name = name
        self._lock = threading.RLock()
        self._tls = threading.local()
        self.graph = graph if graph is not None else GRAPH

    def _depth(self) -> int:
        return getattr(self._tls, "depth", 0)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            depth = self._depth()
            self._tls.depth = depth + 1
            if depth == 0:
                self.graph.note_acquired(self.name, id(self))
        return ok

    def release(self) -> None:
        depth = self._depth() - 1
        self._tls.depth = depth
        if depth == 0:
            self.graph.note_released(self.name, id(self))
        self._lock.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()


def new_lock(name: str) -> "threading.Lock | SentinelLock":
    """A mutex for ``name``: plain ``threading.Lock`` when the sentinel is
    off (zero overhead), an edge-recording :class:`SentinelLock` when on."""
    if _enabled:
        return SentinelLock(name)
    return threading.Lock()


def new_rlock(name: str) -> "threading.RLock | SentinelRLock":
    """Reentrant variant of :func:`new_lock`."""
    if _enabled:
        return SentinelRLock(name)
    return threading.RLock()


@contextlib.contextmanager
def audit() -> Iterator[LockGraph]:
    """One scoped deadlock audit: enable the sentinel, reset the global
    graph, yield it, and restore the previous enable state on exit — the
    shared shell of every soak mode and ``bench_controller
    --lock-sentinel``.  The caller decides what to do with the graph
    (assert cycle-free, attach stats to a report)."""
    prev = enable(True)
    GRAPH.reset()
    try:
        yield GRAPH
    finally:
        enable(prev)
