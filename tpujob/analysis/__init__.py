"""Static-analysis and runtime concurrency sentinels for the operator.

Two tiers, one discipline (the invariants four of six PRs re-fixed by hand,
now mechanically enforced):

- :mod:`tpujob.analysis.engine` + :mod:`tpujob.analysis.rules` — *tpulint*,
  the dependency-free AST rule engine behind ``make lint``: thread-publish
  ordering (TPL001), transport-stack verb completeness (TPL002), guarded-by
  lock discipline (TPL003), monotonic-clock duration math (TPL004),
  swallowed exceptions (TPL005), the legacy syntax/import/whitespace
  checks (TPL000/TPL100/TPL101), and the interprocedural protocol
  conformance family (TPL200 annotation wire protocol, TPL201 metric/docs
  parity, TPL202 condition lifecycle, TPL203 expectation bookkeeping)
  built on :mod:`tpujob.analysis.registry`, the once-per-run project-wide
  wire-registry extraction.
- :mod:`tpujob.analysis.lockgraph` — an opt-in runtime lock-order sentinel:
  instrumented locks record per-thread acquisition edges into a global
  graph; cycles (potential deadlocks) and long holds surface in the chaos
  soaks and ``bench_controller --lock-sentinel``.

This package stays import-light on purpose: the kube/controller modules
import :mod:`tpujob.analysis.lockgraph` at module load, so nothing here may
pull in the engine (which parses the whole repo) as a side effect.
"""
