"""tpulint: the repo's dependency-free, plugin-based AST rule engine.

``scripts/lint.py`` (and therefore ``make lint`` / ``make ci`` / ``make
test``) is a thin CLI over this module.  The engine:

- discovers rule plugins in :mod:`tpujob.analysis.rules` (every module's
  ``RULES`` list), each a :class:`Rule` with a stable ``TPLxxx`` id;
- parses every repo source exactly once into a :class:`FileContext`
  (AST + lines + ``# noqa`` map) shared by all per-file rules, plus a
  :class:`Project` handle for cross-module rules (TPL002 reads five
  transport layers at once);
- suppresses findings via ``# noqa`` on the finding's line — bare ``noqa``
  kills everything, ``# noqa: TPL003`` (or a rule's declared alias, e.g.
  ``F401`` for TPL100) kills just that rule;
- subtracts a committed baseline (``.tpulint-baseline.json`` at the repo
  root) so *documented* pre-existing debt/false positives don't block CI;
  ``--write-baseline`` (``make lint-baseline``) regenerates it.

Baseline fingerprints are line-CONTENT addressed (rule id + path + hash of
the stripped source line + occurrence index), so unrelated edits shifting
line numbers don't invalidate them, while editing the flagged line itself
does — the finding then resurfaces for a fresh decision.
"""
from __future__ import annotations

import argparse
import ast
import hashlib
import importlib
import json
import pkgutil
import re
import sys
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
SCAN_DIRS = ("tpujob", "e2e", "tests", "scripts")
TOP_FILES = ("bench.py", "bench_models.py", "bench_controller.py", "soak.py",
             "__graft_entry__.py")
BASELINE_NAME = ".tpulint-baseline.json"

_NOQA_RE = re.compile(
    r"#\s*noqa(?P<codes>:\s*[A-Z]+[0-9]+(?:\s*,\s*[A-Z]+[0-9]+)*)?",
    re.IGNORECASE,
)
_CODE_RE = re.compile(r"[A-Z]+[0-9]+", re.IGNORECASE)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


class FileContext:
    """One parsed source file shared by every per-file rule."""

    def __init__(self, root: Path, path: Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.source = path.read_text()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source)  # SyntaxError propagates (TPL000)
        self._noqa: Optional[Dict[int, Optional[frozenset]]] = None
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    def line(self, lineno: int) -> str:
        return self.lines[lineno - 1] if 0 < lineno <= len(self.lines) else ""

    @property
    def noqa(self) -> Dict[int, Optional[frozenset]]:
        """lineno -> None (bare noqa: everything) or the suppressed codes."""
        if self._noqa is None:
            out: Dict[int, Optional[frozenset]] = {}
            for i, text in enumerate(self.lines, 1):
                if "noqa" not in text.lower():
                    continue  # cheap prefilter, case-folded like the regex
                m = _NOQA_RE.search(text)
                if not m:
                    continue
                codes = m.group("codes")
                if codes is None:
                    out[i] = None
                else:
                    out[i] = frozenset(
                        c.upper() for c in _CODE_RE.findall(codes))
            self._noqa = out
        return self._noqa

    def suppressed(self, rule_id: str, lineno: int,
                   aliases: Sequence[str] = ()) -> bool:
        codes = self.noqa.get(lineno, ...)
        if codes is ...:
            return False
        if codes is None:
            return True  # bare noqa
        wanted = {rule_id.upper(), *(a.upper() for a in aliases)}
        return bool(wanted & codes)

    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            parents: Dict[ast.AST, ast.AST] = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    parents[child] = node
            self._parents = parents
        return self._parents


def dotted_name(node: ast.AST) -> Optional[str]:
    """``self._lock`` / ``threading.Thread`` as a dotted string, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_sources(root: Path) -> Iterator[Path]:
    for d in SCAN_DIRS:
        base = root / d
        if base.is_dir():
            yield from sorted(base.rglob("*.py"))
    for f in TOP_FILES:
        p = root / f
        if p.exists():
            yield p


class Project:
    """Every parsed source of one tree; the cross-module rules' handle."""

    def __init__(self, root: Path, files: Optional[Iterable[Path]] = None):
        self.root = Path(root)
        self.syntax_errors: List[Finding] = []
        self._contexts: Dict[str, FileContext] = {}
        for path in (list(files) if files is not None
                     else iter_sources(self.root)):
            rel = path.relative_to(self.root).as_posix()
            try:
                self._contexts[rel] = FileContext(self.root, path)
            except SyntaxError as e:
                self.syntax_errors.append(Finding(
                    "TPL000", rel, e.lineno or 1,
                    f"syntax error: {e.msg}"))

    def contexts(self) -> List[FileContext]:
        return [self._contexts[k] for k in sorted(self._contexts)]

    def context(self, rel: str) -> Optional[FileContext]:
        return self._contexts.get(rel)


class Rule:
    """One lint rule.  Subclasses set the metadata and override one hook.

    ``scope`` restricts per-file checks to paths starting with any of the
    given repo-relative prefixes (empty = everywhere).  ``noqa_aliases``
    are foreign codes accepted in ``# noqa:`` lines (e.g. ``F401``).
    """

    id: str = ""
    name: str = ""
    rationale: str = ""
    scope: Tuple[str, ...] = ()
    noqa_aliases: Tuple[str, ...] = ()

    def applies(self, ctx: FileContext) -> bool:
        return not self.scope or any(ctx.rel.startswith(p) for p in self.scope)

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_project(self, project: Project) -> Iterable[Finding]:
        return ()


def load_rules() -> List[Rule]:
    """Discover every plugin in tpujob.analysis.rules (modules' RULES lists),
    sorted by rule id."""
    from tpujob.analysis import rules as rules_pkg

    out: List[Rule] = []
    for mod_info in pkgutil.iter_modules(rules_pkg.__path__):
        mod = importlib.import_module(
            f"{rules_pkg.__name__}.{mod_info.name}")
        out.extend(getattr(mod, "RULES", ()))
    out.sort(key=lambda r: r.id)
    ids = [r.id for r in out]
    assert len(ids) == len(set(ids)), f"duplicate rule ids: {ids}"
    return out


def run_rules(project: Project,
              rules: Optional[Sequence[Rule]] = None,
              select: Optional[Iterable[str]] = None) -> List[Finding]:
    """All unsuppressed findings (noqa applied, baseline NOT applied)."""
    rules = list(rules) if rules is not None else load_rules()
    if select is not None:
        wanted = {s.upper() for s in select}
        rules = [r for r in rules if r.id in wanted]
    findings: List[Finding] = []
    if select is None or "TPL000" in {s.upper() for s in (select or ())}:
        findings.extend(project.syntax_errors)
    by_alias = {r.id: r.noqa_aliases for r in rules}
    for rule in rules:
        for ctx in project.contexts():
            if not rule.applies(ctx):
                continue
            findings.extend(rule.check_file(ctx))
        findings.extend(rule.check_project(project))
    out: List[Finding] = []
    for f in findings:
        ctx = project.context(f.path)
        if ctx is not None and ctx.suppressed(
                f.rule, f.line, by_alias.get(f.rule, ())):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def _fingerprints(project: Project, findings: Sequence[Finding]) -> List[str]:
    """Line-content fingerprints, one per finding, order-aligned."""
    occ: Dict[Tuple[str, str, str], int] = {}
    out: List[str] = []
    for f in findings:
        ctx = project.context(f.path)
        text = ctx.line(f.line).strip() if ctx is not None else ""
        digest = hashlib.sha1(text.encode()).hexdigest()[:12]
        key = (f.rule, f.path, digest)
        n = occ.get(key, 0)
        occ[key] = n + 1
        out.append(f"{f.rule}|{f.path}|{digest}|{n}")
    return out


def load_baseline(path: Path) -> Dict[str, Dict[str, Any]]:
    if not path.exists():
        return {}
    doc = json.loads(path.read_text())
    return {e["fingerprint"]: e for e in doc.get("findings", [])}


def write_baseline(path: Path, project: Project,
                   findings: Sequence[Finding]) -> int:
    entries = [
        {"fingerprint": fp, "rule": f.rule, "path": f.path,
         "message": f.message,
         "line_at_capture": f.line}
        for f, fp in zip(findings, _fingerprints(project, findings))
    ]
    doc = {
        "_comment": (
            "tpulint baseline: DOCUMENTED pre-existing findings only (see "
            "docs/analysis/README.md). Regenerate with `make lint-baseline`; "
            "fingerprints are line-content addressed so they survive line "
            "shifts but expire when the flagged line is edited."),
        "findings": entries,
    }
    path.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    return len(entries)


def apply_baseline(project: Project, findings: Sequence[Finding],
                   baseline: Dict[str, Dict[str, Any]]
                   ) -> Tuple[List[Finding], int, List[str]]:
    """(kept findings, baselined count, stale fingerprints)."""
    fps = _fingerprints(project, findings)
    kept: List[Finding] = []
    used = set()
    for f, fp in zip(findings, fps):
        if fp in baseline:
            used.add(fp)
        else:
            kept.append(f)
    stale = sorted(set(baseline) - used)
    return kept, len(used), stale


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpulint", description=__doc__.partition("\n")[0])
    p.add_argument("--root", default=str(REPO_ROOT),
                   help="tree to scan (default: the repo root)")
    p.add_argument("--select", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from current findings and exit")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the committed baseline (report everything)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--registry-dump", action="store_true",
                   help="print the extracted wire registry (annotations, "
                        "metric families, conditions, pod call sites) as "
                        "JSON and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    rules = load_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.name}")
            if r.rationale:
                print(f"        {r.rationale}")
        return 0
    root = Path(args.root).resolve()
    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    if args.write_baseline and select is not None:
        # a selected run produces no findings for the unselected rules, so
        # rewriting the baseline from it would silently drop their entries
        print("tpulint: --write-baseline cannot be combined with --select "
              "(it would truncate the unselected rules' baseline entries)",
              file=sys.stderr)
        return 2
    project = Project(root)
    if args.registry_dump:
        from tpujob.analysis.registry import wire_registry

        print(json.dumps(wire_registry(project).to_json(), indent=2))
        return 0
    findings = run_rules(project, rules, select)
    baseline_path = root / BASELINE_NAME

    if args.write_baseline:
        n = write_baseline(baseline_path, project, findings)
        print(f"tpulint: baseline written with {n} finding(s) "
              f"-> {baseline_path.name}")
        return 0

    baselined = 0
    stale: List[str] = []
    if not args.no_baseline:
        findings, baselined, stale = apply_baseline(
            project, findings, load_baseline(baseline_path))
        if select is not None:
            stale = []  # unselected rules' findings are absent by construction
    for f in findings:
        print(f.render())
    for fp in stale:
        # a stale entry is an ERROR, not a note: left in place, it would
        # silently suppress a future finding whose line content happens to
        # match the dead fingerprint (a reintroduced regression)
        print(f"tpulint: stale baseline entry (finding fixed? run `make "
              f"lint-baseline` to prune): {fp}")
    if findings or stale:
        print(f"\ntpulint: {len(findings)} problem(s), "
              f"{len(stale)} stale baseline entr(y/ies)"
              + (f" ({baselined} baselined)" if baselined else ""))
        return 1
    suffix = f" ({baselined} baselined)" if baselined else ""
    print(f"tpulint: clean{suffix}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
