"""Watch a TPUJob's lifecycle as a rendered table.

Mirror of ``sdk/python/kubeflow/pytorchjob/api/py_torch_job_watch.py``:
poll the job, print NAME/STATE/TIME rows on transitions, stop on Succeeded
or Failed (py_torch_job_watch.py:29-60 renders the k8s watch stream the
same way; polling keeps this transport-agnostic).
"""
from __future__ import annotations

import time
from typing import Optional

from tpujob.api import constants as c
from tpujob.api.types import TPUJob
from tpujob.sdk.client import job_state

TERMINAL = (c.JOB_SUCCEEDED, c.JOB_FAILED)
_FMT = "{:<32} {:<12} {:<24}"


def watch_job(
    client,
    name: str,
    namespace: Optional[str] = None,
    timeout_seconds: float = 600,
    poll_interval: float = 0.5,
    out=None,
) -> TPUJob:
    """Print one row per observed state change; return the terminal job."""
    import sys

    out = out or sys.stdout
    ns = namespace or client.namespace
    print(_FMT.format("NAME", "STATE", "TIME"), file=out)
    deadline = time.monotonic() + timeout_seconds
    last_state = None
    job = None
    while time.monotonic() < deadline:
        job = client.get(name, ns)
        state = job_state(job) or "Pending"
        if state != last_state:
            print(_FMT.format(name, state, time.strftime("%H:%M:%S")), file=out)
            last_state = state
        if state in TERMINAL:
            return job
        time.sleep(poll_interval)
    raise TimeoutError(f"watch timeout for TPUJob {name} in {ns}")
