"""TPUJobClient: the SDK entrypoint.

Behavioral mirror of the reference SDK client
(``sdk/python/kubeflow/pytorchjob/api/py_torch_job_client.py``):

- create/get/patch/delete              (:29-197)
- wait_for_job / wait_for_condition    (:200-279, poll loop + timeout)
- get_job_status / is_job_running / is_job_succeeded  (:282-316)
- get_pod_names / get_logs             (:319-393, label-selector lookup)

Deltas: typed ``TPUJob`` objects instead of raw dicts (dicts accepted on
create for YAML-manifest workflows), transport injection instead of baked
kubeconfig handling (in-cluster vs kubeconfig auth lives in the transport
layer, ``tpujob.kube``), and watch-based waiting as an alternative to
polling.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Union

from tpujob.api import constants as c
from tpujob.api.defaults import set_defaults_tpujob
from tpujob.api.types import TPUJob
from tpujob.api.validation import validate_tpujob_spec
from tpujob.kube.client import ClientSet
from tpujob.kube.errors import NotFoundError

TERMINAL_CONDITIONS = (c.JOB_SUCCEEDED, c.JOB_FAILED)


def job_state(job: TPUJob) -> str:
    """Latest condition with status True ('' when none yet)."""
    latest = ""
    for cond in job.status.conditions:
        if cond.status == "True":
            latest = cond.type
    return latest


class TPUJobClient:
    """SDK client over any ApiServer-surface transport.

    ``TPUJobClient(InMemoryAPIServer())`` for tests/simulation,
    ``TPUJobClient(HTTPApiClient(url))`` for a tpujob API server, or
    ``TPUJobClient(KubeApiTransport())`` in a real cluster.
    """

    def __init__(self, transport, namespace: str = "default"):
        self.clients = ClientSet(transport)
        self.namespace = namespace

    # -- CRUD (reference :53-197) ------------------------------------------

    def create(self, job: Union[TPUJob, Dict[str, Any]],
               namespace: Optional[str] = None, validate: bool = True) -> TPUJob:
        if isinstance(job, dict):
            job = TPUJob.from_dict(job)
        if not job.metadata.namespace:
            job.metadata.namespace = namespace or self.namespace
        if validate:
            set_defaults_tpujob(job)
            errs = validate_tpujob_spec(job.spec)
            if errs:
                raise ValueError(f"invalid TPUJob spec: {'; '.join(errs)}")
        return self.clients.tpujobs.create(job)

    def get(self, name: str, namespace: Optional[str] = None) -> TPUJob:
        return self.clients.tpujobs.get(namespace or self.namespace, name)

    def patch(self, name: str, patch: Dict[str, Any],
              namespace: Optional[str] = None) -> TPUJob:
        return self.clients.tpujobs.patch(namespace or self.namespace, name, patch)

    def delete(self, name: str, namespace: Optional[str] = None) -> None:
        self.clients.tpujobs.delete(namespace or self.namespace, name)

    # -- waiting (reference :200-279) --------------------------------------

    def wait_for_job(
        self,
        name: str,
        namespace: Optional[str] = None,
        timeout_seconds: float = 600,
        polling_interval: float = 1.0,
        status_callback: Optional[Callable[[TPUJob], None]] = None,
    ) -> TPUJob:
        """Block until the job reaches Succeeded or Failed."""
        return self.wait_for_condition(
            name, TERMINAL_CONDITIONS, namespace=namespace,
            timeout_seconds=timeout_seconds, polling_interval=polling_interval,
            status_callback=status_callback,
        )

    def wait_for_condition(
        self,
        name: str,
        expected_conditions,
        namespace: Optional[str] = None,
        timeout_seconds: float = 600,
        polling_interval: float = 1.0,
        status_callback: Optional[Callable[[TPUJob], None]] = None,
    ) -> TPUJob:
        """Poll until any expected condition is True (reference :235-279)."""
        deadline = time.monotonic() + timeout_seconds
        job = None
        while time.monotonic() < deadline:
            try:
                job = self.get(name, namespace)
            except NotFoundError:
                job = None
            if job is not None:
                if status_callback:
                    status_callback(job)
                for cond in job.status.conditions:
                    if cond.type in expected_conditions and cond.status == "True":
                        return job
            time.sleep(polling_interval)
        raise TimeoutError(
            f"Timeout waiting for TPUJob {name} in namespace "
            f"{namespace or self.namespace} to enter one of the conditions "
            f"{tuple(expected_conditions)}."
        )

    # -- status predicates (reference :282-316) ----------------------------

    def get_job_status(self, name: str, namespace: Optional[str] = None) -> str:
        """Latest True condition type ('' when no status yet)."""
        return job_state(self.get(name, namespace))

    def is_job_running(self, name: str, namespace: Optional[str] = None) -> bool:
        return self.get_job_status(name, namespace) == c.JOB_RUNNING

    def is_job_succeeded(self, name: str, namespace: Optional[str] = None) -> bool:
        return self.get_job_status(name, namespace) == c.JOB_SUCCEEDED

    # -- pods & logs (reference :319-393) ----------------------------------

    def get_pod_names(
        self,
        name: str,
        namespace: Optional[str] = None,
        replica_type: Optional[str] = None,
        replica_index: Optional[int] = None,
    ) -> List[str]:
        """Pod names by the controller's labels (reference label-selector
        semantics, utils.py:20-76)."""
        selector = {c.LABEL_GROUP_NAME: c.GROUP_NAME, c.LABEL_JOB_NAME: name}
        if replica_type:
            selector[c.LABEL_REPLICA_TYPE] = replica_type.lower()
        if replica_index is not None:
            selector[c.LABEL_REPLICA_INDEX] = str(replica_index)
        pods = self.clients.pods.list(namespace or self.namespace, selector)
        return sorted(p.metadata.name for p in pods)

    def get_logs(
        self,
        name: str,
        namespace: Optional[str] = None,
        replica_type: Optional[str] = "master",
        replica_index: Optional[int] = None,
        follow: bool = False,
    ) -> Dict[str, str]:
        """{pod_name: log_text} for the selected replica pods.

        Reads through the transport's ``pod_logs`` endpoint
        (``KubeApiTransport.pod_logs`` → ``read_namespaced_pod_log`` on a
        real cluster; the in-memory simulator's log store in tests).  A
        transport without the endpoint returns empty strings but warns, so
        a silent blank result can't masquerade as empty logs (reference
        surfaces log-read errors, ``py_torch_job_client.py:319-393``).
        """
        ns = namespace or self.namespace
        names = self.get_pod_names(name, ns, replica_type, replica_index)
        server = self.clients.tpujobs.server
        reader = getattr(server, "pod_logs", None)
        if reader is None:
            import logging

            logging.getLogger("tpujob.sdk").warning(
                "transport %s has no pod_logs endpoint; get_logs returns "
                "empty strings", type(server).__name__,
            )
        out: Dict[str, str] = {}
        for pod_name in names:
            out[pod_name] = reader(ns, pod_name, follow=follow) if reader else ""
        return out
