"""User-facing Python SDK (the reference's ``sdk/python/kubeflow/pytorchjob``).

``TPUJobClient`` mirrors ``PyTorchJobClient``
(``api/py_torch_job_client.py:29-393``): create/get/patch/delete,
wait_for_job / wait_for_condition polling, status predicates, pod-name
lookup by controller labels, and log retrieval — speaking the typed TPUJob
objects of ``tpujob.api`` over any transport implementing the ApiServer
surface (in-memory, HTTP, or a real cluster).
"""
from tpujob.sdk.client import TPUJobClient  # noqa: F401
from tpujob.sdk.watch import watch_job  # noqa: F401
