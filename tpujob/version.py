"""Operator build metadata.

Mirrors reference ``version/version.go:27-40`` (``PrintVersionAndExit``:
version + git SHA on the binary).  The SHA is baked into the operator image
via the ``TPUJOB_GIT_SHA`` env (Dockerfile build arg); from a git checkout
it is read live; otherwise "unknown".
"""
from __future__ import annotations

import os
import subprocess

import tpujob


def git_sha() -> str:
    baked = os.environ.get("TPUJOB_GIT_SHA")
    if baked:
        return baked
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            capture_output=True, text=True, timeout=5,
        )
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip()
    except (OSError, subprocess.SubprocessError):
        # no git, no checkout, or a hung/slow git (TimeoutExpired) — the
        # version string must never break operator startup
        pass
    return "unknown"


def version_string() -> str:
    from tpujob.runtime import native_version

    return (
        f"tpujob-operator {tpujob.__version__} "
        f"(git {git_sha()}, native kernel {native_version})"
    )
