"""Operator CLI entrypoint: ``python -m tpujob.server [flags]``.

Mirrors reference ``cmd/pytorch-operator.v1/main.go``.
"""
from __future__ import annotations

import sys

from tpujob.server.app import OperatorApp
from tpujob.server.options import parse_options
from tpujob.version import version_string


def main(argv=None) -> int:
    opt = parse_options(argv)
    print(f"{version_string()} (apiserver={opt.apiserver})", file=sys.stderr)
    OperatorApp(opt).run(block=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
