"""Operator CLI entrypoint: ``python -m tpujob.server [flags]``.

Mirrors reference ``cmd/pytorch-operator.v1/main.go``.
"""
from __future__ import annotations

import sys

import tpujob
from tpujob.server.app import OperatorApp
from tpujob.server.options import parse_options


def main(argv=None) -> int:
    opt = parse_options(argv)
    print(f"tpujob-operator {tpujob.__version__} (apiserver={opt.apiserver})", file=sys.stderr)
    OperatorApp(opt).run(block=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
