"""Operator flags.

Mirrors reference ``cmd/pytorch-operator.v1/app/options/options.go:27-84``
(ServerOption + AddFlags), adapted: ``--apiserver`` points at the tpujob
API server (HTTP) or selects the in-process simulator.
"""
from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import List, Optional


@dataclass
class ServerOption:
    apiserver: str = "memory"  # "memory" or an http://host:port
    namespace: str = ""  # "" = all namespaces (corev1.NamespaceAll)
    threadiness: int = 1
    json_log_format: bool = True
    enable_gang_scheduling: bool = False
    gang_scheduler_name: str = "volcano"
    # /metrics + /debug listener: 0 disables, negative binds an ephemeral
    # port (tests/smokes that need a real scrapable HTTP surface without
    # fighting over fixed ports; the bound port is MonitoringServer.port)
    monitoring_port: int = 8443
    resync_period_s: float = 12 * 3600
    init_container_image: str = "alpine:3.10"
    enable_leader_election: bool = True
    leader_election_id: str = "tpujob-operator"
    # namespace holding the leader-election Lease.  "" = derive at runtime:
    # OPERATOR_NAMESPACE (downward API, reference server.go:72-76), then the
    # in-cluster serviceaccount namespace, then "default".  Without this,
    # two operators deployed in different namespaces would fight over one
    # default/tpujob-operator lease (round-3 verdict item 3).
    leader_election_namespace: str = ""
    lease_duration_s: float = 15.0
    renew_deadline_s: float = 5.0
    retry_period_s: float = 3.0
    # write fencing (rides leader election): the controller's mutating API
    # calls carry a (holder, lease-generation) token and are rejected the
    # moment leadership is lost, so a deposed leader resuming mid-handover
    # cannot double-create pods.  Only meaningful with leader election on.
    enable_fencing: bool = True
    # sharded control plane (> 0 enables): jobs hash into this many virtual
    # shards, rendezvous-assigned across the live member fleet, one fencing
    # lease per shard.  Replaces single-leader election — every member runs
    # its informers and syncs only the shards it owns.  The whole fleet
    # must agree on the count; the shardmaps/tpujob-shards object records
    # it and members adopt the recorded value over this flag.
    shard_count: int = 0
    # how long a shard handoff waits for the shard's in-flight syncs before
    # giving up on the graceful release and letting the lease expire
    shard_drain_timeout_s: float = 5.0
    qps: float = 50.0
    burst: int = 100
    # crash-loop damper: decaying delay between a counted ExitCode restart
    # and the replacement pod's creation (<= 0 = instant recreate)
    restart_backoff_s: float = 1.0
    restart_backoff_max_s: float = 300.0
    # elastic resize: how long a scale-down's checkpoint barrier waits for
    # the workload's ack before draining anyway (<= 0 skips the barrier)
    resize_drain_grace_s: float = 15.0
    # workqueue per-key failure backoff (client-go rate limiter bounds)
    workqueue_base_backoff_s: float = 0.005
    workqueue_max_backoff_s: float = 1200.0
    # flight recorder + per-sync tracing (tpujob/obs): --no-trace restores
    # the untraced hot path; the /debug/* endpoints then serve empty data
    enable_tracing: bool = True
    # a sync slower than this dumps its span tree to the log, token-bucket
    # rate-limited per job (<= 0 disables the dump)
    slow_sync_threshold_s: float = 5.0
    flight_recorder_size: int = 256  # timeline entries retained per job
    # API write path: no-op status suppression, merge-patch status writes,
    # and per-job event coalescing (see docs/monitoring "write QPS at scale")
    suppress_noop_status: bool = True
    status_patch: bool = True
    settle_window_s: float = 0.02
    # API read path: continue-token paged informer LISTs (<= 0 = one
    # unpaged LIST) and watch BOOKMARK resume points (see docs/monitoring
    # "read QPS at scale")
    informer_page_size: int = 500
    watch_bookmarks: bool = True
    # cold-start barrier budget: how long run() waits for every informer's
    # initial LIST — six-figure object counts need minutes, not seconds
    cache_sync_timeout_s: float = 120.0
    # workload telemetry plane: progress-heartbeat ingestion + tpujob_job_*
    # metrics (--no-telemetry disables the whole plane) and the stall
    # watchdog (Stalled condition after this many heartbeat-less seconds;
    # <= 0 disables the watchdog, metrics still flow)
    enable_telemetry: bool = True
    stall_timeout_s: float = 600.0
    stall_policy: str = "event"  # "event" | "restart"
    stall_check_interval_s: float = 0.0  # <= 0 derives stall_timeout / 4
    # goodput accounting plane: per-job phase ledger + tpujob_job_goodput_*
    # / tpujob_job_badput_seconds_total{phase} metrics + the projected-
    # goodput-loss victim costing the gang scheduler consumes
    # (--no-goodput disables; victim choice then falls back to raw
    # steps-past-checkpoint)
    enable_goodput: bool = True
    # native gang scheduler: modeled fleet capacity as slice pools, e.g.
    # "v4-32x4" or "v4-16x2,v5e-16x1".  Non-empty enables the admission
    # queue: jobs hold NO pods until the scheduler places their whole gang
    # all-or-nothing; "" disables (the pre-scheduler behavior).
    scheduler_capacity: str = ""
    scheduler_tick_s: float = 0.2  # decision-loop cadence
    # aging promotion: a queued gang's effective tier rises one level per
    # this many seconds waited (anti-starvation bound; <= 0 disables)
    scheduler_aging_s: float = 60.0
    # preempt lower-tier gangs under pressure (checkpoint barrier first)
    scheduler_preemption: bool = True
    # how long the preemption checkpoint barrier waits for the workload's
    # ack before evicting anyway (<= 0 evicts immediately)
    scheduler_preempt_grace_s: float = 5.0
    # elastic capacity: under pressure, shrink a lower-tier multislice gang
    # by slices (staged drain, zero strikes) before resorting to eviction,
    # and grow shrunk gangs back into idle capacity
    scheduler_flex: bool = True
    # torus defragmentation: compact fragmented free capacity by migrating
    # small gangs (checkpoint-barriered) so large contiguous gangs place
    scheduler_defrag: bool = True
    # fragmentation ratio (1 - largest free run / total free hosts) above
    # which the defragmenter starts planning compaction moves
    scheduler_defrag_threshold: float = 0.5
    # node inventory: how long a node's heartbeat lease may go unchanged
    # (controller monotonic clock) before the scheduler duty flips its
    # durable phase NotReady, excludes it from placement and migrates its
    # gangs.  Heartbeat flaps INSIDE one grace window never flip anything.
    node_grace_s: float = 30.0
    # per-node migration damper: a host may trigger at most one gang-
    # migration episode per this window (doubling per episode, capped), so
    # a flapping node can never drive a migration storm.  <= 0 derives two
    # grace periods.
    node_migration_damp_s: float = 0.0
    # fleet observatory (--observatory): an in-process thread scraping N
    # member /metrics + /debug/fleet endpoints on an interval, merging them
    # into one invariant-checked fleet view with SLO burn-rate alerting
    # (tpujob/obs/observatory; also runnable standalone via
    # `python -m tpujob.obs.observatory --targets ...`)
    enable_observatory: bool = False
    # comma-separated member base URLs to scrape; "" = self-scrape this
    # instance's own monitoring listener (single-member observatory)
    observatory_targets: str = ""
    observatory_interval_s: float = 1.0
    # HTTP port for the observatory's merged /debug/observatory +
    # /debug/alerts + /debug/why surface (0 disables, negative = ephemeral)
    observatory_port: int = 0
    # how long a partition-invariant violation (job double-exported /
    # shard orphaned) must PERSIST before it counts: the legitimate shard-
    # handoff window.  <= 0 derives lease_duration + one scrape interval.
    observatory_handoff_grace_s: float = 0.0
    # multi-cluster federation: which cluster THIS member belongs to.
    # Non-empty activates the reconciler's federation gate — a job whose
    # durable tpujob.dev/cluster annotation names another cluster is held
    # dark (no pods, no failure strikes).  "" = not federated (default;
    # every existing single-cluster deployment is unchanged).
    cluster_name: str = ""
    # federation meta-controller (--federation): an in-process replica of
    # the cluster-sharding meta-controller (tpujob/server/federation):
    # scrape every member cluster, own a rendezvous-assigned subset,
    # place/spill/rescue their jobs.  Requires cluster handles the CLI can
    # only express as scrape targets (--federation-clusters); e2e and
    # embedders construct ClusterHandles with real API transports.
    enable_federation: bool = False
    # semicolon-separated cluster specs "name=url1|url2", e.g.
    # "us-east=http://a:9443|http://b:9443;eu-west=http://c:9443"
    federation_clusters: str = ""
    federation_interval_s: float = 1.0
    # HTTP port for the merged /debug/federation surface (0 disables,
    # negative = ephemeral)
    federation_port: int = 0
    # queue wait beyond which a job spills over to a less-loaded feasible
    # cluster (two-phase transfer; <= 0 disables spillover)
    federation_spillover_wait_s: float = 30.0
    # how long a cluster must stay CONFIRMED dark (stale scrapes + no live
    # member lease on an uncached re-read) before failover fires.
    # <= 0 derives one lease term + two federation intervals.
    federation_dark_grace_s: float = 0.0
    # failover damper base: episode N of the same cluster waits
    # base * 2^(N-1) before the next failover may fire.  <= 0 derives two
    # lease terms.
    federation_damp_s: float = 0.0


class _LazyVersionAction(argparse.Action):
    """--version prints version + git SHA and exits (version.go:27-40).
    Lazy: version_string() shells out to git, which must not run on every
    operator startup just to build the parser (round-2 advisor low)."""

    def __call__(self, parser, namespace, values, option_string=None):
        from tpujob.version import version_string

        print(version_string())  # stdout, like argparse's builtin version action
        parser.exit()


def add_flags(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--version", action=_LazyVersionAction, nargs=0,
                        help="print version and exit")
    parser.add_argument("--apiserver", default="memory",
                        help="tpujob API server URL, or 'memory' for the in-process simulator")
    parser.add_argument("--namespace", default="",
                        help="namespace to watch ('' = all namespaces)")
    parser.add_argument("--threadiness", type=int, default=1,
                        help="number of concurrent reconcile workers")
    parser.add_argument("--json-log-format", action="store_true", default=True)
    parser.add_argument("--no-json-log-format", dest="json_log_format", action="store_false")
    parser.add_argument("--enable-gang-scheduling", action="store_true", default=False)
    parser.add_argument("--gang-scheduler-name", default="volcano")
    parser.add_argument("--monitoring-port", type=int, default=8443,
                        help="port for /metrics and /healthz (0 disables, "
                             "negative binds an ephemeral port)")
    parser.add_argument("--resync-period", type=float, default=12 * 3600, dest="resync_period_s")
    parser.add_argument("--init-container-image", default="alpine:3.10")
    parser.add_argument("--enable-leader-election", action="store_true", default=True)
    parser.add_argument("--no-leader-election", dest="enable_leader_election", action="store_false")
    parser.add_argument("--leader-election-id", default="tpujob-operator")
    parser.add_argument("--leader-election-namespace", default="",
                        dest="leader_election_namespace",
                        help="namespace for the leader-election Lease "
                             "(default: operator's own namespace)")
    parser.add_argument("--fencing", dest="enable_fencing", action="store_true",
                        default=True,
                        help="fence the controller's writes on the leader-"
                             "election token (default on; no-op without "
                             "leader election)")
    parser.add_argument("--no-fencing", dest="enable_fencing", action="store_false",
                        help="disable write fencing (a deposed leader's in-"
                             "flight writes are no longer rejected)")
    parser.add_argument("--shards", type=int, default=0, dest="shard_count",
                        help="enable the sharded control plane with this "
                             "many virtual job shards (0 = single elected "
                             "leader); run N replicas with the same value "
                             "to scale the controller out")
    parser.add_argument("--shard-drain-timeout", type=float, default=5.0,
                        dest="shard_drain_timeout_s",
                        help="seconds a shard handoff waits for in-flight "
                             "syncs before skipping the graceful release "
                             "(the lease then expires instead)")
    parser.add_argument("--lease-duration", type=float, default=15.0, dest="lease_duration_s")
    parser.add_argument("--renew-deadline", type=float, default=5.0, dest="renew_deadline_s")
    parser.add_argument("--retry-period", type=float, default=3.0, dest="retry_period_s")
    parser.add_argument("--kube-api-qps", type=float, default=50.0, dest="qps")
    parser.add_argument("--kube-api-burst", type=int, default=100, dest="burst")
    parser.add_argument("--restart-backoff", type=float, default=1.0,
                        dest="restart_backoff_s",
                        help="base delay between a counted ExitCode restart and "
                             "the replacement pod (exponential, decaying; <=0 "
                             "recreates instantly)")
    parser.add_argument("--restart-backoff-max", type=float, default=300.0,
                        dest="restart_backoff_max_s",
                        help="cap on the exponential restart backoff delay")
    parser.add_argument("--resize-drain-grace", type=float, default=15.0,
                        dest="resize_drain_grace_s",
                        help="seconds a scale-down's checkpoint barrier "
                             "waits for the workload's checkpoint ack "
                             "before deleting the drained replicas anyway "
                             "(<=0 drains immediately)")
    parser.add_argument("--workqueue-base-backoff", type=float, default=0.005,
                        dest="workqueue_base_backoff_s")
    parser.add_argument("--workqueue-max-backoff", type=float, default=1200.0,
                        dest="workqueue_max_backoff_s")
    parser.add_argument("--trace", dest="enable_tracing", action="store_true",
                        default=True,
                        help="per-sync tracing + flight recorder (default on)")
    parser.add_argument("--no-trace", dest="enable_tracing", action="store_false",
                        help="disable tracing/flight recorder (restores the "
                             "untraced reconcile hot path)")
    parser.add_argument("--slow-sync-threshold", type=float, default=5.0,
                        dest="slow_sync_threshold_s",
                        help="dump the span tree of any sync slower than this "
                             "many seconds, rate-limited per job (<=0 disables)")
    parser.add_argument("--flight-recorder-size", type=int, default=256,
                        dest="flight_recorder_size",
                        help="timeline entries retained per job for /debug/jobs")
    parser.add_argument("--suppress-noop-status", dest="suppress_noop_status",
                        action="store_true", default=True,
                        help="skip status writes when the recomputed status "
                             "matches the informer cache semantically "
                             "(default on)")
    parser.add_argument("--no-suppress-noop-status", dest="suppress_noop_status",
                        action="store_false",
                        help="write status on every changed sync, even when "
                             "only volatile timestamps moved")
    parser.add_argument("--status-patch", dest="status_patch",
                        action="store_true", default=True,
                        help="ship status writes as a JSON-merge-patch of "
                             "only the changed fields (default on)")
    parser.add_argument("--no-status-patch", dest="status_patch",
                        action="store_false",
                        help="restore full-object status PUTs")
    parser.add_argument("--settle-window", type=float, default=0.02,
                        dest="settle_window_s",
                        help="per-job event-coalescing window in seconds: "
                             "burst watch events on one job collapse into a "
                             "single sync scheduled this far out (<=0 "
                             "disables coalescing)")
    parser.add_argument("--informer-page-size", type=int, default=500,
                        dest="informer_page_size",
                        help="LIST chunk size (?limit=&continue=) for "
                             "informer initial syncs and relists; <=0 "
                             "restores one unpaged LIST per relist")
    parser.add_argument("--watch-bookmarks", dest="watch_bookmarks",
                        action="store_true", default=True,
                        help="request watch BOOKMARK events so quiet "
                             "informer streams resume instead of relisting "
                             "after history compaction (default)")
    parser.add_argument("--no-watch-bookmarks", dest="watch_bookmarks",
                        action="store_false",
                        help="disable watch bookmarks (reconnects without "
                             "recent data events degrade to relists)")
    parser.add_argument("--cache-sync-timeout", type=float, default=120.0,
                        dest="cache_sync_timeout_s",
                        help="seconds to wait for the informers' initial "
                             "LIST at cold start before failing; size to "
                             "your object count (100k objects needs minutes)")
    parser.add_argument("--telemetry", dest="enable_telemetry",
                        action="store_true", default=True,
                        help="ingest workload progress heartbeats "
                             "(tpujob.dev/progress pod annotations) into "
                             "per-job metrics + /debug/fleet (default on)")
    parser.add_argument("--no-telemetry", dest="enable_telemetry",
                        action="store_false",
                        help="disable the workload telemetry plane "
                             "(heartbeats ignored, watchdog off)")
    parser.add_argument("--stall-timeout", type=float, default=600.0,
                        dest="stall_timeout_s",
                        help="progress watchdog: flip a job's Stalled "
                             "condition when its reported step has not "
                             "advanced for this many seconds (resize/"
                             "restart/churn windows exempt; <=0 disables)")
    parser.add_argument("--stall-policy", choices=("event", "restart"),
                        default="event", dest="stall_policy",
                        help="what a detected stall does beyond the "
                             "condition + event: 'restart' deletes the "
                             "stuck heartbeat-publishing replica once per "
                             "stall episode")
    parser.add_argument("--stall-check-interval", type=float, default=0.0,
                        dest="stall_check_interval_s",
                        help="watchdog re-check cadence in seconds "
                             "(<=0 derives stall-timeout / 4)")
    parser.add_argument("--goodput", dest="enable_goodput",
                        action="store_true", default=True,
                        help="account every second of each job's life to a "
                             "phase ledger (goodput/badput metrics + the "
                             "scheduler's projected-loss victim costing; "
                             "default on)")
    parser.add_argument("--no-goodput", dest="enable_goodput",
                        action="store_false",
                        help="disable the goodput accounting plane (victim "
                             "choice falls back to raw steps-past-"
                             "checkpoint)")
    parser.add_argument("--sched-capacity", default="",
                        dest="scheduler_capacity",
                        help="enable the native gang scheduler with this "
                             "modeled slice capacity (e.g. 'v4-32x4' or "
                             "'v4-16x2,v5e-16x1'); jobs then queue for "
                             "all-or-nothing admission ('' disables)")
    parser.add_argument("--sched-tick", type=float, default=0.2,
                        dest="scheduler_tick_s",
                        help="gang-scheduler decision-loop cadence (s)")
    parser.add_argument("--sched-aging", type=float, default=60.0,
                        dest="scheduler_aging_s",
                        help="aging promotion: a queued gang gains one "
                             "priority tier per this many seconds waited "
                             "(anti-starvation bound; <=0 disables)")
    parser.add_argument("--sched-preemption", dest="scheduler_preemption",
                        action="store_true", default=True,
                        help="preempt lower-tier gangs under pressure, "
                             "checkpoint barrier first (default on)")
    parser.add_argument("--no-sched-preemption", dest="scheduler_preemption",
                        action="store_false",
                        help="disable preemption (queued gangs wait for "
                             "capacity to free naturally)")
    parser.add_argument("--sched-preempt-grace", type=float, default=5.0,
                        dest="scheduler_preempt_grace_s",
                        help="seconds the preemption checkpoint barrier "
                             "waits for the workload's ack before evicting "
                             "anyway (<=0 evicts immediately)")
    parser.add_argument("--sched-flex", dest="scheduler_flex",
                        action="store_true", default=True,
                        help="shrink lower-tier multislice gangs by slices "
                             "under pressure instead of evicting them, and "
                             "grow them back into idle capacity (default on)")
    parser.add_argument("--no-sched-flex", dest="scheduler_flex",
                        action="store_false",
                        help="disable num_slices flex (pressure falls back "
                             "to preemption)")
    parser.add_argument("--sched-defrag", dest="scheduler_defrag",
                        action="store_true", default=True,
                        help="compact fragmented free capacity by migrating "
                             "small gangs behind a checkpoint barrier "
                             "(default on)")
    parser.add_argument("--no-sched-defrag", dest="scheduler_defrag",
                        action="store_false",
                        help="disable torus defragmentation")
    parser.add_argument("--sched-defrag-threshold", type=float, default=0.5,
                        dest="scheduler_defrag_threshold",
                        help="fragmentation ratio (1 - largest free run / "
                             "total free hosts) above which the "
                             "defragmenter plans compaction moves")
    parser.add_argument("--node-grace", type=float, default=30.0,
                        dest="node_grace_s",
                        help="seconds a node's heartbeat lease may go "
                             "unchanged before it flips NotReady and its "
                             "gangs are migrated (flaps inside one grace "
                             "window never flip anything)")
    parser.add_argument("--node-migration-damp", type=float, default=0.0,
                        dest="node_migration_damp_s",
                        help="per-node migration damping window in seconds "
                             "(a host triggers at most one migration "
                             "episode per window, doubling per episode; "
                             "<=0 derives two node-grace periods)")
    parser.add_argument("--observatory", dest="enable_observatory",
                        action="store_true", default=False,
                        help="run the fleet observatory in-process: scrape "
                             "the member /debug/fleet endpoints on an "
                             "interval, merge them into one invariant-"
                             "checked fleet view, and evaluate the SLO "
                             "burn-rate alerts")
    parser.add_argument("--no-observatory", dest="enable_observatory",
                        action="store_false",
                        help="disable the in-process fleet observatory")
    parser.add_argument("--observatory-targets", default="",
                        dest="observatory_targets",
                        help="comma-separated member base URLs the "
                             "observatory scrapes (e.g. "
                             "'http://op-0:8443,http://op-1:8443'); empty "
                             "= scrape this instance's own listener")
    parser.add_argument("--observatory-interval", type=float, default=1.0,
                        dest="observatory_interval_s",
                        help="observatory scrape/merge cadence in seconds")
    parser.add_argument("--observatory-port", type=int, default=0,
                        dest="observatory_port",
                        help="port for the observatory's merged "
                             "/debug/observatory + /debug/alerts + "
                             "/debug/why surface (0 disables, negative = "
                             "ephemeral)")
    parser.add_argument("--observatory-handoff-grace", type=float,
                        default=0.0, dest="observatory_handoff_grace_s",
                        help="seconds a partition-invariant violation must "
                             "persist before it counts (the legitimate "
                             "shard-handoff window; <=0 derives "
                             "lease-duration + one scrape interval)")
    parser.add_argument("--cluster-name", default="", dest="cluster_name",
                        help="name of the cluster this member belongs to; "
                             "non-empty activates the federation gate (a "
                             "job owned by another cluster per its durable "
                             "tpujob.dev/cluster annotation is held dark: "
                             "no pods, no failure strikes)")
    parser.add_argument("--federation", dest="enable_federation",
                        action="store_true", default=False,
                        help="run a federation meta-controller replica "
                             "in-process: scrape every member cluster, own "
                             "a rendezvous-assigned subset, place/spill/"
                             "rescue their jobs")
    parser.add_argument("--no-federation", dest="enable_federation",
                        action="store_false",
                        help="disable the in-process federation replica")
    parser.add_argument("--federation-clusters", default="",
                        dest="federation_clusters",
                        help="semicolon-separated cluster scrape specs "
                             "'name=url1|url2', e.g. 'us-east=http://a:9443"
                             "|http://b:9443;eu-west=http://c:9443'")
    parser.add_argument("--federation-interval", type=float, default=1.0,
                        dest="federation_interval_s",
                        help="federation tick cadence in seconds")
    parser.add_argument("--federation-port", type=int, default=0,
                        dest="federation_port",
                        help="port for the merged /debug/federation "
                             "surface (0 disables, negative = ephemeral)")
    parser.add_argument("--federation-spillover-wait", type=float,
                        default=30.0, dest="federation_spillover_wait_s",
                        help="queue wait in seconds beyond which a job "
                             "spills over to a less-loaded feasible "
                             "cluster (<=0 disables spillover)")
    parser.add_argument("--federation-dark-grace", type=float, default=0.0,
                        dest="federation_dark_grace_s",
                        help="seconds a cluster must stay confirmed dark "
                             "before failover fires (<=0 derives one "
                             "lease term + two federation intervals)")
    parser.add_argument("--federation-damp", type=float, default=0.0,
                        dest="federation_damp_s",
                        help="failover damper base in seconds: episode N "
                             "of the same cluster waits base * 2^(N-1) "
                             "(<=0 derives two lease terms)")


def parse_options(argv: Optional[List[str]] = None) -> ServerOption:
    import os

    parser = argparse.ArgumentParser(prog="tpujob-operator",
                                     description="TPU-native job operator")
    add_flags(parser)
    ns = parser.parse_args(argv)
    opt = ServerOption(**{k: v for k, v in vars(ns).items() if k in ServerOption.__dataclass_fields__})
    # in-cluster namespace detection (reference server.go:72-76 reads
    # KUBEFLOW_NAMESPACE from the downward API)
    if not opt.namespace:
        opt.namespace = os.environ.get("OPERATOR_NAMESPACE", "")
    return opt
