"""Leader election over an API-server lease object.

Mirrors reference ``app/server.go:146-171``: a resource lock (there an
EndpointsLock, here a ``leases`` object in the API server) with
lease-duration/renew-deadline/retry-period semantics, an ``is_leader``
gauge, and fatal loss-of-leadership.
"""
from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Callable, Optional

from tpujob.kube.errors import ConflictError, NotFoundError
from tpujob.kube.fencing import FencingToken
from tpujob.server import metrics

log = logging.getLogger("tpujob.leaderelection")

RESOURCE_LEASES = "leases"


def rfc3339micro(ts: float) -> str:
    """coordination.k8s.io/v1 MicroTime wire format (renewTime/acquireTime)."""
    frac = int(round((ts % 1.0) * 1e6))
    if frac >= 1_000_000:  # rounding carried into the next second
        ts, frac = ts + 1, 0
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(ts)) + f".{frac:06d}Z"


def parse_lease_time(value) -> Optional[float]:
    """Epoch seconds from a MicroTime string (or a bare number, which older
    lease records may carry); ``None`` when absent or unparseable.

    Handles RFC3339 offsets ('+00:00' as well as 'Z'): another client's
    serializer may emit either.  Callers must FAIL CLOSED on None — treating
    garbage as epoch 0 would make a live leader's lease look expired and
    let a standby steal leadership (round-3 advisor finding)."""
    if value in (None, ""):
        return None
    try:
        return float(value)
    except (TypeError, ValueError):
        pass
    from datetime import datetime, timezone

    s = str(value)
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    try:
        dt = datetime.fromisoformat(s)
    except ValueError:
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


def acquire_or_renew_lease(
    server,
    namespace: str,
    name: str,
    identity: str,
    lease_duration: float,
    renewing: bool = False,
) -> Optional[int]:
    """Try to take (or renew) the named lease for ``identity``.

    Returns the lease generation (``leaseTransitions``) now held, or
    ``None`` when another holder's unexpired lease stands (or the write
    lost an optimistic-concurrency race).  ``renewing=True`` asserts the
    caller believes it ALREADY holds this lease: its own record then
    renews at a stable generation — a bump would fence the holder's own
    in-flight writes.  Any fresh acquisition (expired/released lease, or
    our own lease re-taken after a restart while not ``renewing``) bumps
    the generation, so a paused twin can never mint the same token.

    Transport errors propagate; callers own the retry cadence.  This is
    the shared core of the single-leader elector and the per-shard leases
    of the sharded control plane (``tpujob.server.sharding``).
    """
    now = time.time()
    # typed coordination.k8s.io/v1 Lease wire format: MicroTime strings
    # and integer seconds, so the record round-trips through a real
    # apiserver (client-go resourcelock.LeaseLock semantics)
    record = {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "holderIdentity": identity,
            "leaseDurationSeconds": max(1, int(round(lease_duration))),
            "acquireTime": rfc3339micro(now),
            "renewTime": rfc3339micro(now),
            "leaseTransitions": 0,
        },
    }
    try:
        current = server.get(RESOURCE_LEASES, namespace, name)
    except NotFoundError:
        try:
            server.create(RESOURCE_LEASES, record)
            return 0
        except Exception as e:
            # losing the create race (409) or a transient transport
            # error: normal contention, but never swallow it unseen
            log.debug("lease create did not win: %s", e)
            return None
    spec = current.get("spec") or {}
    holder = spec.get("holderIdentity")
    renew = parse_lease_time(spec.get("renewTime"))
    # expiry uses our configured duration when renewing our own lock;
    # for another holder, honor the duration they advertised
    advertised = spec.get("leaseDurationSeconds")
    duration = (
        lease_duration
        if holder == identity or advertised in (None, "")
        else float(advertised)
    )
    # fail closed: a held lease whose renewTime we cannot parse is
    # treated as live — stealing from a healthy leader (split-brain)
    # is far worse than waiting for it to release or rewrite the lease
    expired = renew is not None and now - renew > duration
    if holder == identity or expired or not holder:
        if holder == identity and renewing:
            # our own renewal: the fencing generation must stay stable
            # for the whole tenure or every renew would fence ourselves
            record["spec"]["acquireTime"] = spec.get("acquireTime") or rfc3339micro(now)
            record["spec"]["leaseTransitions"] = int(spec.get("leaseTransitions") or 0)
        else:
            # any FRESH acquisition bumps the generation — including a
            # restarted process with a stable configured identity taking
            # its dead predecessor's expired lease.  Keying on the
            # holder string alone would mint the predecessor's exact
            # token and a paused twin could write through the fence.
            transitions = int(spec.get("leaseTransitions") or 0)
            record["spec"]["leaseTransitions"] = transitions + 1
        record["metadata"]["resourceVersion"] = (current.get("metadata") or {}).get(
            "resourceVersion"
        )
        try:
            server.update(RESOURCE_LEASES, record)
            return int(record["spec"]["leaseTransitions"])
        except (ConflictError, NotFoundError):
            return None
    return None


def release_lease(server, namespace: str, name: str, identity: str) -> None:
    """Graceful release: zero ``holderIdentity`` on our own lease so a
    standby (or our own restart) acquires immediately instead of waiting
    out the lease duration (client-go ReleaseOnCancel).  The lease object
    itself survives — deleting it would reset ``leaseTransitions`` and
    with it the monotonic generation the fencing tokens depend on."""
    try:
        current = server.get(RESOURCE_LEASES, namespace, name)
    except Exception as e:
        # best effort: a failed release degrades to the lease expiring
        log.warning("lease read for release failed (standby must wait "
                    "it out): %s", e)
        return
    spec = current.get("spec") or {}
    if spec.get("holderIdentity") != identity:
        return  # not ours: never clobber another holder's lease
    record = {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "resourceVersion": (current.get("metadata") or {}).get("resourceVersion"),
        },
        "spec": {
            "holderIdentity": "",
            "leaseDurationSeconds": spec.get("leaseDurationSeconds"),
            "acquireTime": spec.get("acquireTime"),
            "renewTime": rfc3339micro(time.time()),
            "leaseTransitions": int(spec.get("leaseTransitions") or 0),
        },
    }
    try:
        server.update(RESOURCE_LEASES, record)
    except Exception as e:
        # best effort: a failed release degrades to the lease expiring
        log.warning("lease release failed (standby must wait it out): %s", e)


class LeaderElector:
    def __init__(
        self,
        server,  # ApiServer-interface transport
        lock_name: str = "tpujob-operator",
        namespace: str = "default",
        identity: Optional[str] = None,
        lease_duration: float = 15.0,
        renew_deadline: float = 5.0,
        retry_period: float = 3.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        self.server = server
        self.lock_name = lock_name
        self.namespace = namespace
        self.identity = identity or f"{lock_name}-{uuid.uuid4().hex[:8]}"
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.is_leader = False
        # this instance's own observed transitions (acquire/loss/release):
        # the deterministic per-elector view of the process-global
        # leader_transitions metric, which concurrent electors share
        self.transitions = 0
        # the lease generation (leaseTransitions) our current leadership was
        # acquired at: half of the fencing token.  Written by the elector
        # thread on every successful acquire/renew, read by FencedTransport
        # from worker threads (single attribute, atomic enough).
        self._generation = 0
        # a hard kill (crash simulation) clears this so the stale lease
        # stays in place and a standby must wait out lease_duration — the
        # crash-only failure mode the chaos harness exercises
        self.release_on_stop = True
        # the OnStartedLeading thread (see run()), exposed so an embedding
        # app can join it on shutdown — the controller's worker threads are
        # only known once this callback returns
        self.leading_thread: Optional[threading.Thread] = None

    def current_token(self) -> Optional[FencingToken]:
        """The fencing token of the CURRENT leadership, None when not
        leading — the ``fence`` provider for :class:`FencedTransport`."""
        if not self.is_leader:
            return None
        return FencingToken(self.identity, self._generation)

    # -- lock record ---------------------------------------------------------

    def _try_acquire_or_renew(self) -> bool:
        try:
            return self._try_acquire_or_renew_inner()
        except (NotFoundError, ConflictError):
            return False
        except Exception as e:  # transport errors must NOT kill the elector:
            # a dead elector thread with a live controller is split-brain
            log.warning("leader election transport error: %s", e)
            return False

    def _try_acquire_or_renew_inner(self) -> bool:
        generation = acquire_or_renew_lease(
            self.server, self.namespace, self.lock_name, self.identity,
            self.lease_duration, renewing=self.is_leader)
        if generation is None:
            return False
        self._generation = generation
        return True

    def release(self) -> None:
        """Graceful release (see :func:`release_lease`): zero
        ``holderIdentity`` on our own lease so a standby — or our own
        restart — acquires immediately instead of waiting out
        ``lease_duration``."""
        release_lease(self.server, self.namespace, self.lock_name, self.identity)

    # -- run loop ------------------------------------------------------------

    def run(self, stop_event: threading.Event) -> None:
        """Blocks: acquire, then renew until loss (which is fatal, like the
        reference) or stop."""
        while not stop_event.is_set():
            if self._try_acquire_or_renew():
                break
            log.info("%s waiting for leadership", self.identity)
            if stop_event.wait(self.retry_period):
                return
        if stop_event.is_set():
            # stopped right as the acquire succeeded: we hold the lease but
            # never led — a graceful stop must still hand it back
            if self.release_on_stop:
                self.release()
            return
        self.is_leader = True
        metrics.is_leader.set(1)
        self.transitions += 1
        metrics.leader_transitions.inc()
        log.info("%s became leader (generation %d)", self.identity, self._generation)
        if self.on_started_leading:
            # separate thread, like client-go's OnStartedLeading goroutine:
            # the controller's cold start (informer LIST + cache sync) can
            # outlast lease_duration on a big cluster, and running it inline
            # would block renewal — the lease would expire MID cold start
            # and a standby would steal leadership from a healthy leader
            t = threading.Thread(target=self.on_started_leading, daemon=True,
                                 name="leading-callback")
            t.start()
            # published only once started: a racing joiner must never see an
            # unstarted Thread (join would raise) — and joining the elector
            # thread first (see OperatorApp._stop_threads) makes this
            # publication visible before anyone reads it
            self.leading_thread = t
        while not stop_event.is_set():
            # the renew deadline is a DURATION: it must ride the monotonic
            # clock — an NTP step during the window would otherwise expire
            # a healthy renewal loop early (or stretch it past the lease)
            deadline = time.monotonic() + self.renew_deadline
            renewed = False
            while time.monotonic() < deadline and not stop_event.is_set():
                if self._try_acquire_or_renew():
                    renewed = True
                    break
                time.sleep(min(0.1, self.retry_period))
            if stop_event.is_set():
                break
            if not renewed:
                self.is_leader = False
                metrics.is_leader.set(0)
                self.transitions += 1
                metrics.leader_transitions.inc()
                log.error("%s lost leadership", self.identity)
                if self.on_stopped_leading:
                    self.on_stopped_leading()
                return
            if stop_event.wait(self.retry_period):
                break
        # clean stop: zero holderIdentity for a fast failover.  A hard kill
        # (release_on_stop=False, crash simulation) skips BOTH the release
        # and the transition count — a SIGKILLed process could report
        # neither, and the simulated crash must not skew the
        # leader_transitions series operators alert on
        self.is_leader = False
        metrics.is_leader.set(0)
        if self.release_on_stop:
            self.transitions += 1
            metrics.leader_transitions.inc()
            self.release()
