"""Sharded control plane: N controller instances, one fleet.

The single elected leader (``leader_election.py``) syncs every job; at
placement-scale job counts the controller itself is the bottleneck and the
single point of failure.  This module shards the job set across a fleet:

- **Job → shard**: consistent hash of the job UID over a fixed number of
  virtual shards (``shard_of_uid``).  The mapping never moves — only the
  shard → member assignment does — so rebalance cost is bounded by shards,
  never by jobs.
- **Shard → member**: rendezvous (highest-random-weight) hashing over the
  live membership (``rendezvous_owner``).  Every member computes the same
  assignment from the same membership view; adding a member moves only the
  shards the newcomer wins (≈ 1/N of them, all TO the newcomer), removing
  one moves only its own shards.
- **Membership**: one heartbeat lease per member
  (``tpujob-member-<identity>``); a member whose lease expires is treated
  as dead and its shards rebalance to the survivors.
- **Shard map**: one ``shardmaps/tpujob-shards`` object in the API server
  records the fleet-wide shard count (the one number every member MUST
  agree on — a mismatch would map one job to two different shards and
  reopen the double-sync window; members adopt the map's count over their
  local flag) plus a best-effort view of current assignments for
  operators.
- **Per-shard fencing**: one fencing lease per shard
  (``tpujob-shard-<i>``), the PR-4 generation machinery applied per shard.
  Every mutating call a member makes while syncing a job carries a
  :class:`~tpujob.kube.fencing.FencingToken` naming that job's shard lease
  at the generation the shard was acquired; the fence-validating server
  rejects a deposed owner's stale generation server-side.
- **Handoff protocol**: releasing a shard first marks it *draining* (the
  controller drops its keys at dequeue), then waits for the shard's
  in-flight syncs to finish (``on_shard_drain``), and only then zeroes the
  shard lease.  A drain that times out skips the release and lets the
  lease expire instead — in either case there is no instant at which two
  members may sync the same job: the old owner stops syncing before the
  new owner can acquire.  Acquisition mirrors it: the crash-loop damper is
  rebuilt for the shard (``on_shard_prepare``) BEFORE the shard turns
  active, then every cached job of the shard is enqueued
  (``on_shard_acquired``) so events filtered while another member owned it
  are reconstructed from the shared informer cache.
"""
from __future__ import annotations

import contextlib
import contextvars
import hashlib
import logging
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence, Set

from tpujob.analysis import lockgraph
from tpujob.api import constants as c
from tpujob.kube.errors import AlreadyExistsError, ConflictError, NotFoundError
from tpujob.kube.fencing import FencingToken
from tpujob.server import metrics
from tpujob.server.leader_election import (
    RESOURCE_LEASES,
    acquire_or_renew_lease,
    parse_lease_time,
    release_lease,
    rfc3339micro,
)

log = logging.getLogger("tpujob.sharding")

RESOURCE_SHARD_MAPS = "shardmaps"
SHARD_MAP_NAME = "tpujob-shards"
SHARD_LEASE_PREFIX = "tpujob-shard"
MEMBER_LEASE_PREFIX = "tpujob-member"


def stable_hash(value: str) -> int:
    """Process-independent 64-bit hash.  Every member must map the same uid
    to the same shard and score rendezvous candidates identically, and
    Python's builtin ``hash()`` is salted per process."""
    return int.from_bytes(hashlib.md5(value.encode("utf-8")).digest()[:8], "big")


def shard_of_uid(uid: str, num_shards: int) -> int:
    """The shard a job UID lives in — fixed for the job's whole life."""
    return stable_hash(f"uid:{uid}") % num_shards


def rendezvous_owner(shard, members: Sequence[str]) -> Optional[str]:
    """Highest-random-weight owner of ``shard`` among ``members``.

    Deterministic in the (unordered) membership set.  Adding a member
    reassigns exactly the shards the newcomer wins — on average 1/N of
    them — and never shuffles a shard between two surviving members;
    removing one reassigns only the shards it owned.

    ``shard`` is any stable key: shard INDICES here, cluster NAMES in the
    federation meta-controller (the same 1/N stability argument holds at
    cluster granularity — that reuse is why the key is not typed int)."""
    best: Optional[str] = None
    best_w = -1
    for m in members:
        w = stable_hash(f"shard:{shard}:member:{m}")
        if w > best_w or (w == best_w and (best is None or m < best)):
            best, best_w = m, w
    return best


def shard_lease_name(shard: int) -> str:
    return f"{SHARD_LEASE_PREFIX}-{shard}"


def member_lease_name(identity: str) -> str:
    return f"{MEMBER_LEASE_PREFIX}-{identity}"


def heartbeat_member_lease(server, namespace: str, identity: str,
                           lease_duration: float,
                           prefix: str = MEMBER_LEASE_PREFIX) -> None:
    """Write one membership heartbeat lease (create-or-renew).  The lease
    name embeds the identity, so there is no contention — only our own
    stale record — and generations are irrelevant: membership only needs
    liveness, the per-duty leases carry the fencing generations.

    Module-level because TWO membership planes heartbeat this way: shard
    coordinators (``tpujob-member-*``) and federation replicas
    (``prefix`` selects the plane)."""
    now = time.time()
    name = f"{prefix}-{identity}"
    record = {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "holderIdentity": identity,
            "leaseDurationSeconds": max(1, int(round(lease_duration))),
            "acquireTime": rfc3339micro(now),
            "renewTime": rfc3339micro(now),
            "leaseTransitions": 0,
        },
    }
    try:
        current = server.get(RESOURCE_LEASES, namespace, name)
    except NotFoundError:
        try:
            server.create(RESOURCE_LEASES, record)
            return
        except AlreadyExistsError:
            current = server.get(RESOURCE_LEASES, namespace, name)
    spec = current.get("spec") or {}
    record["spec"]["acquireTime"] = spec.get("acquireTime") or rfc3339micro(now)
    record["metadata"]["resourceVersion"] = (
        (current.get("metadata") or {}).get("resourceVersion"))
    try:
        server.update(RESOURCE_LEASES, record)
    except (ConflictError, NotFoundError):
        pass  # raced (only ever with our own writes); next tick renews


def live_lease_holders(server, namespace: str, prefix: str,
                       default_duration: float,
                       now: Optional[float] = None) -> List[str]:
    """Identities of every member whose ``<prefix>-*`` heartbeat lease is
    unexpired.

    Fail closed on an unparseable renewTime (treat the member as live, the
    elector's rule): evicting a healthy member on garbage would hand its
    shards — or, in the federation plane, its clusters — to a rival while
    it still syncs them, exactly the double-sync window this module exists
    to close.  An empty holderIdentity is a graceful departure and is
    excluded; a lease expired past its own declared duration (falling back
    to ``default_duration`` when it declares none) is dead."""
    now = time.time() if now is None else now
    out: List[str] = []
    for lease in server.list(RESOURCE_LEASES, namespace):
        name = (lease.get("metadata") or {}).get("name") or ""
        if not name.startswith(f"{prefix}-"):
            continue
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        if not holder:
            continue  # gracefully departed
        renew = parse_lease_time(spec.get("renewTime"))
        duration = float(spec.get("leaseDurationSeconds")
                         or default_duration)
        if renew is not None and now - renew > duration:
            continue  # expired: the member is dead
        out.append(holder)
    return sorted(set(out))


# The shard the in-flight sync (or informer-handler write) belongs to.  Set
# by the controller strictly around the work for one job, so it propagates
# through the transport stack — and through the slow-start batch pool,
# which runs its tasks under copied contexts — down to FencedTransport's
# token provider without plumbing (the PR-4 call-token pattern).
_SYNC_SHARD: "contextvars.ContextVar[Optional[int]]" = contextvars.ContextVar(
    "tpujob_sync_shard", default=None
)


def current_sync_shard() -> Optional[int]:
    """The shard attached to the in-flight sync (None = no shard context)."""
    return _SYNC_SHARD.get()


@contextlib.contextmanager
def sync_shard(shard: Optional[int]):
    token = _SYNC_SHARD.set(shard)
    try:
        yield
    finally:
        _SYNC_SHARD.reset(token)


class ShardCoordinator:
    """One fleet member's shard lifecycle: heartbeat, rebalance, handoff.

    Runs a single background loop (:meth:`run`, elector-style): heartbeat
    the member lease, observe the live membership, renew owned shard
    leases, hand off shards rendezvous hashing no longer assigns here, and
    acquire newly-assigned shards once their previous owner released them
    (or their lease expired).  The controller consults :meth:`is_active`
    at enqueue and dequeue, and :meth:`current_call_token` fences every
    mutating call on the owning shard's lease generation.
    """

    def __init__(
        self,
        server,  # ApiServer-interface transport (unfenced, like the elector's)
        num_shards: int,
        identity: Optional[str] = None,
        namespace: str = "default",
        lease_duration: float = 15.0,
        retry_period: float = 3.0,
        drain_timeout: float = 5.0,
        on_shard_prepare: Optional[Callable[[int], None]] = None,
        on_shard_acquired: Optional[Callable[[int], None]] = None,
        on_shard_drain: Optional[Callable[[int, float], bool]] = None,
    ):
        self.server = server
        self.num_shards = int(num_shards)
        self.identity = identity or f"tpujob-member-{uuid.uuid4().hex[:8]}"
        self.namespace = namespace or "default"
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self.drain_timeout = drain_timeout
        # acquisition hooks: prepare runs BEFORE the shard turns active (no
        # worker can be syncing its jobs yet — the damper-rebuild window),
        # acquired runs after (the enqueue replay); drain is the handoff
        # barrier and must return True only when no in-flight sync remains
        self.on_shard_prepare = on_shard_prepare
        self.on_shard_acquired = on_shard_acquired
        self.on_shard_drain = on_shard_drain
        self._lock = lockgraph.new_lock("shard-coordinator")
        # shard -> lease generation it was acquired at (the fencing half)
        self._owned: Dict[int, int] = {}  # guarded by self._lock
        # shards mid-handoff: still leased (in-flight syncs keep their valid
        # tokens) but no longer active (no NEW sync may start)
        self._draining: Set[int] = set()  # guarded by self._lock
        # monotonic stamp of each shard's last successful lease renewal: a
        # shard not renewed for a full lease_duration is treated as lost
        # even if no rival was observed (our writes would be server-fenced
        # the moment one takes it — stop issuing them at the source)
        self._renewed: Dict[int, float] = {}  # guarded by self._lock
        # last observed live membership (observability/tests)
        self._members: List[str] = []  # guarded by self._lock
        # this instance's own acquisition+release/loss count: the
        # deterministic per-member view of the process-global
        # shard_rebalances_total metric, which a multi-member test shares
        self.rebalances = 0  # guarded by self._lock

    # -- sharding surface consumed by the controller -------------------------

    def shard_of_uid(self, uid: str) -> int:
        return shard_of_uid(uid, self.num_shards)

    def is_active(self, shard: int) -> bool:
        """True iff this member currently owns ``shard`` and is not
        draining it — the only state in which a sync of its jobs may
        START here."""
        with self._lock:
            return shard in self._owned and shard not in self._draining

    def owned_shards(self) -> List[int]:
        with self._lock:
            return sorted(self._owned)

    def members(self) -> List[str]:
        with self._lock:
            return list(self._members)

    def sync_shard_context(self, shard: Optional[int]):
        """Context manager binding ``shard`` to the calls it encloses (see
        :func:`sync_shard`); the controller wraps each sync in it."""
        return sync_shard(shard)

    def token_for_shard(self, shard: int) -> Optional[FencingToken]:
        """The fencing token of this member's CURRENT tenure over ``shard``
        (None when not held) — valid through a drain, dead after release."""
        with self._lock:
            generation = self._owned.get(shard)
        if generation is None:
            return None
        return FencingToken(self.identity, generation,
                            lease=shard_lease_name(shard))

    def current_call_token(self) -> Optional[FencingToken]:
        """The ``fence`` provider for :class:`FencedTransport`: the token of
        the in-flight sync's shard, or None (= reject locally) when the
        call has no shard context or the shard is no longer held."""
        shard = current_sync_shard()
        if shard is None:
            return None
        return self.token_for_shard(shard)

    # -- membership ----------------------------------------------------------

    def _heartbeat(self) -> None:
        """Write our member lease — the shared membership heartbeat
        (:func:`heartbeat_member_lease`) on the shard plane's prefix."""
        heartbeat_member_lease(self.server, self.namespace, self.identity,
                               self.lease_duration)

    def _live_members(self) -> List[str]:
        """Identities of every member whose heartbeat lease is unexpired —
        the shared fail-closed read (:func:`live_lease_holders`) on the
        shard plane's prefix."""
        return live_lease_holders(self.server, self.namespace,
                                  MEMBER_LEASE_PREFIX, self.lease_duration)

    # -- shard map -----------------------------------------------------------

    def _ensure_shard_map(self) -> None:
        """Create the fleet's shard-map object, or adopt its shard count.

        The shard count is the one parameter every member MUST agree on:
        a member running with a different ``--shards`` flag would hash the
        same job into a different shard id and the exactly-one-owner
        invariant would no longer cover it.  First member in wins; everyone
        else adopts the map's count (logging loudly on mismatch) before
        acquiring anything."""
        record = {
            "apiVersion": c.API_VERSION,
            "kind": "ShardMap",
            "metadata": {"name": SHARD_MAP_NAME, "namespace": self.namespace},
            "spec": {"shards": self.num_shards},
            "status": {"assignments": {}},
        }
        try:
            current = self.server.get(
                RESOURCE_SHARD_MAPS, self.namespace, SHARD_MAP_NAME)
        except NotFoundError:
            try:
                self.server.create(RESOURCE_SHARD_MAPS, record)
                return
            except AlreadyExistsError:
                current = self.server.get(
                    RESOURCE_SHARD_MAPS, self.namespace, SHARD_MAP_NAME)
        declared = int(((current.get("spec") or {}).get("shards"))
                       or self.num_shards)
        if declared != self.num_shards:
            log.error(
                "shard map %s declares %d shards but this member was "
                "configured with %d; adopting the map's count — a split "
                "shard-count fleet would double-sync jobs",
                SHARD_MAP_NAME, declared, self.num_shards)
            self.num_shards = declared

    def _update_shard_map(self, shard: int, holder: str, generation: int) -> None:
        """Best-effort assignment record for operators (/debug + kubectl);
        the per-shard leases stay the authoritative fencing state."""
        entry = ({"holder": holder, "generation": generation}
                 if holder else None)  # None deletes the key (merge patch)
        try:
            self.server.patch(
                RESOURCE_SHARD_MAPS, self.namespace, SHARD_MAP_NAME,
                {"status": {"assignments": {str(shard): entry}}})
        except Exception as e:  # noqa: TPL005 - observability write only;
            log.debug("shard map update failed (best effort): %s", e)

    # -- rebalance / handoff -------------------------------------------------

    def _tick(self) -> None:
        # the starvation sweep runs FIRST and unconditionally: during a
        # transport outage the heartbeat/membership calls below fail and
        # skip the rest of the tick, and a deposed member that cannot
        # reach the API server must still stop minting shard tokens once
        # a full lease_duration passed without a successful renewal — a
        # rival may already own its shards
        now = time.monotonic()
        with self._lock:
            starved = [s for s, renewed in self._renewed.items()
                       if s in self._owned
                       and now - renewed > self.lease_duration]
        for shard in starved:
            self._lost(shard, "renewal starved past lease_duration")
        self._heartbeat()
        members = self._live_members()
        with self._lock:
            self._members = list(members)
            owned_now = dict(self._owned)
        if self.identity in members:
            desired = {s for s in range(self.num_shards)
                       if rendezvous_owner(s, members) == self.identity}
        else:
            # our own heartbeat is not visible (expired or unreadable):
            # assume deposed and shed everything — the conservative side of
            # the exactly-one-owner invariant
            desired = set()
        for shard in sorted(set(owned_now) & desired):
            self._renew_shard(shard)
        for shard in sorted(set(owned_now) - desired):
            self._handoff(shard)
        for shard in sorted(desired - set(owned_now)):
            self._try_acquire(shard)

    def _renew_shard(self, shard: int) -> None:
        try:
            generation = acquire_or_renew_lease(
                self.server, self.namespace, shard_lease_name(shard),
                self.identity, self.lease_duration, renewing=True)
        except Exception as e:
            # transient transport error: retry next tick; sustained
            # failure is handled by _tick's unconditional starvation
            # sweep (which also covers outages that fail the tick before
            # this method ever runs)
            log.warning("shard %d: lease renewal failed: %s", shard, e)
            return
        if generation is None:
            self._lost(shard, "lease shows another holder")
            return
        with self._lock:
            if shard in self._owned:
                self._owned[shard] = generation
                self._renewed[shard] = time.monotonic()

    def _try_acquire(self, shard: int) -> None:
        try:
            generation = acquire_or_renew_lease(
                self.server, self.namespace, shard_lease_name(shard),
                self.identity, self.lease_duration, renewing=False)
        except Exception as e:
            log.warning("shard %d: acquisition attempt failed: %s", shard, e)
            return
        if generation is None:
            return  # previous owner's lease still stands: wait it out
        # prepare BEFORE activation: the crash-loop damper rebuild for the
        # shard's jobs must not race a worker already syncing them — no
        # worker can, because is_active is still False
        if self.on_shard_prepare is not None:
            try:
                self.on_shard_prepare(shard)
            except Exception:
                log.exception("shard %d: prepare hook failed", shard)
        with self._lock:
            self._owned[shard] = generation
            self._renewed[shard] = time.monotonic()
            self.rebalances += 1
        metrics.shard_rebalances.inc()
        metrics.shard_ownership.labels(shard=str(shard)).set(1)
        log.info("%s acquired shard %d (generation %d)",
                 self.identity, shard, generation)
        self._update_shard_map(shard, self.identity, generation)
        if self.on_shard_acquired is not None:
            try:
                self.on_shard_acquired(shard)
            except Exception:
                log.exception("shard %d: acquired hook failed", shard)

    def _handoff(self, shard: int) -> None:
        """Drain-before-release: mark draining (no new sync starts), wait
        out the in-flight syncs, then zero the shard lease so the next
        owner acquires immediately.  A drain that times out (a wedged
        sync may still write) skips the release — the lease expiring is
        the safe fallback, exactly like the app-shutdown rule."""
        started = time.monotonic()
        with self._lock:
            if shard not in self._owned:
                return
            self._draining.add(shard)
        drained = True
        if self.on_shard_drain is not None:
            try:
                drained = bool(self.on_shard_drain(shard, self.drain_timeout))
            except Exception:
                log.exception("shard %d: drain hook failed", shard)
                drained = False
        if drained:
            release_lease(self.server, self.namespace,
                          shard_lease_name(shard), self.identity)
            self._update_shard_map(shard, "", 0)
        else:
            log.warning(
                "shard %d: drain timed out; NOT releasing — an in-flight "
                "write may still land, so the next owner must wait out the "
                "lease", shard)
        with self._lock:
            self._owned.pop(shard, None)
            self._draining.discard(shard)
            self._renewed.pop(shard, None)
            self.rebalances += 1
        metrics.shard_rebalances.inc()
        metrics.shard_ownership.labels(shard=str(shard)).set(0)
        metrics.shard_handoff_duration.observe(time.monotonic() - started)
        log.info("%s released shard %d (drained=%s, handoff %.3fs)",
                 self.identity, shard, drained, time.monotonic() - started)

    def _lost(self, shard: int, why: str) -> None:
        """Deposed without a handoff (lease stolen after expiry, renewal
        starved): drop ownership immediately.  No drain — the rival may
        already be syncing; our in-flight writes die at the server-side
        fence (stale generation), and new syncs never start because
        is_active flipped."""
        with self._lock:
            if self._owned.pop(shard, None) is None:
                return
            self._draining.discard(shard)
            self._renewed.pop(shard, None)
            self.rebalances += 1
        metrics.shard_rebalances.inc()
        metrics.shard_ownership.labels(shard=str(shard)).set(0)
        log.error("%s lost shard %d (%s); fence closed locally",
                  self.identity, shard, why)

    def release_all(self) -> None:
        """Graceful departure: zero every owned shard lease plus the member
        heartbeat, so survivors rebalance immediately instead of waiting
        out lease_duration.  Callers must have drained the workers first
        (OperatorApp.shutdown joins them before calling this) — there is
        deliberately no in-loop release on stop, because the coordinator
        thread cannot know whether a worker still has a write in flight."""
        with self._lock:
            owned = sorted(self._owned)
            self._owned.clear()
            self._draining.clear()
            self._renewed.clear()
            self.rebalances += len(owned)
        for shard in owned:
            release_lease(self.server, self.namespace,
                          shard_lease_name(shard), self.identity)
            metrics.shard_ownership.labels(shard=str(shard)).set(0)
            metrics.shard_rebalances.inc()
            self._update_shard_map(shard, "", 0)
        release_lease(self.server, self.namespace,
                      member_lease_name(self.identity), self.identity)

    # -- run loop ------------------------------------------------------------

    def run(self, stop_event: threading.Event) -> None:
        """Blocks until stop: ensure the shard map, then tick forever."""
        while not stop_event.is_set():
            try:
                self._ensure_shard_map()
                break
            except Exception as e:
                # transport errors must NOT kill the coordinator: a dead
                # coordinator thread with live workers is split-brain (the
                # elector's rule, applied here)
                log.warning("shard map bootstrap failed: %s", e)
            if stop_event.wait(self.retry_period):
                return
        while not stop_event.is_set():
            try:
                self._tick()
            except Exception as e:
                log.warning("shard coordinator tick failed: %s", e)
            if stop_event.wait(self.retry_period):
                return
