"""Native gang scheduler: all-or-nothing admission, fair share, preemption.

The upstream operator punts gang scheduling to kube-batch (it only renders a
PodGroup, ``jobcontroller.go:224-278``) — nothing in the repo decided WHICH
job runs WHERE or WHEN, so an oversubscribed fleet wedged capacity with
partially-created gangs and starved low-priority jobs forever.  This module
is the native replacement: an admission queue in front of the reconciler.

Contract:

- **All-or-nothing admission.**  A job enters the queue the moment it is
  created (the reconciler's admission gate holds its pods back); the
  scheduler admits the WHOLE gang against a modeled fleet of TPU slices
  (``--sched-capacity``, e.g. ``v4-16x2``) or not at all.  The admission is
  one durable annotation write (``tpujob.dev/sched-assignment``), so there
  is no instant at which a gang holds part of its capacity.
- **Topology-aware placement.**  Each gang slice packs onto torus-adjacent
  hosts of one fleet slice (contiguous intervals of the snake host order,
  ``api/quota.py``); multislice gangs take distinct slices of one pool.
  Never-placeable shapes are rejected at admission with a durable Failed
  condition (written by the reconciler gate) — an infeasible gang cannot
  wedge the queue.
- **Priority tiers + fair share + aging.**  Queue order is effective tier
  (declared tier promoted one level per ``--sched-aging`` waited — the
  anti-starvation bound), then per-namespace dominant-share (chips of the
  modeled fleet), then FIFO.
- **Checkpoint-aware preemption.**  Under pressure a higher-tier gang
  preempts lower-tier victims chosen by lowest PROJECTED GOODPUT LOST
  (redo-the-at-risk-steps at the victim's own observed step rate plus its
  observed restore + requeue costs, from the goodput phase ledger; jobs
  with no ledger keep the legacy raw steps-past-checkpoint ordering via
  the heartbeat fallback).  Eviction is the
  PR-9 drain protocol re-aimed: publish ``tpujob.dev/preempt-target``, wait
  the bounded checkpoint barrier (workload ack / telemetry checkpoint
  catch-up / grace), then mark ``tpujob.dev/sched-evicted`` — the
  reconciler deletes the pods (NOT failure strikes) and the capacity is
  released only once the last pod is gone, so a re-admission can never land
  on hosts the victim still occupies.
- **Elastic capacity (num_slices flex).**  Before evicting anyone, the
  pressure planner tries the CHEAPER move: shrink a running low-tier
  multislice gang by whole slices (``tpujob.dev/flex-slices``) through the
  staged-resize drain barrier — zero failure strikes, the workload
  checkpoints and re-rendezvouses at the smaller world — down to its
  declared floor (``schedulingPolicy.minSlices`` / the min-slices
  annotation).  A background grower flexes shrunk gangs back into idle
  capacity, fair-share ordered, one slice per tick.  Moves are priced by
  the goodput ledger: flex (restore only) < migrate (redo + restore) <
  preempt (redo + restore + requeue) by construction, so the cheapest
  plan wins.
- **Torus defragmentation.**  An idle-tick planner watches the
  fragmentation ratio (1 - largest free contiguous run / total free
  hosts) and, past a threshold, compacts the cheapest telemetry-backed
  small gang through the ordinary checkpoint-barrier migration so large
  contiguous gangs become placeable WITHOUT preempting anyone.
- **Crash/handoff resumability.**  Every decision is an annotation already
  committed; each tick re-derives the whole capacity model from the
  informer cache (the PR-9 staging-record stance).  In a sharded fleet the
  scheduler duty rides shard 0: only the member owning it runs ticks, and
  every write carries shard 0's fencing token so a deposed scheduler is
  rejected server-side.
"""
from __future__ import annotations

import collections
import functools
import json
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from tpujob.analysis import lockgraph
from tpujob.api import constants as c
from tpujob.api.defaults import set_defaults_tpujob
from tpujob.api.quota import (
    GangRequest,
    SlicePoolSpec,
    TIER_MAX,
    capacity_chips,
    effective_tier,
    feasibility_errors,
    flex_request,
    gang_request,
    namespace_share,
    parse_capacity,
    pool_fits,
    queue_sort_key,
)
from tpujob.api.nodes import (
    is_cordoned,
    node_name,
    node_phase,
    synthesize_nodes,
)
from tpujob.api.topology import TopologyError
from tpujob.api.types import TPUJob
from tpujob.controller import barrier
from tpujob.controller import status as st
from tpujob.kube.client import RESOURCE_NODES, RESOURCE_TPUJOBS
from tpujob.kube.control import gen_labels
from tpujob.kube.errors import AlreadyExistsError, ApiError, NotFoundError
from tpujob.kube.informers import INDEX_JOB_NAME
from tpujob.obs.goodput import GoodputView, heartbeat_view
from tpujob.server import metrics
from tpujob.server.inventory import Inventory, NodeHealth, build_inventory

log = logging.getLogger("tpujob.scheduler")

# In a sharded fleet the scheduler duty rides this shard: the member owning
# it runs the decision loop, and every admission write carries its fencing
# token (a deposed scheduler's writes die server-side, the PR-8 contract).
SCHEDULER_SHARD = 0


_parse_wall = st.parse_iso  # THE status-timestamp parser, one grammar


# ---------------------------------------------------------------------------
# placements
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SlicePlacement:
    pool: int  # index into the capacity pools
    slice_index: int  # which slice of the pool
    host_lo: int  # first host (inclusive) in snake order
    host_hi: int  # last host (exclusive)


@dataclass(frozen=True)
class Assignment:
    """One admitted gang's placement — the payload of the durable
    ``tpujob.dev/sched-assignment`` annotation."""

    accelerator: str  # pool accelerator the gang landed on
    slices: Tuple[SlicePlacement, ...]
    chips: int  # modeled chip cost (dominant-share accounting)

    def to_json(self) -> str:
        return json.dumps({
            "accelerator": self.accelerator,
            "chips": self.chips,
            "slices": [{"pool": s.pool, "slice": s.slice_index,
                        "hosts": [s.host_lo, s.host_hi]} for s in self.slices],
        }, sort_keys=True, separators=(",", ":"))

    @classmethod
    def from_json(cls, raw: str) -> Optional["Assignment"]:
        try:
            d = json.loads(raw)
            slices = tuple(
                SlicePlacement(pool=int(s["pool"]),
                               slice_index=int(s["slice"]),
                               host_lo=int(s["hosts"][0]),
                               host_hi=int(s["hosts"][1]))
                for s in d["slices"])
            return cls(accelerator=str(d.get("accelerator") or ""),
                       slices=slices, chips=int(d.get("chips") or 0))
        except (ValueError, KeyError, TypeError):
            return None


@functools.lru_cache(maxsize=512)
def _parse_assignment_cached(raw: str) -> Optional[Assignment]:
    """Memoized Assignment parse for the per-replica ``node_for`` path: the
    reconciler asks once per missing index (node-gate) and again per pod
    build, all against the identical annotation string — O(replicas)
    redundant JSON parses per sync otherwise (the PR-11 gang-request-cache
    lesson).  Callers must treat the shared instance as read-only."""
    return Assignment.from_json(raw)


def assignment_node(asg: Assignment, ordinal: int) -> Optional[str]:
    """The Node name the ``ordinal``-th replica of an admitted gang runs
    on: replicas fill each slice's torus-adjacent host run in order, one
    host per replica, clamped to the assignment's extent (a gang whose
    replica count outgrew its placement is mid-re-place; the clamp keeps
    the binding total until the new assignment commits)."""
    if not asg.slices or ordinal < 0:
        return None
    hps = asg.slices[0].host_hi - asg.slices[0].host_lo
    if hps <= 0:
        return None
    si = min(ordinal // hps, len(asg.slices) - 1)
    s = asg.slices[si]
    host = min(s.host_lo + ordinal % hps, s.host_hi - 1)
    return node_name(asg.accelerator, s.pool, s.slice_index, host)


def trimmed_assignment(asg: Assignment, flex: int) -> Assignment:
    """The assignment narrowed to its first ``flex`` slices — the flex
    drain removes the HIGHEST replica indices, which map onto the HIGHEST
    slice indices of the placement, so the surviving gang keeps exactly
    the leading slices.  Chips shrink proportionally (every slice of an
    assignment costs the same)."""
    keep = asg.slices[:flex]
    per_slice = asg.chips // len(asg.slices) if asg.slices else 0
    return Assignment(accelerator=asg.accelerator, slices=keep,
                      chips=per_slice * len(keep))


class CapacityModel:
    """Host-interval occupancy over the fleet's slice pools.

    Hosts of one slice are numbered along the snake order (``api/quota``),
    so a contiguous ``[lo, hi)`` interval IS a torus-adjacent host path;
    allocation is first-fit contiguous per slice.  ``unavailable`` is the
    health gate: host coordinates whose node is NotReady, cordoned, or
    absent from the inventory — :meth:`place` can never allocate across
    them, atomically with the all-or-nothing guarantee (the whole gang
    lands on healthy hosts or nothing is mutated).  Single-threaded by
    design: only the scheduler tick mutates a model, and the preemption
    planner works on :meth:`clone` copies.
    """

    def __init__(self, pools: List[SlicePoolSpec],
                 unavailable: Optional[set] = None):
        self.pools = pools
        self.unavailable = frozenset(unavailable or ())
        # (pool, slice) -> sorted [lo, hi) intervals with their owner keys
        self._used: Dict[Tuple[int, int], List[Tuple[int, int, str]]] = {}
        # (pool, slice) -> sorted blocked host indices, from unavailable
        self._blocked: Dict[Tuple[int, int], List[int]] = {}
        for pool, si, host in self.unavailable:
            self._blocked.setdefault((pool, si), []).append(host)
        for hosts in self._blocked.values():
            hosts.sort()

    def clone(self) -> "CapacityModel":
        out = CapacityModel(self.pools, self.unavailable)
        out._used = {k: list(v) for k, v in self._used.items()}
        return out

    def reserve(self, owner: str, asg: Assignment) -> List[str]:
        """Re-reserve a durable assignment while rebuilding the model from
        the informer cache.  Returns any conflicts found (an overlap means
        corrupt state — two committed assignments share hosts — which the
        tick reports loudly but does not amplify with more writes)."""
        problems: List[str] = []
        for s in asg.slices:
            if s.pool >= len(self.pools) \
                    or s.slice_index >= self.pools[s.pool].count \
                    or s.host_hi > self.pools[s.pool].shape.hosts \
                    or s.host_lo < 0 or s.host_lo >= s.host_hi:
                problems.append(
                    f"{owner}: assignment {s} exceeds the modeled capacity")
                continue
            ivals = self._used.setdefault((s.pool, s.slice_index), [])
            for lo, hi, other in ivals:
                if s.host_lo < hi and lo < s.host_hi:
                    problems.append(
                        f"{owner}: hosts [{s.host_lo},{s.host_hi}) of pool "
                        f"{s.pool} slice {s.slice_index} overlap {other} "
                        f"[{lo},{hi})")
            ivals.append((s.host_lo, s.host_hi, owner))
            ivals.sort()
        return problems

    def release(self, owner: str) -> None:
        for key, ivals in list(self._used.items()):
            kept = [iv for iv in ivals if iv[2] != owner]
            if kept:
                self._used[key] = kept
            else:
                self._used.pop(key, None)

    def _free_interval(self, pool: int, slice_index: int,
                       need: int) -> Optional[int]:
        """First-fit contiguous free interval of ``need`` hosts (snake
        order = torus-adjacent) that avoids both reservations and
        unavailable (dead/cordoned/absent) hosts, or None."""
        for lo, hi in self.free_runs(pool, slice_index):
            if hi - lo >= need:
                return lo
        return None

    def _outside(self, pool: int, slice_index: int, host: int) -> bool:
        """A coordinate beyond the pools' current extents: deleting a
        pool's HIGHEST slice (or a whole pool) shrinks the derived grid, so
        its hosts never enter ``unavailable`` — they simply stop existing.
        An assignment still naming them is stranded all the same."""
        if pool >= len(self.pools):
            return True
        p = self.pools[pool]
        return slice_index >= p.count or host >= p.shape.hosts

    def blocked_hosts(self, asg: Assignment) -> List[Tuple[int, int, int]]:
        """Host coordinates of ``asg`` that are currently unavailable
        (dead/cordoned, or outside the live grid entirely) — the trigger
        for checkpoint-aware gang migration."""
        out: List[Tuple[int, int, int]] = []
        for s in asg.slices:
            for h in range(s.host_lo, s.host_hi):
                if ((s.pool, s.slice_index, h) in self.unavailable
                        or self._outside(s.pool, s.slice_index, h)):
                    out.append((s.pool, s.slice_index, h))
        return out

    def place(self, req: GangRequest, owner: str) -> Optional[Assignment]:
        """All-or-nothing placement: ``num_slices`` distinct slices of ONE
        pool, each with a torus-adjacent run of ``hosts_per_slice`` hosts.
        Mutates the model on success; touches nothing on failure — no gang
        is ever partially placed."""
        for pi, pool in enumerate(self.pools):
            if not pool_fits(req, pool):
                continue
            found: List[SlicePlacement] = []
            for si in range(pool.count):
                lo = self._free_interval(pi, si, req.hosts_per_slice)
                if lo is None:
                    continue
                found.append(SlicePlacement(
                    pool=pi, slice_index=si,
                    host_lo=lo, host_hi=lo + req.hosts_per_slice))
                if len(found) == req.num_slices:
                    break
            if len(found) < req.num_slices:
                continue
            asg = Assignment(accelerator=pool.accelerator,
                             slices=tuple(found),
                             chips=req.chips_on(pool))
            for s in found:
                ivals = self._used.setdefault((s.pool, s.slice_index), [])
                ivals.append((s.host_lo, s.host_hi, owner))
                ivals.sort()
            return asg
        return None

    def used_hosts(self) -> int:
        return sum(hi - lo for ivals in self._used.values()
                   for lo, hi, _ in ivals)

    def total_hosts(self) -> int:
        return sum(p.count * p.shape.hosts for p in self.pools)

    def free_runs(self, pool: int, slice_index: int) -> List[Tuple[int, int]]:
        """The free contiguous ``[lo, hi)`` host runs of one slice — the
        gaps between reservations and blocked (unavailable) hosts, i.e.
        everywhere :meth:`_free_interval` could land an allocation."""
        hosts = self.pools[pool].shape.hosts
        occupied = list(self._used.get((pool, slice_index), []))
        occupied += [(h, h + 1, "") for h in
                     self._blocked.get((pool, slice_index), ())]
        occupied.sort()
        runs: List[Tuple[int, int]] = []
        cursor = 0
        for lo, hi, _ in occupied:
            if lo > cursor:
                runs.append((cursor, lo))
            cursor = max(cursor, hi)
        if hosts > cursor:
            runs.append((cursor, hosts))
        return runs


# ---------------------------------------------------------------------------
# torus defragmentation (pure planner: unit-testable without a scheduler)
# ---------------------------------------------------------------------------


def fragmentation_stats(cap: CapacityModel) -> Tuple[int, int]:
    """(largest free contiguous run, total free hosts) across the fleet."""
    largest = 0
    total = 0
    for pi, pool in enumerate(cap.pools):
        for si in range(pool.count):
            for lo, hi in cap.free_runs(pi, si):
                total += hi - lo
                largest = max(largest, hi - lo)
    return largest, total


def fragmentation_ratio(cap: CapacityModel) -> float:
    """How shredded the free capacity is: 0.0 = every free host sits in
    one contiguous (placeable) run, -> 1.0 = the free hosts are scattered
    in slivers no gang can use.  0.0 when nothing is free at all — a full
    fleet is not fragmented, it is busy."""
    largest, total = fragmentation_stats(cap)
    if total <= 0:
        return 0.0
    return 1.0 - largest / float(total)


@dataclass(frozen=True)
class DefragMove:
    """One planned compaction: migrate ``key`` off ``src`` so the freed
    hosts merge into a larger contiguous run; ``dst`` is where the same
    first-fit placement the real re-admission runs will land it."""

    key: str
    src: Assignment
    dst: Assignment


def plan_defrag(cap: CapacityModel,
                gangs: List[Tuple[str, Assignment, GangRequest]],
                max_moves: int = 1) -> List[DefragMove]:
    """Greedy compaction plan over a CLONE of the capacity model.

    ``gangs`` are the movable candidates in preference order (cheapest
    projected migration cost first).  Each accepted move must STRICTLY
    grow the largest free contiguous run — the planner's whole point is
    making a bigger gang placeable, and a move that merely shuffles equal
    fragments is churn.  Moves apply to the simulation sequentially, so
    the emitted list is executable in order: each ``dst`` was placed by
    the same first-fit that will re-place the gang for real, against the
    exact occupancy the earlier moves leave behind.  Each gang moves at
    most once per plan.
    """
    sim = cap.clone()
    moves: List[DefragMove] = []
    moved: set = set()
    for _ in range(max(0, max_moves)):
        base_largest, _ = fragmentation_stats(sim)
        best = None
        for key, asg, req in gangs:
            if key in moved:
                continue
            trial = sim.clone()
            trial.release(key)
            dst = trial.place(req, key)
            if dst is None or dst.slices == asg.slices:
                continue  # nowhere better to go (or first-fit lands back)
            largest, _ = fragmentation_stats(trial)
            if largest <= base_largest:
                continue  # no strict gain: not worth a checkpoint barrier
            if best is None or largest > best[0]:
                best = (largest, key, asg, dst, trial)
        if best is None:
            break
        _, key, asg, dst, trial = best
        sim = trial
        moved.add(key)
        moves.append(DefragMove(key=key, src=asg, dst=dst))
    return moves


# ---------------------------------------------------------------------------
# the scheduler
# ---------------------------------------------------------------------------


@dataclass
class _Admitted:
    key: str
    namespace: str
    name: str
    tier: int
    assignment: Assignment
    evicting: bool  # eviction marker set: pods being vacated
    preempting: bool  # preempt target published, barrier pending
    req: Optional[GangRequest] = None  # the SPEC-shaped request
    flex: Optional[int] = None  # flexed slice count (None = full shape)
    ann: Dict[str, str] = field(repr=False, default_factory=dict)


class GangScheduler:
    """The admission decision loop.  One instance rides one controller; the
    reconciler consults it (``unschedulable_errors``, ``queue_position``)
    and holds pods back for unadmitted jobs (the admission gate)."""

    def __init__(
        self,
        controller,
        capacity: str,
        tick_s: float = 0.2,
        aging_s: float = 60.0,
        enable_preemption: bool = True,
        preempt_grace_s: float = 5.0,
        node_grace_s: float = 30.0,
        node_damp_s: float = 0.0,
        enable_flex: bool = True,
        enable_defrag: bool = True,
        defrag_threshold: float = 0.5,
    ):
        self.controller = controller
        # --sched-capacity is the BOOTSTRAP: it synthesizes Node objects on
        # the first active tick of an empty inventory, and every subsequent
        # tick rebuilds the capacity model from the live Node informer
        # cache.  self.pools tracks the currently effective pools (rebound
        # atomically each tick; placement_errors reads it lock-free).
        self.bootstrap_capacity = capacity
        self.bootstrap_pools = parse_capacity(capacity)
        self.pools = self.bootstrap_pools
        self.fleet_chips = capacity_chips(self.pools)
        self.tick_s = tick_s
        self.aging_s = aging_s
        self.enable_preemption = enable_preemption
        self.preempt_grace_s = preempt_grace_s
        self.node_grace_s = node_grace_s
        self.enable_flex = enable_flex
        self.enable_defrag = enable_defrag
        self.defrag_threshold = defrag_threshold
        self._lock = lockgraph.new_lock("gang-scheduler")
        # node heartbeat health + per-node migration damper (LRU-bounded,
        # swept on node delete).  Guarded by self._lock: the tick's
        # inventory rebuild and the reconciler's node_excluded gate share it.
        self.health = NodeHealth(node_grace_s, node_damp_s)  # guarded by self._lock
        # "modeled" until the Node informer cache shows live inventory;
        # then "nodes" — surfaced in /debug/fleet's scheduler block
        self._inventory_mode = "modeled"  # guarded by self._lock
        self._nodes_bootstrapped = False
        self._bootstrap_started = False
        self._capacity_warned = False
        # node health flips committed but not yet echoed by the cache
        # (the _release_sent discipline applied to NotReady/Ready writes)
        self._health_sent: Dict[str, str] = {}  # guarded by self._lock
        # host coordinates unavailable as of the last tick (for debug)
        self._last_inventory: Optional[Inventory] = None  # guarded by self._lock
        self.migrations = 0  # guarded by self._lock; lifetime migration count
        # never-placeable verdicts keyed to the spec generation they were
        # computed against, consumed by the reconciler gate (which writes
        # the durable Failed condition).  Generation-keyed so a legal spec
        # fix racing the tick can never be failed on a verdict for the OLD
        # shape — Failed is irreversible.
        self._unschedulable: Dict[str, Tuple[int, List[str]]] = {}  # guarded by self._lock
        # per-incarnation queue anchors (durable floor: the Queued
        # condition's transition timestamp)
        self._queued_anchor: Dict[str, float] = {}  # guarded by self._lock
        # per-incarnation preemption barrier anchors (durable floor: the
        # preempt-target annotation's timestamp)
        self._preempt_anchor: Dict[str, float] = {}  # guarded by self._lock
        # admissions written but not yet echoed by the informer cache: the
        # scheduler's expectations ledger.  A rebuild from a cache that
        # trails our own committed admission would see those hosts as free
        # and double-book them — exactly the partial/overlapping placement
        # the all-or-nothing contract forbids.  Entries retire when the
        # cache shows the assignment (or the job vanished).
        self._pending_admissions: Dict[str, Assignment] = {}  # guarded by self._lock
        # gang requests cached by (uid, generation): the request is a pure
        # function of the spec, and generation bumps exactly when the spec
        # changes — so the heavyweight dataclass parse runs once per spec
        # revision, not once per job per tick (pruned with the other maps)
        self._req_cache: Dict[Tuple, Tuple[Optional[GangRequest], Optional[str]]] = {}  # guarded by self._lock
        # release patches already committed, keyed by the assignment value
        # they released: until the cache echoes the removal, every tick
        # would otherwise re-issue the same idempotent patch — pure write
        # amplification under load.  An entry retires when the cache shows
        # the annotation gone (or a NEW assignment value, a re-admission).
        self._release_sent = barrier.SentLedger()  # guarded by self._lock
        # preempt-target publishes committed but not yet echoed by the
        # cache: dedups the publish (a re-issue from a stale-cache tick
        # would wipe an ack the workload just wrote) and marks the victim
        # in-flight for the preemption planner across the echo window
        self._preempt_sent = barrier.SentLedger()  # guarded by self._lock
        # flex-slices writes committed but not yet echoed: until the echo,
        # the value we committed IS the gang's flex target (a stale-cache
        # tick must neither re-shrink nor double-grow it)
        self._flex_sent = barrier.SentLedger()  # guarded by self._lock
        # queue positions of the last tick (debug + /debug/fleet)
        self._queue_view: List[Dict[str, Any]] = []  # guarded by self._lock
        self._decisions: collections.deque = collections.deque(maxlen=64)  # guarded by self._lock
        # per-job decision rings (the /debug/why surface): every entry
        # carries a monotonic per-job seq plus the duty epoch, so a merged
        # reader detects gaps after a handoff (seq restarts, epoch rises)
        # instead of silently splicing two members' histories.  Bounded per
        # job (deque maxlen) and pruned with the other per-job maps.
        self._rings: Dict[str, collections.deque] = {}  # guarded by self._lock
        self._ring_seq: Dict[str, int] = {}  # guarded by self._lock
        self._ring_epoch = 0  # guarded by self._lock; bumps per duty acquisition
        self._duty_active = False  # guarded by self._lock
        # last per-job queue verdict (why-not-running), recorded into the
        # ring only on CHANGE so a stable wait does not wash the ring out
        self._verdicts: Dict[str, Dict[str, Any]] = {}  # guarded by self._lock
        # admitted-state view of the last tick (explain() reads it)
        self._admitted_view: Dict[str, Dict[str, Any]] = {}  # guarded by self._lock
        self._tick_durations: collections.deque = collections.deque(maxlen=512)  # guarded by self._lock
        self.admissions = 0  # guarded by self._lock; lifetime admission count
        self.preemptions = 0  # guarded by self._lock; lifetime preemption count
        self.flexes = 0  # guarded by self._lock; lifetime flex moves (both ways)
        self.defrag_moves = 0  # guarded by self._lock; lifetime defrag moves
        self._thread: Optional[threading.Thread] = None

    # -- surface consumed by the reconciler gate -----------------------------

    def placement_errors(self, job: TPUJob) -> Optional[List[str]]:
        """Feasibility verdict for the exact job object the caller holds —
        a pure function of the fleet pools and the spec, so every fleet
        member's admission gate judges its own shards' jobs locally
        (without waiting for, or racing, the shard-0 decision loop), and a
        verdict can never be stale against the spec it is applied to."""
        try:
            req = gang_request(job)
        except TopologyError:
            return None  # unresolvable: strict validation fails it
        return self._never_placeable(req)

    def _never_placeable(self, req: GangRequest) -> Optional[List[str]]:
        """NEVER-placeable means infeasible on the fleet at FULL health:
        the verdict is irreversible (a durable Failed condition), so it
        must hold against both the live Node-derived pools AND the
        bootstrap shape — a half-bootstrapped or degraded inventory
        (dead slice, deleted nodes) transiently shrinks the live pools,
        and failing a gang that fits the configured fleet would convert a
        recoverable outage into a permanent verdict.  Such gangs queue
        instead."""
        errs = feasibility_errors(req, self.pools)
        if errs and feasibility_errors(req, self.bootstrap_pools):
            return errs
        return None

    def unschedulable_errors(self, key: str,
                             generation: Optional[int] = None
                             ) -> Optional[List[str]]:
        """The durable-verdict feed: why this job can never be placed
        (None = feasible, or not yet examined).  ``generation`` is the
        spec generation of the job the CALLER is holding: a verdict
        computed against any other generation answers None — the spec
        changed under the tick, and the next tick re-judges the new shape
        (an irreversible Failed must never land on a stale verdict)."""
        with self._lock:
            entry = self._unschedulable.get(key)
            if entry is None:
                return None
            gen, errs = entry
            if generation is not None and gen != generation:
                return None
            return list(errs) if errs else None

    def queue_position(self, key: str) -> Optional[int]:
        with self._lock:
            for row in self._queue_view:
                if row["job"] == key:
                    return row["position"]
            return None

    def request_summary(self, job: TPUJob) -> str:
        try:
            req = gang_request(job)
        except TopologyError as e:
            return f"unresolvable shape ({e})"
        what = req.accelerator or "any-slice"
        return (f"{req.num_slices} slice(s) of {what} x "
                f"{req.hosts_per_slice} host(s)")

    # -- run loop ------------------------------------------------------------

    def start(self, stop_event: threading.Event) -> threading.Thread:
        # start before publish: a shutdown racing construction must never
        # join a created-but-unstarted Thread (TPL001)
        thread = threading.Thread(target=self.run, args=(stop_event,),
                                  daemon=True, name="gang-scheduler")
        thread.start()
        self._thread = thread
        return thread

    def run(self, stop_event: threading.Event) -> None:
        while not stop_event.wait(self.tick_s):
            try:
                self.tick()
            except Exception:
                # a scheduler tick must never die permanently: transient
                # transport faults retry next tick (the coordinator's rule)
                log.exception("scheduler tick failed; retrying next tick")

    def _active(self) -> bool:
        """Whether this instance currently holds the scheduler duty: the
        owner of SCHEDULER_SHARD in a sharded fleet, everyone otherwise
        (single-leader instances only run the thread while leading)."""
        sharder = self.controller.sharder
        if sharder is None:
            return True
        return sharder.is_active(SCHEDULER_SHARD)

    # -- node inventory ------------------------------------------------------

    def _node_store(self):
        informer = getattr(self.controller, "node_informer", None)
        return informer.store if informer is not None else None

    @staticmethod
    def _zero_node_gauges() -> None:
        """The one-exporter-per-series handoff discipline (the
        sched_queue_depth / tpujob_job_* stance): a member that is not the
        scheduler duty — or whose inventory is empty/modeled — must not
        keep exporting the last active tick's node counts next to the live
        owner's, or fleet-wide sums double-count."""
        for state in ("ready", "not_ready", "cordoned"):
            metrics.node_count.labels(state=state).set(0)

    def _refresh_inventory(self, now: float):
        """Rebuild (pools, unavailable hosts) from the live Node informer
        cache — the tick's view of what hardware actually exists and is
        healthy.  An empty inventory bootstraps Node objects from the
        ``--sched-capacity`` string (once) and places against the modeled
        pools until the cache echoes them, so every pre-inventory shape
        keeps working unchanged."""
        store = self._node_store()
        nodes = store.list() if store is not None else []
        if store is not None and not self._nodes_bootstrapped:
            if not nodes:
                # an empty inventory starts the bootstrap; pre-existing
                # nodes (a REAL inventory) mean there is nothing to seed
                self._bootstrap_started = True
            if self._bootstrap_started:
                # resume until complete: a chaos-faulted partial bootstrap
                # must not strand a half-synthesized fleet (the cache going
                # non-empty is no proof every host was created)
                self._bootstrap_nodes(nodes)
            else:
                self._nodes_bootstrapped = True
        if not nodes:
            with self._lock:
                self._inventory_mode = "modeled"
                self._last_inventory = None
            self.pools = self.bootstrap_pools
            self.fleet_chips = capacity_chips(self.pools)
            self._zero_node_gauges()
            return self.pools, set()
        with self._lock:
            inv = build_inventory(nodes, self.health, now)
            self._last_inventory = inv
            self._inventory_mode = "nodes"
        if inv.has_real_nodes and self.bootstrap_capacity \
                and not self._capacity_warned:
            # one-time (per process) warning: both a capacity string and a
            # live inventory are configured — the string is only the
            # bootstrap fallback and the Node objects win from here on
            self._capacity_warned = True
            log.warning(
                "--sched-capacity %r is configured alongside a live Node "
                "inventory (%d node(s)); the capacity string is a bootstrap "
                "fallback only — placement follows the Node objects, and "
                "the string is ignored while any Node exists",
                self.bootstrap_capacity, len(nodes))
        if any(p.count for p in inv.pools):
            self.pools = inv.pools
        else:
            self.pools = self.bootstrap_pools  # every node malformed
        self.fleet_chips = capacity_chips(self.pools)
        metrics.node_count.labels(state="ready").set(len(inv.ready))
        metrics.node_count.labels(state="not_ready").set(len(inv.not_ready))
        metrics.node_count.labels(state="cordoned").set(len(inv.cordoned))
        self._reconcile_node_health(nodes, inv)
        return self.pools, inv.unavailable

    def _bootstrap_nodes(self, cached: List[Dict[str, Any]]) -> None:
        """Synthesize Node objects from the bootstrap capacity string: one
        Node per modeled host, seeded once against an empty inventory and
        RESUMED across ticks until every host exists.  Transient write
        faults retry next tick; an already-exists answer means another
        member (or a previous incarnation) won the race — both count."""
        have = {(m.get("metadata") or {}).get("name") for m in cached}
        done = 0
        total = 0
        for obj in synthesize_nodes(self.bootstrap_pools):
            total += 1
            if obj["metadata"]["name"] in have:
                done += 1
                continue
            try:
                self.controller.clients.server.create(RESOURCE_NODES, obj)
                done += 1
            except AlreadyExistsError:
                done += 1
            except ApiError as e:
                log.warning("node bootstrap: create %s failed (%s); "
                            "retrying next tick",
                            obj["metadata"]["name"], e)
                return  # partial bootstrap: resumed next tick
        self._nodes_bootstrapped = done == total
        if self._nodes_bootstrapped:
            log.info("node inventory bootstrapped: %d host(s) synthesized "
                     "from --sched-capacity %r", done,
                     self.bootstrap_capacity)

    def _reconcile_node_health(self, nodes: List[Dict[str, Any]],
                               inv: Inventory) -> None:
        """Flip the durable Ready/NotReady verdict (with the taint
        annotation recording why) for nodes whose effective health
        diverged — the scheduler duty's write, deduped per target phase
        until the cache echoes it."""
        live_names = set()
        for obj in nodes:
            name = (obj.get("metadata") or {}).get("name") or ""
            live_names.add(name)
            phase = node_phase(obj)
            with self._lock:
                stale_age = self.health.stale_for(obj)
                sent = self._health_sent.get(name)
                if sent == phase:
                    self._health_sent.pop(name, None)  # echo landed
                    sent = None
            if stale_age is not None and phase != c.NODE_NOT_READY:
                if sent == c.NODE_NOT_READY:
                    continue  # committed, waiting for the echo
                # confirming UNCACHED read before the irreversible-looking
                # flip (the adopt path's quorum-recheck stance): a broken
                # watch/relist can freeze the cached heartbeat and
                # masquerade as node silence — if the fresh read shows the
                # lease advanced, observe() re-anchors and no flip happens
                stale_age = self._confirm_stale(name)
                if stale_age is None:
                    continue
                taint = (f"heartbeat stale for {stale_age:.1f}s "
                         f"(grace {self.node_grace_s:g}s)")
                if self._flip_node(name, c.NODE_NOT_READY, taint):
                    metrics.node_transitions.labels(
                        to="not_ready").inc()
                    self._note("node-notready", f"node/{name}", taint)
            elif phase == c.NODE_NOT_READY and not is_cordoned(obj):
                with self._lock:
                    alive = (self.health.observe(obj)
                             and self.health.stale_for(obj) is None)
                if not alive or sent == c.NODE_READY:
                    continue
                if self._flip_node(name, c.NODE_READY, None):
                    metrics.node_transitions.labels(to="ready").inc()
                    self._note("node-ready", f"node/{name}",
                               "heartbeat resumed; taint cleared")
        with self._lock:
            for name in [n for n in self._health_sent
                         if n not in live_names]:
                self._health_sent.pop(name, None)

    def _confirm_stale(self, name: str) -> Optional[float]:
        """Re-read the node uncached and re-judge its heartbeat: the stale
        age when genuinely silent, None when the fresh read shows the lease
        advanced (the cache was lying) or the read failed (confirm again
        next tick — deferring a flip is the safe direction)."""
        try:
            fresh = self.controller.clients.server.get(
                RESOURCE_NODES, "default", name)
        except ApiError:
            return None
        with self._lock:
            self.health.observe(fresh)  # re-anchors if the lease advanced
            return self.health.stale_for(fresh)

    def _flip_node(self, name: str, phase: str,
                   taint: Optional[str]) -> bool:
        """Commit one durable node-health flip: the taint annotation (the
        WHY) rides a metadata patch, the phase a status patch.  False =
        did not commit (retried next tick)."""
        server = self.controller.clients.server
        try:
            server.patch(RESOURCE_NODES, "default", name, {
                "metadata": {"annotations": {
                    c.ANNOTATION_NODE_TAINT: taint}}})
            server.patch_status(RESOURCE_NODES, "default", name,
                                {"phase": phase})
        except NotFoundError:
            return False
        except ApiError as e:
            log.warning("node %s: health flip to %s failed (%s); retrying "
                        "next tick", name, phase, e)
            return False
        with self._lock:
            self._health_sent[name] = phase
        return True

    def _maybe_migrate(self, entry: _Admitted, asg: Assignment,
                       cap: CapacityModel, now: float) -> None:
        """Checkpoint-aware gang migration: a gang with any replica on a
        dead/cordoned/absent host is driven through the existing
        checkpoint-barrier eviction (publish target -> ack-or-grace ->
        evict with no failure strike -> re-queue with an aging head-start
        -> re-admit on healthy hosts).  Damped per-node so a flapping host
        can never trigger a migration storm."""
        blocked = cap.blocked_hosts(asg)
        if not blocked:
            return
        names = sorted({node_name(asg.accelerator, p, s, h)
                        for p, s, h in blocked})
        with self._lock:
            if not any(self.health.migration_allowed(n, now)
                       for n in names):
                return  # every trigger host is inside its damping window
        if not self._patch(entry.namespace, entry.name,
                           barrier.preempt_target_patch(
                               {c.ANNOTATION_MIGRATED_FROM:
                                ",".join(names)}),
                           f"migrate (host(s) {names} unavailable)"):
            return  # did not commit: retried next tick
        metrics.sched_migrations.inc()
        with self._lock:
            self.migrations += 1
            self._preempt_sent.record(entry.key)
            for n in names:
                self.health.note_migration(n, now)
            if self.aging_s > 0:
                # aging head-start: the migrated gang re-queues at its own
                # tier as if it had already waited one aging period — a
                # migration must not send a long-running job to the back
                # of the line behind fresh arrivals
                head_start = now - self.aging_s
                cur = self._queued_anchor.get(entry.key)
                self._queued_anchor[entry.key] = (
                    head_start if cur is None else min(cur, head_start))
        entry.preempting = True
        self._note("migrate", entry.key,
                   f"host(s) {', '.join(names)} dead/cordoned; migrating "
                   "through the checkpoint barrier")
        view = self.goodput_view(entry.key)
        self._note_move(entry.key, "migrate",
                        float("inf") if view is None else view.migrate_loss_s)
        self.controller.enqueue_job(entry.key)

    # -- reconciler-facing node surface --------------------------------------

    def node_excluded(self, name: Optional[str]) -> bool:
        """Whether pods must not be (re)created onto this host right now:
        cordoned, durably NotReady (even if heartbeats just resumed — pods
        wait for the scheduler duty's Ready flip-back, so birth follows the
        committed truth), locally heartbeat-stale, or absent from a live
        inventory.  Judged from the shared node informer cache + this
        member's OWN monotonic anchors, so every fleet member gates its
        own creations without waiting on the shard-0 decision loop."""
        if not name:
            return False
        store = self._node_store()
        if store is None:
            return False
        obj = store.get("default", name)
        with self._lock:
            if obj is None:
                # no Node object: with a live inventory the host does not
                # exist; in modeled mode (pre-bootstrap echo) nothing is
                # excluded — the pre-inventory behavior
                return self._inventory_mode == "nodes"
            if not self.health.observe(obj):
                return True
        return (is_cordoned(obj)
                or node_phase(obj) == c.NODE_NOT_READY)

    def node_dead(self, name: Optional[str]) -> bool:
        """Whether the host is confirmed dead (NOT merely cordoned): its
        heartbeat is stale past grace, its durable verdict is NotReady, or
        its Node object is gone from a live inventory.  Gates the release
        of a vacated gang's reservation when terminating pods linger on a
        host that will never confirm their deletion."""
        if not name:
            return False
        store = self._node_store()
        if store is None:
            return False
        obj = store.get("default", name)
        with self._lock:
            if obj is None:
                return self._inventory_mode == "nodes"
            if is_cordoned(obj):
                return False  # cordoned is administrative, not dead
            if self.health.stale_for(obj) is not None:
                return True
        return node_phase(obj) == c.NODE_NOT_READY

    def node_for(self, job: TPUJob, rtype: str, index: int) -> Optional[str]:
        """The host the gang's committed assignment binds this replica to
        (None = unadmitted or unparsable).  Deterministic: replicas map
        onto the assignment's torus-adjacent host runs in coordinator-first
        ordinal order, so the reconciler, the chaos harness and the
        invariant trackers all agree on the pod->Node edge."""
        ann = job.metadata.annotations or {}
        raw = ann.get(c.ANNOTATION_SCHED_ASSIGNMENT)
        if raw is None:
            return None
        asg = _parse_assignment_cached(raw)
        if asg is None or not asg.slices:
            return None
        masters = 0
        if rtype != c.REPLICA_TYPE_MASTER:
            mspec = job.spec.tpu_replica_specs.get(c.REPLICA_TYPE_MASTER)
            if mspec is not None:
                masters = (mspec.replicas if mspec.replicas is not None
                           else 1)
        ordinal = index if rtype == c.REPLICA_TYPE_MASTER else masters + index
        return assignment_node(asg, ordinal)

    def forget_node(self, name: str) -> None:
        """Node object deleted: sweep its per-node damper/anchor/flip
        ledgers (the LRU-map hygiene the PR-3 token buckets follow) so a
        long node-churn soak cannot grow them without bound."""
        with self._lock:
            self.health.forget(name)
            self._health_sent.pop(name, None)

    # -- the decision tick ---------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One admission pass.  Stateless-by-rebuild: the capacity model,
        the queue, and every in-flight preemption are re-derived from the
        informer cache (committed annotations), so a crashed or
        rebalanced-in scheduler resumes mid-protocol for free."""
        if not self._active():
            # the scheduler duty left this member (shard-0 handoff): its
            # gauge must not keep exporting the last owned tick's depth
            # next to the new owner's live value — the one-exporter
            # discipline the tpujob_job_* families follow on handoff.
            # Every per-decision ledger drops too: another member owns the
            # protocol now, and replaying OUR stale pending/sent entries
            # after regaining the duty would evict healthy re-admitted
            # gangs (a phantom in-flight preemption) or reserve phantom
            # hosts (a pending admission the interim owner released).  The
            # durable annotations are the truth the regained duty rebuilds
            # from.
            metrics.sched_queue_depth.set(0)
            metrics.sched_fragmentation.set(0)
            self._zero_node_gauges()
            with self._lock:
                self._queue_view = []
                self._pending_admissions.clear()
                self._release_sent.clear()
                self._preempt_sent.clear()
                self._flex_sent.clear()
                self._queued_anchor.clear()
                self._preempt_anchor.clear()
                self._health_sent.clear()
                # the decision rings drop with the duty: another member
                # narrates the protocol now, and a reader merging both
                # members' rings would see one job twice.  The next
                # acquisition rebuilds them (fresh epoch) from durable
                # annotations + re-observed ticks.
                self._rings.clear()
                self._ring_seq.clear()
                self._verdicts.clear()
                self._admitted_view.clear()
                self._duty_active = False
            return {"active": False}
        with self._lock:
            if not self._duty_active:
                # duty (re)acquired: a fresh epoch marks the ring rebuild
                # boundary for merged readers (seq restarts at 1)
                self._duty_active = True
                self._ring_epoch += 1
        t0 = time.monotonic()
        now = t0 if now is None else now
        shard = (SCHEDULER_SHARD if self.controller.sharder is not None
                 else None)
        with self.controller._shard_call_context(shard):
            report = self._tick_inner(now)
        dur = time.monotonic() - t0
        with self._lock:
            self._tick_durations.append(dur)
        return report

    def _tick_inner(self, now: float) -> Dict[str, Any]:
        now_wall = time.time()
        pools, unavailable = self._refresh_inventory(now)
        cap = CapacityModel(pools, unavailable)
        admitted: List[_Admitted] = []
        queued: List[Tuple[GangRequest, str, str, str, float]] = []
        ns_chips: Dict[str, float] = {}
        seen: set = set()
        live_req_keys: set = set()
        conflicts: List[str] = []
        unschedulable: Dict[str, List[str]] = {}

        for obj in self.controller.job_informer.store.list():
            meta = obj.get("metadata") or {}
            name = meta.get("name")
            if not name:
                continue
            ns = meta.get("namespace") or "default"
            key = f"{ns}/{name}"
            seen.add(key)
            ann = meta.get("annotations") or {}
            raw = ann.get(c.ANNOTATION_SCHED_ASSIGNMENT)
            if self._finished(obj):
                if raw is not None:
                    # a finished gang holds no chips: release its capacity
                    # (once per assignment value — the echo retires it)
                    self._release(key, ns, name, raw, "release (job finished)")
                continue
            req, ck = self._request_for(obj)
            live_req_keys.add(ck)
            if raw is not None:
                # our own committed write (a trim/grow rewrites the
                # assignment in place) may still be ahead of the cache:
                # until the echo lands, the value we committed IS the
                # placement — reserving the stale cached value would
                # double-book the freed/claimed hosts
                with self._lock:
                    pend = self._pending_admissions.get(key)
                    if pend is not None and pend.to_json() == raw:
                        self._pending_admissions.pop(key, None)  # echoed
                        pend = None
                asg = pend if pend is not None else Assignment.from_json(raw)
                if asg is None:
                    log.warning("%s: corrupt sched-assignment %r; dropping "
                                "(the gate re-queues the job)", key, raw)
                    self._patch(ns, name,
                                {c.ANNOTATION_SCHED_ASSIGNMENT: None},
                                "drop corrupt assignment")
                    continue
                conflicts.extend(cap.reserve(key, asg))
                preempting = ann.get(c.ANNOTATION_PREEMPT_TARGET) is not None
                with self._lock:
                    if preempting:
                        # the publish echoed: the dedup entry retires
                        self._preempt_sent.retire(key)
                    elif key in self._preempt_sent:
                        # our committed publish, not yet echoed: the victim
                        # IS in flight (the planner must not re-pick it,
                        # and the publish must not re-issue and wipe a
                        # just-written ack)
                        preempting = True
                entry = _Admitted(
                    key=key, namespace=ns, name=name,
                    tier=req.tier if req is not None else 0,
                    assignment=asg,
                    evicting=ann.get(c.ANNOTATION_SCHED_EVICTED) is not None,
                    preempting=preempting,
                    req=req,
                    flex=self._effective_flex(key, ann, req),
                    ann=ann)
                admitted.append(entry)
                if not entry.evicting:
                    ns_chips[entry.namespace] = (
                        ns_chips.get(entry.namespace, 0.0) + asg.chips)
                if (req is not None and not entry.evicting
                        and not entry.preempting
                        and self._outgrew(flex_request(req, entry.flex),
                                          asg)):
                    # an admitted gang's spec GREW past its committed
                    # placement (an elastic resize of an unpinned gang —
                    # UPDATE admission allows it, and the PR-9 pre-pass
                    # would happily create the extra pods): the assignment
                    # no longer covers the gang, which would silently
                    # overcommit the modeled fleet.  Re-place it through
                    # the normal checkpoint-barrier eviction; the re-queued
                    # job re-admits at its new shape when capacity allows.
                    # (A FLEXED gang is judged at its flexed shape — the
                    # trimmed assignment is the intended placement, not an
                    # outgrown one.)
                    if self._patch(ns, name, barrier.preempt_target_patch(),
                                   "re-place (gang grew past its "
                                   "assignment)"):
                        with self._lock:
                            self._preempt_sent.record(key)
                        entry.preempting = True
                        self._note("re-place", key,
                                   "spec grew past the committed "
                                   "assignment; re-queueing at the new "
                                   "shape")
                        self.controller.enqueue_job(key)
                if not entry.evicting and not entry.preempting:
                    self._maybe_migrate(entry, asg, cap, now)
                if not entry.evicting and not entry.preempting:
                    self._advance_flex(entry, cap)
                self._advance_eviction(entry, now, now_wall)
                continue
            # -- unadmitted: queue or reject ---------------------------------
            with self._lock:
                # the cache shows the annotations gone: any release we
                # sent has echoed — retire the dedup entries
                self._release_sent.retire(key)
                self._preempt_sent.retire(key)
                self._flex_sent.retire(key)
                pend = self._pending_admissions.get(key)
            if pend is not None:
                # our own committed admission, not yet echoed by the cache:
                # its hosts are NOT free, and the job is NOT queued
                conflicts.extend(cap.reserve(key, pend))
                continue
            if req is None:
                continue  # unresolvable/malformed: the sync fails it
            errs = self._never_placeable(req)
            if errs:
                unschedulable[key] = (
                    int(meta.get("generation") or 0), errs)
                continue
            queued.append((req, key, ns, name,
                           self._queued_since(key, obj, now, now_wall)))

        # surface fresh never-placeable verdicts (the reconciler gate writes
        # the durable condition) and enqueue their syncs
        with self._lock:
            new_unsched = [k for k, v in unschedulable.items()
                           if self._unschedulable.get(k) != v]
            self._unschedulable = unschedulable
            # prune per-incarnation anchors of jobs that left the cluster
            for d in (self._queued_anchor, self._preempt_anchor,
                      self._pending_admissions):
                for k in [k for k in d if k not in seen]:
                    d.pop(k, None)
            for ledger in (self._release_sent, self._preempt_sent,
                           self._flex_sent):
                ledger.prune(seen)
            for k in [k for k in self._req_cache if k not in live_req_keys]:
                self._req_cache.pop(k, None)
        for k in new_unsched:
            self._note("unschedulable", k, "; ".join(unschedulable[k][1]))
            self.controller.enqueue_job(k)

        # queue order: effective tier desc, fair share asc, FIFO
        entries = []
        for req, key, ns, name, since in queued:
            eff = effective_tier(req.tier, now - since, self.aging_s)
            share = namespace_share(ns_chips.get(req.namespace, 0.0),
                                    self.fleet_chips)
            entries.append((queue_sort_key(req, eff, share, since),
                            req, key, ns, name, since, eff))
        entries.sort(key=lambda e: e[0])
        metrics.sched_queue_depth.set(len(entries))

        view = []
        admits = 0
        preempts = 0
        for pos, (_, req, key, ns, name, since, eff) in enumerate(entries):
            view.append({
                "job": key, "position": pos, "tier": req.tier,
                "effective_tier": eff,
                "wait_s": round(max(0.0, now - since), 3),
                "request": (f"{req.num_slices}x{req.hosts_per_slice} hosts"
                            + (f" ({req.accelerator})"
                               if req.accelerator else "")),
            })
        with self._lock:
            self._queue_view = view

        blocked = False
        unplaced = False
        flexed = 0
        head_key: Optional[str] = None  # who blocked the scan (explainability)
        examined: set = set()
        for pos, (_, req, key, ns, name, since, eff) in enumerate(entries):
            if blocked:
                break
            examined.add(key)
            asg = cap.place(req, key)
            if asg is not None:
                if self._patch(ns, name, {
                        c.ANNOTATION_SCHED_ASSIGNMENT: asg.to_json()},
                        f"admit ({asg.to_json()})"):
                    admits += 1
                    wait = max(0.0, now - since)
                    metrics.sched_admissions.inc()
                    metrics.sched_admission_wait.observe(wait)
                    with self._lock:
                        self.admissions += 1
                        self._queued_anchor.pop(key, None)
                        self._pending_admissions[key] = asg
                    self._note("admit", key,
                               f"wait {wait:.3f}s tier {req.tier}/{eff}")
                    self.controller.enqueue_job(key)
                else:
                    # the admission write did not commit: the capacity the
                    # model just booked is NOT durably held — stop the scan
                    # so no later gang is placed around a phantom booking
                    blocked = True
                    head_key = key
                continue
            # no room for this gang: the capacity planner prices every
            # legal move against strictly-lower-tier gangs — flex shrinks
            # (restore cost only) before migrations before preemptions
            # (full projected goodput loss) — and returns the cheapest set
            # that frees enough contiguous capacity
            moves, plan_why = self._plan_capacity(req, eff, admitted, cap)
            self._record_verdict(key, self._queued_verdict(
                req, eff, pos, max(0.0, now - since), admitted, cap,
                moves, plan_why))
            if moves:
                for kind, victim, target, cost in moves:
                    if kind == "flex":
                        if self._flex_to(victim, target, cost,
                                         f"for {key} (tier "
                                         f"{req.tier}/{eff})"):
                            flexed += 1
                        continue
                    # the publish CONSUMES any stale ack in the same
                    # patch (the PR-9 resize drain's consume-at-publish
                    # rule): an ack left behind by a previous episode —
                    # e.g. one that raced the release — must never let
                    # THIS episode's barrier pass before the workload
                    # checkpoints
                    if self._patch(victim.namespace, victim.name,
                                   barrier.preempt_target_patch(),
                                   f"preempt (for {key})"):
                        preempts += 1
                        metrics.sched_preemptions.inc()
                        with self._lock:
                            self.preemptions += 1
                            self._preempt_sent.record(victim.key)
                            victim.preempting = True
                        self._note(
                            "preempt", victim.key,
                            f"tier {victim.tier} victim for {key} "
                            f"(tier {req.tier}/{eff})")
                        self._note_move(victim.key, "preempt", cost)
                        self.controller.enqueue_job(victim.key)
                # head-of-line while its capacity frees: no backfill
                # may steal the hosts the moves are vacating
                blocked = True
                head_key = key
                continue
            unplaced = True
            if eff >= TIER_MAX:
                # aged to the cap and still unplaceable without victims:
                # hold the line — backfilling past it is exactly how a big
                # gang starves behind an endless stream of small ones
                blocked = True
                head_key = key

        # the entries the blocked scan never reached: their verdict is pure
        # queue position — nothing about THEIR shape was judged this tick
        for pos, (_, req, key, ns, name, since, eff) in enumerate(entries):
            if key in examined:
                continue
            self._record_verdict(key, {
                "reason": "queue-position",
                "detail": (f"queue position {pos} behind {head_key} "
                           "(head-of-line holds the scan while its "
                           "capacity frees)"),
                "behind": head_key,
                "position": pos, "tier": req.tier, "effective_tier": eff,
                "aging_credit": eff - req.tier,
                "wait_s": round(max(0.0, now - since), 3),
                "blockers": [head_key] if head_key else [],
            })

        # admitted/queued state views + verdict GC for jobs that left the
        # queue (admitted, finished, deleted) — a stale why-not-running
        # answer is worse than none
        queued_keys = {key for _, _, key, _, _, _, _ in entries}
        with self._lock:
            self._admitted_view = {
                a.key: {
                    "tier": a.tier,
                    "accelerator": a.assignment.accelerator,
                    "slices": len(a.assignment.slices),
                    "chips": a.assignment.chips,
                    "evicting": a.evicting,
                    "preempting": a.preempting,
                    "flex": a.flex,
                } for a in admitted}
            for k in [k for k in self._verdicts if k not in queued_keys]:
                self._verdicts.pop(k, None)
            for d in (self._rings, self._ring_seq):
                for k in [k for k in d if k not in seen]:
                    d.pop(k, None)

        metrics.sched_fragmentation.set(fragmentation_ratio(cap))
        if not blocked and not unplaced:
            # nothing queued is waiting on capacity: idle headroom goes
            # first to restoring shrunk gangs, then to compaction (one
            # mutation class per tick — both are whole-gang moves)
            if not self._grow_flexed(admitted, cap, ns_chips):
                self._maybe_defrag(admitted, cap, now)

        return {"active": True, "queued": len(entries), "admitted": admits,
                "preempted": preempts, "flexed": flexed,
                "conflicts": conflicts}

    @staticmethod
    def _outgrew(req: GangRequest, asg: Assignment) -> bool:
        """Whether the gang's CURRENT request no longer fits inside its
        committed assignment (a grow; a shrink keeps the over-reservation,
        the safe direction — capacity is never overcommitted by holding
        too much)."""
        if req.num_slices > len(asg.slices):
            return True
        return any(s.host_hi - s.host_lo < req.hosts_per_slice
                   for s in asg.slices)

    @staticmethod
    def _finished(obj: Dict[str, Any]) -> bool:
        for cond in ((obj.get("status") or {}).get("conditions")) or []:
            if cond.get("status") == "True" and cond.get("type") in (
                    c.JOB_SUCCEEDED, c.JOB_FAILED):
                return True
        return False

    def _request_for(self, obj: Dict[str, Any]
                     ) -> Tuple[Optional[GangRequest], Tuple]:
        """The job's gang request, cached by (uid, generation): a pure
        function of the spec, which changes exactly when generation bumps —
        so the heavyweight dataclass parse runs once per spec revision, not
        once per job per tick.  None = unresolvable (the reconciler's
        strict validation fails the job)."""
        meta = obj.get("metadata") or {}
        ck = (meta.get("uid") or meta.get("name"),
              int(meta.get("generation") or 0))
        with self._lock:
            hit = self._req_cache.get(ck)
        if hit is not None:
            return hit[0], ck
        try:
            job = TPUJob.from_dict(obj)
            set_defaults_tpujob(job)
            out = (gang_request(job), None)
        except TopologyError as e:
            out = (None, str(e))
        except (TypeError, ValueError):
            out = (None, "malformed")
        with self._lock:
            self._req_cache[ck] = out
        return out[0], ck

    # -- preemption ----------------------------------------------------------

    def _progress_from_pods(self, key: str
                            ) -> Optional[Tuple[float, Optional[float]]]:
        """THE heartbeat-annotation fallback parser — the single place the
        scheduler ever hand-reads ``tpujob.dev/progress``: in a sharded
        fleet the shard-0 owner's ProgressTracker only holds its OWN
        shards' rows, but every member watches every pod, so the shared
        pod informer cache answers for the rest.  Returns the newest
        (step, checkpoint_step); None = no telemetry."""
        from tpujob.api.progress import parse_progress

        ns, _, name = key.partition("/")
        best = None  # ranked like the reconciler: (resize gen, step)
        for obj in self.controller.pod_informer.store.by_index(
                INDEX_JOB_NAME, name):
            meta = obj.get("metadata") or {}
            if (meta.get("namespace") or "default") != ns:
                continue
            raw = (meta.get("annotations") or {}).get(c.ANNOTATION_PROGRESS)
            if not raw:
                continue
            prog = parse_progress(raw)
            if prog is None:
                continue
            rank = (prog.resize_generation, prog.step)
            if best is None or rank > best[0]:
                best = (rank, prog)
        if best is None:
            return None
        prog = best[1]
        return (float(prog.step),
                None if prog.checkpoint_step is None
                else float(prog.checkpoint_step))

    def goodput_view(self, key: str) -> Optional[GoodputView]:
        """The job's goodput cost view: step/checkpoint telemetry from the
        shared pod informer cache (the ONE heartbeat-annotation parser) +
        the controller's phase ledger.  A ledger-backed view prices a
        preemption as PROJECTED GOODPUT LOST — redo the at-risk steps at
        the job's own observed step rate, plus its observed restore and
        requeue costs; a ledger-less job keeps the legacy heartbeat view
        (raw steps-past-checkpoint ordering).  None = no ledger AND no
        telemetry at all.

        Every job is priced through the SAME telemetry source: in a
        sharded fleet the shard-0 owner's ProgressTracker only holds its
        OWN shards' rows, so reading the tracker first would price local
        jobs from one parser and remote jobs from another — the PR-13
        asymmetry.  Every member watches every pod, so the pod cache
        answers uniformly for all of them."""
        prog = self._progress_from_pods(key)
        step, ckpt = (None, None) if prog is None else prog
        ledger = getattr(self.controller, "goodput", None)
        if ledger is not None:
            view = ledger.view(key, step=step, checkpoint_step=ckpt)
            if view is not None:
                return view
        if step is None:
            return None
        return heartbeat_view(step, ckpt)

    def _victim_cost(self, key: str) -> float:
        """Goodput cost of preempting ``key``: the view's projected loss
        in seconds (unknown telemetry = infinite, so victims that publish
        progress — and are provably cheap to evict — go first)."""
        view = self.goodput_view(key)
        if view is None:
            return float("inf")
        return view.projected_loss_s

    def _plan_capacity(self, req: GangRequest, eff_tier: int,
                       admitted: List[_Admitted], cap: CapacityModel,
                       allow_flex: Optional[bool] = None,
                       allow_preempt: Optional[bool] = None,
                       ) -> Tuple[List[Tuple[str, _Admitted, int, float]], str]:
        """Choose the cheapest move set that makes ``req`` placeable:
        strictly-lower-tier gangs only, every legal move priced by the
        goodput ledger and the cheapest (tier, cost) picked each round —
        a flex shrink (one slice off a multislice gang, restore cost
        only, never below its declared floor) before a preemption (full
        projected loss: redo + restore + requeue).  In-flight evictions,
        preemptions and flex drains count as already freeing — a tick
        must not pick NEW victims for capacity that is already being
        vacated.  Returns ``(plan, why)``: (kind, victim, flex_target,
        cost_s) tuples, one per victim (multiple shrinks of one gang
        coalesce into its final target — one publish, one drain); an
        empty plan carries why it is empty ('already-freeing' /
        'movers-disabled' / 'no-victims') for the explainability verdict.

        ``allow_flex``/``allow_preempt`` override the configured movers
        (None = configured): the explainer prices the HYPOTHETICAL ladder
        — what admitting this gang would cost if policy permitted — on a
        throwaway clone, without mutating anything."""
        allow_flex = self.enable_flex if allow_flex is None else allow_flex
        allow_preempt = (self.enable_preemption if allow_preempt is None
                         else allow_preempt)
        sim = cap.clone()
        for a in admitted:
            if a.evicting or a.preempting:
                sim.release(a.key)
            elif a.flex is not None and a.flex < len(a.assignment.slices):
                # an in-flight shrink: its freed slices are already being
                # vacated — model the gang at the flexed shape
                sim.release(a.key)
                sim.reserve(a.key, trimmed_assignment(a.assignment, a.flex))
        if sim.clone().place(req, "probe") is not None:
            # already freeing enough: wait, don't over-move
            return [], "already-freeing"
        if not allow_flex and not allow_preempt:
            return [], "movers-disabled"
        views: Dict[str, Optional[GoodputView]] = {}

        def view_of(key: str) -> Optional[GoodputView]:
            if key not in views:
                views[key] = self.goodput_view(key)
            return views[key]

        shrunk: Dict[str, int] = {}  # victim key -> planned slice count
        evicted: set = set()
        costs: Dict[str, float] = {}
        while True:
            best = None
            for a in admitted:
                if (a.evicting or a.preempting or a.key in evicted
                        or a.tier >= eff_tier):
                    continue
                cur = shrunk.get(a.key)
                if cur is None:
                    cur = (min(len(a.assignment.slices), a.flex)
                           if a.flex is not None
                           else len(a.assignment.slices))
                if (allow_flex and a.req is not None
                        and cur > self._flex_floor(a)):
                    # a shrink only costs the re-rendezvous restore: the
                    # drain runs the checkpoint barrier (no redo) and the
                    # gang keeps running (no requeue) — always finite, so
                    # flex beats preemption at equal tier by construction
                    v = view_of(a.key)
                    cost = 0.0 if v is None else v.flex_loss_s
                    cand = ((a.tier, cost, 0, a.key), "flex", a, cur, cost)
                    if best is None or cand[0] < best[0]:
                        best = cand
                if allow_preempt and a.key not in shrunk:
                    v = view_of(a.key)
                    cost = (float("inf") if v is None
                            else v.projected_loss_s)
                    cand = ((a.tier, cost, 1, a.key), "preempt", a, cur,
                            cost)
                    if best is None or cand[0] < best[0]:
                        best = cand
            if best is None:
                # every shrink bottomed out at its floor: escalate the
                # cheapest already-shrunk victim to a full preemption (the
                # shrink never happened — one move per victim) before
                # declaring the request infeasible
                esc = None
                if allow_preempt:
                    for a in admitted:
                        if (a.evicting or a.preempting or a.key in evicted
                                or a.tier >= eff_tier
                                or a.key not in shrunk):
                            continue
                        v = view_of(a.key)
                        cost = (float("inf") if v is None
                                else v.projected_loss_s)
                        cand = ((a.tier, cost, a.key), a, cost)
                        if esc is None or cand[0] < esc[0]:
                            esc = cand
                if esc is None:
                    return [], "no-victims"  # no workable move set exists
                _, victim, cost = esc
                shrunk.pop(victim.key)
                evicted.add(victim.key)
                costs[victim.key] = cost
                sim.release(victim.key)
                if sim.clone().place(req, "probe") is not None:
                    break
                continue
            _, kind, victim, cur, cost = best
            costs[victim.key] = cost
            if kind == "flex":
                shrunk[victim.key] = cur - 1
                sim.release(victim.key)
                sim.reserve(victim.key,
                            trimmed_assignment(victim.assignment, cur - 1))
            else:
                evicted.add(victim.key)
                sim.release(victim.key)
            if sim.clone().place(req, "probe") is not None:
                break
        plan: List[Tuple[str, _Admitted, int, float]] = []
        for a in admitted:
            if a.key in evicted:
                plan.append(("preempt", a, 0, costs[a.key]))
            elif a.key in shrunk:
                plan.append(("flex", a, shrunk[a.key], costs[a.key]))
        return plan, "planned"

    def _queued_verdict(self, req: GangRequest, eff: int, position: int,
                        wait_s: float, admitted: List[_Admitted],
                        cap: CapacityModel,
                        moves: List[Tuple[str, _Admitted, int, float]],
                        plan_why: str) -> Dict[str, Any]:
        """Why this queued gang is not running RIGHT NOW, with who blocks
        it and what the flex/migrate/preempt ladder would charge to run it
        anyway (the PR-13 projected-loss pricing).  Three reasons:

        - ``waiting-on-drain``: capacity is being vacated for it (moves
          planned this tick, or in-flight evictions/flex drains already
          free enough) — admission lands when the pods are gone;
        - ``fair-share-position``: policy protects the occupants (equal/
          higher tier, or the movers are disabled) — the hypothetical
          ladder below prices what admitting it WOULD cost;
        - ``infeasible-now``: no move set frees a contiguous placement at
          all (fragmentation or sheer shape) — only finishing jobs or new
          capacity unblock it.
        """
        base = {
            "position": position, "tier": req.tier, "effective_tier": eff,
            "aging_credit": eff - req.tier, "wait_s": round(wait_s, 3),
        }
        if moves:
            ladder = [{"kind": kind, "job": v.key, "tier": v.tier,
                       "flex_target": target if kind == "flex" else None,
                       "cost_s": round(cost, 3)}
                      for kind, v, target, cost in moves]
            return {**base, "reason": "waiting-on-drain",
                    "detail": ("capacity planner is vacating "
                               + ", ".join(f"{m['job']} ({m['kind']})"
                                           for m in ladder)),
                    "blockers": [m["job"] for m in ladder],
                    "ladder": ladder}
        if plan_why == "already-freeing":
            vacating = [a.key for a in admitted
                        if a.evicting or a.preempting
                        or (a.flex is not None
                            and a.flex < len(a.assignment.slices))]
            return {**base, "reason": "waiting-on-drain",
                    "detail": ("enough capacity is already vacating: "
                               + (", ".join(vacating) or "(in flight)")),
                    "blockers": vacating, "ladder": []}
        # nothing planned: price the HYPOTHETICAL ladder — every mover
        # enabled, every tier a candidate — on a throwaway clone.  A
        # non-empty answer means policy (tier protection, fair share,
        # disabled movers) is what stands between this gang and capacity;
        # an empty one means no move set would help at all.
        hyp, _ = self._plan_capacity(req, TIER_MAX + 1, admitted, cap,
                                     allow_flex=True, allow_preempt=True)
        if hyp:
            ladder = [{"kind": kind, "job": v.key, "tier": v.tier,
                       "flex_target": target if kind == "flex" else None,
                       "cost_s": round(cost, 3)}
                      for kind, v, target, cost in hyp]
            cause = ("movers disabled"
                     if not self.enable_flex and not self.enable_preemption
                     else "occupants are equal or higher tier")
            return {**base, "reason": "fair-share-position",
                    "detail": (f"blocked by "
                               + ", ".join(f"{m['job']} (tier {m['tier']})"
                                           for m in ladder)
                               + f" — {cause}; admitting it anyway would "
                               f"cost {sum(m['cost_s'] for m in ladder):.3f}s "
                               "projected goodput"),
                    "blockers": [m["job"] for m in ladder],
                    "ladder": ladder}
        return {**base, "reason": "infeasible-now",
                "detail": ("no move set frees a contiguous "
                           f"{req.num_slices}x{req.hosts_per_slice}-host "
                           "placement (fragmentation or shape); waiting "
                           "on finishing jobs or new capacity"),
                "blockers": [], "ladder": []}

    # -- elastic capacity: num_slices flex -----------------------------------

    def _effective_flex(self, key: str, ann: Dict[str, str],
                        req: Optional[GangRequest]) -> Optional[int]:
        """The gang's current flex target: the value WE committed while
        the write is still in flight, else the cached annotation.  None =
        full spec shape — including unparsable or out-of-range garbage
        (acting on corrupt input is how a gang gets silently shrunk)."""
        with self._lock:
            in_flight = self._flex_sent.value(key)
            if in_flight is not None and in_flight == (
                    ann.get(c.ANNOTATION_FLEX_SLICES) or ""):
                self._flex_sent.retire(key)  # echo landed
                in_flight = None
        raw = (in_flight if in_flight is not None
               else ann.get(c.ANNOTATION_FLEX_SLICES))
        if not raw:
            return None
        try:
            flex = int(raw)
        except ValueError:
            return None
        if flex < 1:
            return None
        if req is not None and flex >= req.num_slices:
            return None
        return flex

    def _flex_floor(self, entry: _Admitted) -> int:
        """The slice count below which this gang must be PREEMPTED rather
        than flexed: the min-slices annotation (per-job override) over
        ``schedulingPolicy.minSlices``, default 1, clamped to the spec
        shape.  A gang that cannot make progress under N slices declares
        it here and the planner never shrinks past it."""
        n = (entry.req.num_slices if entry.req is not None
             else len(entry.assignment.slices))
        floor = None
        raw = entry.ann.get(c.ANNOTATION_MIN_SLICES)
        if raw is not None:
            try:
                floor = int(raw)
            except ValueError:
                floor = None
        if floor is None and entry.req is not None:
            floor = entry.req.min_slices
        if floor is None:
            floor = 1
        return max(1, min(n, floor))

    def _flex_to(self, entry: _Admitted, target: int, cost: float,
                 why: str) -> bool:
        """Publish one flex shrink: the durable flex-slices target the
        reconciler's staging gate clamps the gang's Worker count to, which
        drives the ordinary staged-resize drain (checkpoint barrier, zero
        failure strikes).  The assignment is trimmed only after the
        smaller world publishes (:meth:`_advance_flex`) — capacity frees
        when the pods are actually gone, never before."""
        spec_n = (entry.req.num_slices if entry.req is not None
                  else len(entry.assignment.slices))
        value = str(target) if target < spec_n else None
        if not self._patch(entry.namespace, entry.name,
                           {c.ANNOTATION_FLEX_SLICES: value},
                           f"flex to {target} slice(s) ({why})"):
            return False
        metrics.sched_flex.labels(direction="shrink").inc()
        with self._lock:
            self.flexes += 1
            self._flex_sent.record(entry.key, value or "")
        entry.flex = target if value is not None else None
        self._note("flex", entry.key,
                   f"shrink to {target}/{spec_n} slice(s) ({why})")
        self._note_move(entry.key, "flex", cost)
        self.controller.enqueue_job(entry.key)
        return True

    def _advance_flex(self, entry: _Admitted, cap: CapacityModel) -> None:
        """Trim the durable assignment once the flex drain committed: the
        reconciler republished the world at the flexed size, which it only
        does after the drained pods are GONE — so the freed slices are
        safe to hand out, and not an instant earlier (a new gang must
        never land on hosts the draining pods still occupy)."""
        if entry.flex is None or entry.req is None:
            return
        asg = entry.assignment
        if len(asg.slices) <= entry.flex:
            return
        ann = entry.ann
        if ann.get(c.ANNOTATION_TARGET_WORLD_SIZE) is not None:
            return  # drain still staging toward the smaller world
        if ann.get(c.ANNOTATION_WORLD_SIZE) != str(
                entry.flex * entry.req.hosts_per_slice):
            return  # world not yet republished at the flexed shape
        trimmed = trimmed_assignment(asg, entry.flex)
        if not self._patch(entry.namespace, entry.name,
                           {c.ANNOTATION_SCHED_ASSIGNMENT:
                            trimmed.to_json()},
                           f"trim to {entry.flex} slice(s)"):
            return
        with self._lock:
            self._pending_admissions[entry.key] = trimmed
        entry.assignment = trimmed
        cap.release(entry.key)
        cap.reserve(entry.key, trimmed)  # the drained slices free NOW
        self._note("flex-trim", entry.key,
                   f"drain complete; assignment trimmed to {entry.flex} "
                   f"slice(s), {len(asg.slices) - entry.flex} freed")
        self.controller.enqueue_job(entry.key)

    def _grow_flexed(self, admitted: List[_Admitted], cap: CapacityModel,
                     ns_chips: Dict[str, float]) -> bool:
        """Flex ONE shrunk gang back toward its spec shape on an idle
        tick: queued jobs always outrank growth (callers only reach here
        when nothing queued is waiting on capacity), one slice per tick so
        a storm of restored capacity re-expands the fleet gradually, in
        fair-share order — highest tier first, then the namespace deepest
        under its share, then name.  True = a grow was committed."""
        if not self.enable_flex:
            return False
        cands = []
        for a in admitted:
            if a.evicting or a.preempting or a.req is None \
                    or a.flex is None:
                continue
            if len(a.assignment.slices) != a.flex:
                continue  # the shrink is still staging: grow later
            with self._lock:
                if self._flex_sent.value(a.key) is not None:
                    continue  # a flex write is already in flight
            share = namespace_share(ns_chips.get(a.namespace, 0.0),
                                    self.fleet_chips)
            cands.append(((-a.tier, share, a.key), a))
        cands.sort(key=lambda x: x[0])
        for _, a in cands:
            grown = self._grow_one(a, cap)
            if grown is None:
                continue  # no free run on an unused slice: try another
            target = len(grown.slices)
            value = (str(target) if target < a.req.num_slices else None)
            # ONE merge-patch carries the widened assignment AND the new
            # flex target: there is no committed instant at which they
            # disagree (no partial placement, the soak invariant)
            if not self._patch(a.namespace, a.name, {
                    c.ANNOTATION_SCHED_ASSIGNMENT: grown.to_json(),
                    c.ANNOTATION_FLEX_SLICES: value},
                    f"grow to {target} slice(s)"):
                return False
            metrics.sched_flex.labels(direction="grow").inc()
            with self._lock:
                self.flexes += 1
                self._pending_admissions[a.key] = grown
                self._flex_sent.record(a.key, value or "")
            a.assignment = grown
            a.flex = target if value is not None else None
            cap.release(a.key)
            cap.reserve(a.key, grown)
            self._note("flex", a.key,
                       f"grow to {target}/{a.req.num_slices} slice(s) "
                       "(idle capacity)")
            self.controller.enqueue_job(a.key)
            return True
        return False

    def _grow_one(self, entry: _Admitted,
                  cap: CapacityModel) -> Optional[Assignment]:
        """The entry's assignment widened by one slice: the first slice of
        its own pool it does not already occupy with a torus-adjacent free
        run of its per-slice host count.  None = no room (the gang stays
        flexed; a later tick — or the defragmenter — may open a run)."""
        asg = entry.assignment
        if not asg.slices:
            return None
        pi = asg.slices[0].pool
        if pi >= len(cap.pools) \
                or cap.pools[pi].accelerator != asg.accelerator:
            return None  # the pool moved under the gang: don't guess
        used = {s.slice_index for s in asg.slices}
        hps = entry.req.hosts_per_slice
        for si in range(cap.pools[pi].count):
            if si in used:
                continue
            lo = cap._free_interval(pi, si, hps)
            if lo is None:
                continue
            new = SlicePlacement(pool=pi, slice_index=si,
                                 host_lo=lo, host_hi=lo + hps)
            per_slice = asg.chips // len(asg.slices)
            return Assignment(
                accelerator=asg.accelerator,
                slices=asg.slices + (new,),
                chips=per_slice * (len(asg.slices) + 1))
        return None

    # -- torus defragmentation -----------------------------------------------

    def _maybe_defrag(self, admitted: List[_Admitted], cap: CapacityModel,
                      now: float) -> None:
        """On an idle tick with a shredded free map, migrate ONE cheap
        telemetry-backed gang through the ordinary checkpoint-barrier
        eviction so the freed fragments merge into a contiguous run a
        larger gang can use — compaction without preempting anyone.  One
        move fleet-wide at a time, and only provably-cheap movers (finite
        projected migrate cost): compaction must never cost more than the
        placement it enables."""
        if not self.enable_defrag:
            return
        ratio = fragmentation_ratio(cap)
        if ratio <= self.defrag_threshold:
            return
        if any(a.evicting or a.preempting for a in admitted):
            return  # one in-flight vacate fleet-wide
        cands = []
        for a in admitted:
            if a.req is None:
                continue
            if a.flex is not None and len(a.assignment.slices) != a.flex:
                continue  # flex staging in flight
            view = self.goodput_view(a.key)
            cost = float("inf") if view is None else view.migrate_loss_s
            if cost == float("inf"):
                continue
            cands.append((cost, a))
        if not cands:
            return
        cands.sort(key=lambda x: (x[0], x[1].key))
        by_key = {a.key: (a, cost) for cost, a in cands}
        plan = plan_defrag(cap, [
            (a.key, a.assignment, flex_request(a.req, a.flex))
            for _, a in cands], max_moves=1)
        for mv in plan:
            entry, cost = by_key[mv.key]
            names = sorted({
                node_name(mv.src.accelerator, s.pool, s.slice_index, h)
                for s in mv.src.slices
                for h in range(s.host_lo, s.host_hi)})
            if not self._patch(entry.namespace, entry.name,
                               barrier.preempt_target_patch(
                                   {c.ANNOTATION_MIGRATED_FROM:
                                    "defrag:" + ",".join(names)}),
                               "defrag (compact fragmented capacity)"):
                continue
            metrics.sched_defrag_moves.inc()
            with self._lock:
                self.defrag_moves += 1
                self._preempt_sent.record(entry.key)
                if self.aging_s > 0:
                    # the compacted gang re-queues with the migration
                    # head-start: defrag must not cost it queue position
                    head_start = now - self.aging_s
                    cur = self._queued_anchor.get(entry.key)
                    self._queued_anchor[entry.key] = (
                        head_start if cur is None else min(cur, head_start))
            entry.preempting = True
            self._note("defrag", entry.key,
                       f"fragmentation {ratio:.2f} > "
                       f"{self.defrag_threshold:g}; compacting off "
                       f"{len(names)} host(s)")
            self._note_move(entry.key, "defrag", cost)
            self.controller.enqueue_job(entry.key)

    def _note_move(self, key: str, kind: str, cost_s: float) -> None:
        """Record the move and its priced cost in the goodput ledger's
        move trail — the observability record the soak invariants (and
        /debug/fleet) read to prove every flex/defrag/preempt decision
        was the cheapest one available."""
        ledger = getattr(self.controller, "goodput", None)
        if ledger is not None:
            ledger.note_move(key, kind, cost_s)

    def _advance_eviction(self, entry: _Admitted, now: float,
                          now_wall: float) -> None:
        """Drive one victim through the publish -> barrier -> evict ->
        release protocol (each stage is a committed annotation, so a fresh
        scheduler resumes exactly where the old one died)."""
        if entry.evicting:
            # capacity stays reserved until the LAST pod is confirmed gone
            # — only then may the hosts be re-admitted to someone else.
            # Pods lingering on a CONFIRMED-DEAD host don't block the
            # release: their node will never ack the deletion, and the
            # dead host's capacity is unplaceable anyway (health-gated).
            if not self._live_pods(entry.namespace, entry.name,
                                   ignore_dead_nodes=True):
                raw = entry.ann.get(c.ANNOTATION_SCHED_ASSIGNMENT) or ""
                if self._release(entry.key, entry.namespace, entry.name,
                                 raw, "release (eviction complete)"):
                    self._note("release", entry.key, "eviction complete")
                    self.controller.enqueue_job(entry.key)
            return
        if not entry.preempting:
            return
        if self._barrier_passed(entry.key, entry.ann, now, now_wall):
            if self._patch(entry.namespace, entry.name,
                           {c.ANNOTATION_SCHED_EVICTED: st.now_iso()},
                           "evict (barrier passed)"):
                self._note("evict", entry.key, "checkpoint barrier passed")
                with self._lock:
                    self._preempt_anchor.pop(entry.key, None)
                self.controller.enqueue_job(entry.key)

    def _barrier_passed(self, key: str, ann: Dict[str, str],
                        now: float, now_wall: float) -> bool:
        """The preemption checkpoint barrier: the workload acked, or its
        telemetry shows the checkpoint caught up to the step (nothing left
        to lose), or the bounded grace ran out.  Bounded like the resize
        drain barrier — a wedged workload cannot block a preemption
        forever, and the invariant is 'nothing lost past the LAST
        checkpoint', which holds either way."""
        if self.preempt_grace_s <= 0:
            return True
        published_raw = ann.get(c.ANNOTATION_PREEMPT_TARGET)
        if published_raw is None:
            # our publish has not echoed into the cache yet (the entry is
            # preempting via the _preempt_sent ledger): the workload cannot
            # possibly have seen the target, so the barrier FAILS CLOSED —
            # failing open here would evict before the grace window ever
            # started.  The grace clock starts at the echo.
            return False
        acked = ann.get(c.ANNOTATION_PREEMPT_ACK) is not None
        if not acked:
            view = self.goodput_view(key)
            # checkpoint caught up to the step: nothing to lose, an
            # implicit ack (this scheduler-specific edge stays here; the
            # shared judge only sees its verdict)
            acked = (view is not None and view.step is not None
                     and view.checkpoint_step is not None
                     and view.checkpoint_step >= view.step)
        with self._lock:
            return barrier.barrier_passed(
                self._preempt_anchor, key, self.preempt_grace_s,
                acked=acked, published_wall=_parse_wall(published_raw),
                now_mono=now, now_wall=now_wall)

    # -- plumbing ------------------------------------------------------------

    def _live_pods(self, namespace: str, name: str,
                   ignore_dead_nodes: bool = False) -> int:
        """Pods (terminating included) the job still holds, from the shared
        informer cache — the release gate for a vacated gang's capacity.
        ``ignore_dead_nodes`` skips pods bound to confirmed-dead hosts
        (the node is the only thing that could confirm them gone)."""
        selector = gen_labels(name)
        count = 0
        for obj in self.controller.pod_informer.store.by_index(
                INDEX_JOB_NAME, selector[c.LABEL_JOB_NAME]):
            meta = obj.get("metadata") or {}
            if (meta.get("namespace") or "default") != namespace:
                continue
            if ignore_dead_nodes and self.node_dead(
                    (obj.get("spec") or {}).get("nodeName")):
                continue
            count += 1
        return count

    def _queued_since(self, key: str, obj: Dict[str, Any], now: float,
                      now_wall: float) -> float:
        """Monotonic queue anchor: earliest of the in-memory first-seen and
        the durable Queued condition's transition time — so aging survives
        a scheduler crash/handoff instead of resetting to zero."""
        wall = None
        for cond in ((obj.get("status") or {}).get("conditions")) or []:
            if cond.get("type") == c.JOB_QUEUED \
                    and cond.get("status") == "True":
                wall = _parse_wall(cond.get("lastTransitionTime"))
                break
        derived = (now if wall is None
                   else now - max(0.0, now_wall - wall))  # noqa: TPL004 - wall-vs-persisted timestamp math
        with self._lock:
            current = self._queued_anchor.get(key)
            best = derived if current is None else min(current, derived)
            self._queued_anchor[key] = best
            return best

    def _release(self, key: str, namespace: str, name: str, raw: str,
                 what: str) -> bool:
        """Release a gang's capacity annotations exactly once per
        assignment value: the committed patch is idempotent, but re-issuing
        it every tick until the cache echo lands is write amplification
        the API server pays for."""
        with self._lock:
            if self._release_sent.sent(key, raw):
                return False  # already committed; waiting for the echo
        if not self._patch(namespace, name, {
                c.ANNOTATION_SCHED_ASSIGNMENT: None,
                c.ANNOTATION_SCHED_EVICTED: None,
                c.ANNOTATION_PREEMPT_TARGET: None,
                c.ANNOTATION_PREEMPT_ACK: None,
                c.ANNOTATION_MIGRATED_FROM: None,
                # a released gang starts its next admission at the FULL
                # spec shape: the flex target dies with the placement
                c.ANNOTATION_FLEX_SLICES: None,
        }, what):
            return False
        with self._lock:
            self._release_sent.record(key, raw)
        return True

    def _patch(self, namespace: str, name: str,
               annotations: Dict[str, Optional[str]], what: str) -> bool:
        """One annotation merge-patch through the controller's (fenced,
        traced) transport; False = did not commit (retried next tick)."""
        try:
            self.controller.clients.server.patch(
                RESOURCE_TPUJOBS, namespace, name,
                {"metadata": {"annotations": dict(annotations)}})
            return True
        except NotFoundError:
            return False
        except ApiError as e:
            log.warning("%s/%s: scheduler %s failed (%s); retrying next "
                        "tick", namespace, name, what, e)
            return False

    # how many decision entries each job's ring retains: deep enough to
    # hold a whole admit -> flex -> preempt -> re-admit arc, shallow enough
    # that a 10k-job fleet's rings stay bounded
    RING_SIZE = 32

    def _ring_append_locked(self, key: str, kind: str, detail: str,
                     extra: Optional[Dict[str, Any]] = None) -> None:
        """Append one entry to the job's bounded decision ring (caller must
        hold self._lock).  seq is monotonic per job within one duty epoch;
        a ring created after a handoff (epoch > 1) opens with an explicit
        rebuild marker so gap detection never needs heuristics."""
        ring = self._rings.get(key)
        if ring is None:
            ring = self._rings[key] = collections.deque(maxlen=self.RING_SIZE)
            if self._ring_epoch > 1:
                seq = self._ring_seq.get(key, 0) + 1
                self._ring_seq[key] = seq
                ring.append({
                    "at": st.now_iso(), "seq": seq,
                    "epoch": self._ring_epoch, "kind": "ring-rebuilt",
                    "detail": ("decision ring rebuilt from durable "
                               "annotations after duty handoff "
                               f"(epoch {self._ring_epoch})")})
        seq = self._ring_seq.get(key, 0) + 1
        self._ring_seq[key] = seq
        entry: Dict[str, Any] = {
            "at": st.now_iso(), "seq": seq, "epoch": self._ring_epoch,
            "kind": kind, "detail": detail}
        if extra:
            entry.update(extra)
        ring.append(entry)

    def _note(self, kind: str, key: str, detail: str) -> None:
        with self._lock:
            self._decisions.append({
                "at": st.now_iso(), "kind": kind, "job": key,
                "detail": detail})
            if "/" in key:  # per-job keys only (node/… events have no ring)
                self._ring_append_locked(key, kind, detail)
        self.controller.flight.record(
            key, "sched", f"{kind}: {detail}", {"kind": kind})

    def _record_verdict(self, key: str, verdict: Dict[str, Any]) -> None:
        """Record one queued job's why-not-running verdict, appending to
        its decision ring only when the verdict CHANGED (reason/blockers) —
        a job waiting stably for minutes keeps its admission history
        instead of a ring full of identical 'still queued' rows."""
        with self._lock:
            prev = self._verdicts.get(key)
            changed = (prev is None
                       or prev.get("reason") != verdict.get("reason")
                       or prev.get("blockers") != verdict.get("blockers"))
            self._verdicts[key] = verdict
            if changed:
                self._ring_append_locked(
                    key, "queued", verdict.get("detail", ""),
                    {"verdict": verdict})

    # -- observability -------------------------------------------------------

    def tick_latencies(self) -> List[float]:
        with self._lock:
            return sorted(self._tick_durations)

    def explain(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        """The ``/debug/why/<ns>/<name>`` payload: one job's scheduling
        state, its latest why-not-running verdict (blockers + ladder
        price), and its bounded decision ring (seq + epoch for gap
        detection across handoffs).  None = this member has never seen
        the job (a merged reader falls through to the member that has)."""
        key = f"{namespace or 'default'}/{name}"
        with self._lock:
            ring = [dict(e) for e in self._rings.get(key, ())]
            verdict = self._verdicts.get(key)
            verdict = dict(verdict) if verdict is not None else None
            admitted = self._admitted_view.get(key)
            admitted = dict(admitted) if admitted is not None else None
            unsched = self._unschedulable.get(key)
            queue_row = next((dict(r) for r in self._queue_view
                              if r["job"] == key), None)
            epoch = self._ring_epoch
            seq = self._ring_seq.get(key, 0)
        if (not ring and verdict is None and admitted is None
                and unsched is None and queue_row is None):
            return None
        if admitted is not None:
            state = ("evicting" if admitted.get("evicting")
                     else "preempting" if admitted.get("preempting")
                     else "admitted")
        elif unsched is not None:
            state = "unschedulable"
        elif queue_row is not None or verdict is not None:
            state = "queued"
        else:
            state = "unknown"
        return {
            "job": key,
            "state": state,
            "queue": queue_row,
            "verdict": verdict,
            "admitted": admitted,
            "unschedulable": list(unsched[1]) if unsched is not None else None,
            "epoch": epoch,
            "last_seq": seq,
            "ring": ring,
        }

    def debug_snapshot(self) -> Dict[str, Any]:
        """The scheduler half of ``/debug/fleet``: capacity utilization,
        queue positions, and the recent decision log."""
        with self._lock:
            queue = list(self._queue_view)
            decisions = list(self._decisions)
            rings = {k: [dict(e) for e in ring]
                     for k, ring in self._rings.items()}
            verdicts = {k: dict(v) for k, v in self._verdicts.items()}
            epoch = self._ring_epoch
            unsched = {k: list(errs)
                       for k, (_, errs) in self._unschedulable.items()}
            admissions, preemptions = self.admissions, self.preemptions
            migrations = self.migrations
            flexes, defrag_moves = self.flexes, self.defrag_moves
            inventory_mode = self._inventory_mode
            inv = self._last_inventory
            nodes_block = None
            if inv is not None:
                nodes_block = {
                    "ready": len(inv.ready),
                    "not_ready": sorted(inv.not_ready),
                    "cordoned": sorted(inv.cordoned),
                    "unavailable_hosts": len(inv.unavailable),
                }
        return {
            "capacity": [{"accelerator": p.accelerator, "slices": p.count,
                          "hosts_per_slice": p.shape.hosts,
                          "chips": p.total_chips} for p in self.pools],
            # "modeled" = placing against the --sched-capacity bootstrap
            # pools (no Node objects yet); "nodes" = rebuilt from the live
            # Node informer cache each tick
            "inventory": inventory_mode,
            "nodes": nodes_block,
            "node_grace_s": self.node_grace_s,
            "aging_s": self.aging_s,
            "preemption": self.enable_preemption,
            "queue": queue,
            "unschedulable": unsched,
            "admissions_total": admissions,
            "preemptions_total": preemptions,
            "migrations_total": migrations,
            "flex_total": flexes,
            "defrag_moves_total": defrag_moves,
            "flex": self.enable_flex,
            "defrag": self.enable_defrag,
            # bounded (deque maxlen): the decision log can never grow past
            # its ring across a long node-churn soak
            "decisions": decisions,
            # per-job bounded decision rings with monotonic seq + duty
            # epoch: a merged reader detects a handoff gap (seq restarted,
            # epoch rose) instead of splicing two members' histories —
            # the /debug/why payloads, fleet-wide
            "epoch": epoch,
            "rings": rings,
            "verdicts": verdicts,
        }
