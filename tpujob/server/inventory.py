"""Node inventory: live-fleet capacity + heartbeat health for the scheduler.

The PR-11 gang scheduler placed against a *modeled* ``--sched-capacity``
string, so a dead host was invisible: the fleet kept assigning gangs onto
hardware that no longer existed.  This module makes Nodes the source of
truth:

- :class:`NodeHealth` judges each node's liveness on the CONTROLLER's
  monotonic clock (the PR-10 watchdog stance): a node whose heartbeat
  annotation has not changed for the bounded grace is *stale*; a node that
  has NEVER heartbeated is judged by its durable ``status.phase`` alone
  (synthesized/modeled hosts never die by silence).  Per-node state is
  LRU-bounded and swept when the Node object is deleted — the PR-3
  token-bucket discipline, so a long node-churn soak cannot grow it
  without bound.
- :class:`NodeHealth` also owns the per-node **migration damper**: a host
  may trigger at most one gang-migration episode per damping window (the
  window doubles per episode, capped), so a flapping node can never drive
  a migration storm.
- :func:`build_inventory` folds the Node informer cache into the
  ``(pools, unavailable-host set)`` pair the
  :class:`~tpujob.server.scheduler.CapacityModel` is rebuilt from each
  tick.  A host is unavailable when its node is cordoned
  (``tpujob.dev/unschedulable``), effectively NotReady, or simply absent
  from the inventory.
"""
from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from tpujob.api import constants as c
from tpujob.api.nodes import (
    NodeCoord,
    is_cordoned,
    node_coord,
    node_heartbeat,
    node_phase,
)
from tpujob.api.quota import SlicePoolSpec
from tpujob.api.topology import SliceTopology, TopologyError


@dataclass
class _NodeEntry:
    """Per-node monotonic ledger: heartbeat anchor + migration damper."""

    heartbeat: Optional[str] = None  # last observed lease value
    changed_at: float = 0.0  # monotonic instant the value last changed
    # migration damper: no new migration episode may be triggered by this
    # node before this monotonic instant; episodes escalate the window
    hold_until: float = 0.0
    episodes: int = 0
    extra: Dict[str, Any] = field(default_factory=dict)


class NodeHealth:
    """Monotonic heartbeat-staleness judge + per-node migration damper.

    NOT thread-safe by design: one instance rides one GangScheduler, whose
    tick is single-threaded; the reconciler-facing reads go through the
    scheduler's lock.
    """

    # LRU bound on per-node entries (the PR-3 token-bucket discipline):
    # churn through more node names than this evicts the oldest — an
    # evicted-then-reobserved node conservatively restarts its grace.
    MAX_ENTRIES = 4096

    def __init__(self, grace_s: float, damp_s: float = 0.0):
        self.grace_s = grace_s
        # damping window for the FIRST migration episode a node triggers;
        # <= 0 derives two grace periods
        self.damp_s = damp_s if damp_s > 0 else 2 * max(grace_s, 0.0)
        self._entries: "OrderedDict[str, _NodeEntry]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def _entry(self, name: str, now: float) -> _NodeEntry:
        entry = self._entries.get(name)
        if entry is None:
            entry = _NodeEntry(changed_at=now)
            self._entries[name] = entry
            while len(self._entries) > self.MAX_ENTRIES:
                self._entries.popitem(last=False)
        else:
            self._entries.move_to_end(name)
        return entry

    def observe(self, obj: Dict[str, Any], now: Optional[float] = None) -> bool:
        """Whether the node is effectively READY right now.

        Ready = not cordoned, and either (a) its heartbeat changed within
        the grace (liveness overrides a stale durable NotReady — the node
        came back), or (b) it has never heartbeated and its durable status
        says Ready, or (c) its heartbeat went quiet less than one grace ago
        and the durable status still says Ready.  The first observation of
        a node seeds its anchor at "now": a controller restart grants every
        node one fresh grace (conservative, the damper-rebuild stance),
        while the durable NotReady verdict of the previous incarnation
        keeps gating placement meanwhile.
        """
        now = time.monotonic() if now is None else now
        name = (obj.get("metadata") or {}).get("name") or ""
        # anchor the heartbeat BEFORE the cordon verdict: a cordoned node
        # keeps heartbeating, and freezing its anchor while cordoned would
        # let a cordon lasting longer than one grace masquerade as node
        # silence (a false durable NotReady + "heartbeat stale" taint on a
        # perfectly alive host, breaking instant uncordon reversibility)
        hb = node_heartbeat(obj)
        entry = self._entry(name, now)
        if hb != entry.heartbeat:
            entry.heartbeat = hb
            entry.changed_at = now
        if is_cordoned(obj):
            return False
        if hb is None:
            # never heartbeated: durable status is the only signal
            return node_phase(obj) != c.NODE_NOT_READY
        if now - entry.changed_at < self.grace_s or self.grace_s <= 0:
            return True  # fresh lease: alive even if status lags NotReady
        return False  # stale past the bounded grace

    def stale_for(self, obj: Dict[str, Any],
                  now: Optional[float] = None) -> Optional[float]:
        """Seconds the node's heartbeat has been stale past observation
        (None = it has never heartbeated, or is fresh)."""
        now = time.monotonic() if now is None else now
        name = (obj.get("metadata") or {}).get("name") or ""
        entry = self._entries.get(name)
        if entry is None or entry.heartbeat is None:
            return None
        age = now - entry.changed_at
        return age if age >= self.grace_s else None

    # -- migration damper ----------------------------------------------------

    def migration_allowed(self, name: str,
                          now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        entry = self._entries.get(name)
        return entry is None or now >= entry.hold_until

    def note_migration(self, name: str, now: Optional[float] = None) -> None:
        """One migration episode triggered by this node: open its damping
        window (doubling per episode, capped at 16x) so a flapping host
        cannot churn gangs in a storm."""
        now = time.monotonic() if now is None else now
        entry = self._entry(name, now)
        entry.episodes += 1
        window = self.damp_s * min(2 ** (entry.episodes - 1), 16)
        entry.hold_until = now + window

    def forget(self, name: str) -> bool:
        """Sweep the node's ledger when its Node object is deleted (the
        LRU-map hygiene the PR-3 token buckets follow)."""
        return self._entries.pop(name, None) is not None


@dataclass
class Inventory:
    """One tick's view of the fleet: pools indexed by ``spec.pool`` plus
    the host coordinates placement must not touch."""

    pools: List[SlicePoolSpec]
    unavailable: Set[NodeCoord]
    # node names by effective state, for metrics + /debug/fleet
    ready: List[str]
    not_ready: List[str]
    cordoned: List[str]
    # nodes whose heartbeat is stale past grace but whose durable status
    # has not flipped yet (the scheduler duty writes the flip)
    stale: Dict[str, float]
    # any node NOT carrying the synthesized label (a real inventory)
    has_real_nodes: bool = False


def build_inventory(nodes: List[Dict[str, Any]], health: NodeHealth,
                    now: Optional[float] = None) -> Inventory:
    """Fold the Node informer cache into (pools, unavailable hosts).

    Pool list positions are the nodes' declared ``spec.pool`` indices (the
    address space committed assignments already use), so the mapping stays
    stable across rebuilds; a pool index with no resolvable nodes yields a
    zero-slice placeholder.  Coordinates inside a pool's grid with no Node
    object at all are unavailable — the inventory only ever offers hosts
    that exist.
    """
    now = time.monotonic() if now is None else now
    # pool index -> (accelerator, {coord}, max slice index)
    seen: Dict[int, Tuple[str, Set[Tuple[int, int]], int]] = {}
    ready: List[str] = []
    not_ready: List[str] = []
    cordoned: List[str] = []
    stale: Dict[str, float] = {}
    excluded: Set[NodeCoord] = set()
    has_real = False
    for obj in nodes:
        meta = obj.get("metadata") or {}
        name = meta.get("name") or ""
        parsed = node_coord(obj)
        if parsed is None:
            continue  # malformed spec: invisible to placement
        accel, (pool, si, host) = parsed
        labels = meta.get("labels") or {}
        if labels.get(c.LABEL_NODE_SYNTHESIZED) != "true":
            has_real = True
        entry = seen.get(pool)
        if entry is None:
            seen[pool] = (accel, {(si, host)}, si)
        else:
            if entry[0] != accel:
                continue  # pool index claimed by two accelerators: first wins
            entry[1].add((si, host))
            seen[pool] = (entry[0], entry[1], max(entry[2], si))
        # exclusion honors the DURABLE verdict too: a node whose heartbeat
        # resumed but whose status still says NotReady stays excluded until
        # the scheduler duty flips it back Ready — placement and pod birth
        # follow the committed truth, not one member's local anchors
        alive = health.observe(obj, now)
        if is_cordoned(obj):
            cordoned.append(name)
        elif not alive or node_phase(obj) == c.NODE_NOT_READY:
            not_ready.append(name)
            age = health.stale_for(obj, now)
            if age is not None:
                stale[name] = age
        else:
            ready.append(name)
        if (is_cordoned(obj) or not alive
                or node_phase(obj) == c.NODE_NOT_READY):
            excluded.add((pool, si, host))
    pools: List[SlicePoolSpec] = []
    unavailable: Set[NodeCoord] = set(excluded)
    if seen:
        size = max(seen) + 1
        for pi in range(size):
            entry = seen.get(pi)
            if entry is None:
                pools.append(_empty_pool())
                continue
            accel, coords, max_slice = entry
            try:
                shape = SliceTopology.resolve(accel)
            except TopologyError:
                pools.append(_empty_pool())
                continue
            count = max_slice + 1
            pools.append(SlicePoolSpec(accelerator=accel, count=count,
                                       shape=shape))
            for si in range(count):
                for host in range(shape.hosts):
                    if (si, host) not in coords:
                        # no Node object for this coordinate: the host does
                        # not exist — placement must skip it
                        unavailable.add((pi, si, host))
    return Inventory(pools=pools, unavailable=unavailable, ready=ready,
                     not_ready=not_ready, cordoned=cordoned, stale=stale,
                     has_real_nodes=has_real)


def _empty_pool() -> SlicePoolSpec:
    """Placeholder for a pool index with no resolvable nodes: zero slices,
    so nothing places there, while committed assignments naming it still
    reserve (and report) against a defined index space."""
    return SlicePoolSpec(accelerator="v4-8", count=0,
                         shape=SliceTopology.resolve("v4-8"))
