"""Prometheus-style metrics registry.

The reference exposes 7 series via promauto (SURVEY.md §5): jobs
created/deleted/successful/failed/restarted totals, plus the is_leader
gauge, served at ``--monitoring-port`` (``main.go:31-40``).  This module is
the registry; ``tpujob.server.monitoring`` serves it in Prometheus text
exposition format.
"""
from __future__ import annotations

import threading
from typing import Dict


class Counter:
    def __init__(self, name: str, help_text: str, registry: "Registry"):
        self.name = name
        self.help = help_text
        self._value = 0.0
        self._lock = threading.Lock()
        registry._register(self)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def kind(self) -> str:
        return "counter"


class Gauge(Counter):
    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    def kind(self) -> str:
        return "gauge"


class Registry:
    def __init__(self):
        self._metrics: Dict[str, Counter] = {}
        self._lock = threading.Lock()

    def _register(self, m: Counter) -> None:
        with self._lock:
            self._metrics[m.name] = m

    def expose(self) -> str:
        """Prometheus text exposition format."""
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind()}")
            v = m.value
            lines.append(f"{m.name} {int(v) if v == int(v) else v}")
        return "\n".join(lines) + "\n"


# Global registry with the reference's 7 series (renamed for tpujob).
REGISTRY = Registry()
jobs_created = Counter(
    "tpujob_operator_jobs_created_total", "Counts number of TPU jobs created", REGISTRY
)
jobs_deleted = Counter(
    "tpujob_operator_jobs_deleted_total", "Counts number of TPU jobs deleted", REGISTRY
)
jobs_successful = Counter(
    "tpujob_operator_jobs_successful_total", "Counts number of TPU jobs successful", REGISTRY
)
jobs_failed = Counter(
    "tpujob_operator_jobs_failed_total", "Counts number of TPU jobs failed", REGISTRY
)
jobs_restarted = Counter(
    "tpujob_operator_jobs_restarted_total", "Counts number of TPU jobs restarted", REGISTRY
)
is_leader = Gauge(
    "tpujob_operator_is_leader", "Whether this operator instance is the leader", REGISTRY
)
