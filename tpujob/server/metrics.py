"""Prometheus-style metrics registry.

The reference exposes 7 series via promauto (SURVEY.md §5): jobs
created/deleted/successful/failed/restarted totals, plus the is_leader
gauge, served at ``--monitoring-port`` (``main.go:31-40``).  This module is
the registry; ``tpujob.server.monitoring`` serves it in Prometheus text
exposition format.
"""
from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Tuple


def _fmt(v: float) -> str:
    return str(int(v)) if v == int(v) else repr(v)


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash, quote, LF."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class Counter:
    def __init__(self, name: str, help_text: str,
                 registry: Optional["Registry"] = None, label_str: str = ""):
        self.name = name
        self.help = help_text
        self._label_str = label_str  # 'k="v",...' for labeled children
        self._value = 0.0
        self._lock = threading.Lock()
        if registry is not None:
            registry._register(self)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def kind(self) -> str:
        return "counter"

    def samples(self) -> List[Tuple[str, float]]:
        """(series name incl. labels, value) pairs for exposition."""
        if self._label_str:
            return [(f"{self.name}{{{self._label_str}}}", self.value)]
        return [(self.name, self.value)]


class Gauge(Counter):
    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    def kind(self) -> str:
        return "gauge"


# Latency-oriented default buckets (prometheus DefBuckets shifted one decade
# down: controller syncs against a local cache are sub-millisecond).
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


class Histogram:
    """Cumulative-bucket histogram (the promauto.NewHistogram equivalent)."""

    def __init__(self, name: str, help_text: str,
                 registry: Optional["Registry"] = None,
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                 label_str: str = ""):
        self.name = name
        self.help = help_text
        self._label_str = label_str  # 'k="v",...' for labeled children
        self._buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self._buckets) + 1)  # per-bucket + overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()
        if registry is not None:
            registry._register(self)

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self._buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    @property
    def value(self) -> float:
        """Observation count (the scalar a Counter-shaped caller expects)."""
        with self._lock:
            return float(self._count)

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (promql histogram_quantile).

        Values beyond the last finite bucket clamp to that bucket's bound.
        Returns 0.0 with no observations.
        """
        with self._lock:
            counts, total = list(self._counts), self._count
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0.0
        for i, n in enumerate(counts):
            prev_cum = cum
            cum += n
            if cum < rank:
                continue
            if i >= len(self._buckets):
                return self._buckets[-1]
            lo = self._buckets[i - 1] if i > 0 else 0.0
            hi = self._buckets[i]
            if n == 0:
                return hi
            return lo + (hi - lo) * (rank - prev_cum) / n
        return self._buckets[-1]

    def kind(self) -> str:
        return "histogram"

    def samples(self) -> List[Tuple[str, float]]:
        with self._lock:
            counts, total, s = list(self._counts), self._count, self._sum
        lbl = self._label_str
        bucket_prefix = f"{lbl}," if lbl else ""
        suffix = f"{{{lbl}}}" if lbl else ""
        out: List[Tuple[str, float]] = []
        cum = 0
        for ub, n in zip(self._buckets, counts):
            cum += n
            out.append((f'{self.name}_bucket{{{bucket_prefix}le="{_fmt(ub)}"}}', cum))
        out.append((f'{self.name}_bucket{{{bucket_prefix}le="+Inf"}}', total))
        out.append((f"{self.name}_sum{suffix}", s))
        out.append((f"{self.name}_count{suffix}", total))
        return out


class _LabeledFamily:
    """A family of per-label-value child metrics under one metric name
    (the promauto ``NewCounterVec``/``NewHistogramVec`` role).  Children are
    created on first use of a label combination and exposed together; label
    values are escaped per the Prometheus text format."""

    def __init__(self, name: str, help_text: str, registry: "Registry",
                 labelnames: Tuple[str, ...], kind: str):
        self.name = name
        self.help = help_text
        self._labelnames = tuple(labelnames)
        self._kind = kind
        self._children: Dict[Tuple[str, ...], Counter] = {}
        self._lock = threading.Lock()
        registry._register(self)

    def _make_child(self, label_str: str):  # pragma: no cover - overridden
        raise NotImplementedError

    def labels(self, **labelvalues):
        """Child metric for one label-value combination; unknown or missing
        label names raise (a typo'd label must not mint a new series)."""
        if set(labelvalues) != set(self._labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labelvalues)} != declared "
                f"{sorted(self._labelnames)}")
        key = tuple(str(labelvalues[n]) for n in self._labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                label_str = ",".join(
                    f'{n}="{_escape_label_value(v)}"'
                    for n, v in zip(self._labelnames, key))
                child = self._make_child(label_str)
                self._children[key] = child
        return child

    def remove(self, **labelvalues) -> bool:
        """Drop one label combination's child series (returns whether it
        existed).  Per-job families (``tpujob_job_*``) need this: a deleted
        job's gauges would otherwise export stale — and ever-growing —
        heartbeat/checkpoint ages forever."""
        if set(labelvalues) != set(self._labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labelvalues)} != declared "
                f"{sorted(self._labelnames)}")
        key = tuple(str(labelvalues[n]) for n in self._labelnames)
        with self._lock:
            return self._children.pop(key, None) is not None

    def remove_matching(self, predicate) -> int:
        """Bulk :meth:`remove`: drop every child whose label-value tuple
        satisfies ``predicate`` (returns how many).  For sweep paths that
        cannot enumerate the full label sets — e.g. clearing one job's
        children across families whose extra labels (``phase``) the
        sweeper does not know."""
        with self._lock:
            doomed = [k for k in self._children if predicate(k)]
            for k in doomed:
                self._children.pop(k, None)
        return len(doomed)

    def kind(self) -> str:
        return self._kind

    def samples(self) -> List[Tuple[str, float]]:
        with self._lock:
            children = [self._children[k] for k in sorted(self._children)]
        out: List[Tuple[str, float]] = []
        for child in children:
            out.extend(child.samples())
        return out


class LabeledCounter(_LabeledFamily):
    def __init__(self, name: str, help_text: str, registry: "Registry",
                 labelnames: Tuple[str, ...]):
        super().__init__(name, help_text, registry, labelnames, "counter")

    def _make_child(self, label_str: str) -> Counter:
        return Counter(self.name, self.help, label_str=label_str)


class LabeledGauge(_LabeledFamily):
    def __init__(self, name: str, help_text: str, registry: "Registry",
                 labelnames: Tuple[str, ...]):
        super().__init__(name, help_text, registry, labelnames, "gauge")

    def _make_child(self, label_str: str) -> Gauge:
        return Gauge(self.name, self.help, label_str=label_str)


class LabeledSettableCounter(_LabeledFamily):
    """Counter-TYPED family whose children are driven by absolute ``set``
    calls rather than ``inc``: the owning ledger accumulates the cumulative
    value itself, so incremental bookkeeping here would double count on
    every rebuild.  Exposed as ``# TYPE ... counter`` — the series is
    monotonic for any one exporter, and the ledger exports only its
    precisely-observed accumulation (never coarse re-seeded pre-history),
    so a restart/handoff reset drops toward zero exactly like a process
    restart — the reset shape Prometheus ``rate()`` handles."""

    def __init__(self, name: str, help_text: str, registry: "Registry",
                 labelnames: Tuple[str, ...]):
        super().__init__(name, help_text, registry, labelnames, "counter")

    def _make_child(self, label_str: str) -> Gauge:
        return Gauge(self.name, self.help, label_str=label_str)


class LabeledHistogram(_LabeledFamily):
    def __init__(self, name: str, help_text: str, registry: "Registry",
                 labelnames: Tuple[str, ...],
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self._buckets_cfg = buckets
        super().__init__(name, help_text, registry, labelnames, "histogram")

    def _make_child(self, label_str: str) -> Histogram:
        return Histogram(self.name, self.help, buckets=self._buckets_cfg,
                         label_str=label_str)


class Registry:
    def __init__(self):
        self._metrics: Dict[str, Counter] = {}
        self._lock = threading.Lock()

    def _register(self, m: Counter) -> None:
        with self._lock:
            self._metrics[m.name] = m

    def expose(self) -> str:
        """Prometheus text exposition format."""
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind()}")
            for series, v in m.samples():
                lines.append(f"{series} {_fmt(v)}")
        return "\n".join(lines) + "\n"


# Global registry with the reference's 7 series (renamed for tpujob).
REGISTRY = Registry()
jobs_created = Counter(
    "tpujob_operator_jobs_created_total", "Counts number of TPU jobs created", REGISTRY
)
jobs_deleted = Counter(
    "tpujob_operator_jobs_deleted_total", "Counts number of TPU jobs deleted", REGISTRY
)
jobs_successful = Counter(
    "tpujob_operator_jobs_successful_total", "Counts number of TPU jobs successful", REGISTRY
)
jobs_failed = Counter(
    "tpujob_operator_jobs_failed_total", "Counts number of TPU jobs failed", REGISTRY
)
jobs_restarted = Counter(
    "tpujob_operator_jobs_restarted_total", "Counts number of TPU jobs restarted", REGISTRY
)
is_leader = Gauge(
    "tpujob_operator_is_leader", "Whether this operator instance is the leader", REGISTRY
)

# Control-plane hot-path series (this port's addition; the reference exposes
# only the job-lifecycle totals above).  Recorded by JobController.
reconcile_duration = Histogram(
    "tpujob_operator_reconcile_duration_seconds",
    "Latency of one sync_handler call (workqueue item processing)",
    REGISTRY,
)
queue_depth = Gauge(
    "tpujob_operator_queue_depth",
    "Workqueue depth sampled at dequeue time",
    REGISTRY,
)
pods_created = Counter(
    "tpujob_operator_pods_created_total",
    "Counts pods created by the operator's pod control",
    REGISTRY,
)

# Fault-visibility series (this PR's chaos/robustness work): how often the
# transport hurt us and how often the informers had to heal themselves.
api_faults_injected = Counter(
    "tpujob_operator_api_faults_injected_total",
    "API faults injected by the chaos harness (0 outside chaos runs)",
    REGISTRY,
)
watch_reconnects = Counter(
    "tpujob_operator_watch_reconnects_total",
    "Watch streams re-established after a stream death",
    REGISTRY,
)
relists = Counter(
    "tpujob_operator_relists_total",
    "Full LIST+reconcile operations (initial informer sync and 410-Gone "
    "forced relists)",
    REGISTRY,
)

# Span-derived observability series (the flight-recorder PR): latency broken
# down by where one sync actually spent its time, recorded from the span
# tree each root sync span closes (tpujob/obs/trace.py).
queue_latency = Histogram(
    "tpujob_operator_queue_latency_seconds",
    "Time a work-queue item waited between becoming due and being dequeued",
    REGISTRY,
)
api_request_duration = LabeledHistogram(
    "tpujob_operator_api_request_duration_seconds",
    "Latency of one API call made during a sync, by verb/resource/"
    "status code",
    REGISTRY,
    ("verb", "resource", "code"),
)
sync_phase_duration = LabeledHistogram(
    "tpujob_operator_sync_phase_duration_seconds",
    "Latency of one reconcile phase (cache_get, claim, resize, pod_diff, "
    "service_diff, slow_start_create, telemetry, status_update)",
    REGISTRY,
    ("phase",),
)
events_dropped = Counter(
    "tpujob_operator_events_dropped_total",
    "Events whose best-effort API write failed and was swallowed "
    "(the local recorder tail still holds them)",
    REGISTRY,
)

# Crash-only / HA series (the crash-safety PR): leadership churn, cold-start
# recovery latency, and writes rejected by the fencing layer.
leader_transitions = Counter(
    "tpujob_operator_leader_transitions_total",
    "Leadership transitions observed by this instance (acquisitions plus "
    "losses)",
    REGISTRY,
)
cold_start_duration = LabeledHistogram(
    "tpujob_operator_cold_start_duration_seconds",
    "Cold-start recovery latency by stage: controller start -> informer "
    "caches synced (caches_synced) and -> first completed sync (first_sync)",
    REGISTRY,
    ("stage",),
    # cold starts are LIST-of-the-whole-cluster scale, not cache-hit scale:
    # on a big cluster they can exceed the 15 s lease_duration — the default
    # sub-10s latency buckets would collapse exactly the slow cold starts
    # this metric exists to expose into +Inf
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
             120.0, 300.0, 600.0),
)
fenced_writes_rejected = Counter(
    "tpujob_operator_fenced_writes_rejected_total",
    "Mutating API calls rejected by write fencing (leadership lost locally, "
    "or a stale fencing token caught server-side)",
    REGISTRY,
)

# Sharded-control-plane series (the shard PR): which shards this instance
# owns, how often ownership churned, and what a drain-before-release handoff
# costs.  Per-INSTANCE semantics: every member exports its own view, and a
# healthy fleet's shard_ownership sums to exactly 1 per shard across members.
shard_ownership = LabeledGauge(
    "tpujob_operator_shard_ownership",
    "Whether this instance currently owns the shard (1) or not (0); summed "
    "across the fleet each shard must total exactly 1",
    REGISTRY,
    ("shard",),
)
shard_rebalances = Counter(
    "tpujob_operator_shard_rebalances_total",
    "Shard ownership transitions observed by this instance (acquisitions "
    "plus releases and losses)",
    REGISTRY,
)
shard_handoff_duration = Histogram(
    "tpujob_operator_shard_handoff_duration_seconds",
    "Duration of one drain-before-release shard handoff: draining marked "
    "-> in-flight syncs finished -> shard lease released",
    REGISTRY,
)

# Elastic resize series (the staged drain/join resize of a live TPUJob):
# a spec.replicas change on the Worker type is a first-class state
# transition — scale-up joins new replicas and republishes the world size
# only after they are Running; scale-down runs a checkpoint barrier, drains
# the highest-index replicas, and never restarts a surviving pod.
resize_total = LabeledCounter(
    "tpujob_operator_resize_total",
    "Elastic resizes staged, by direction (up = join new replicas, "
    "down = drain the highest-index replicas); a superseded mid-flight "
    "resize counts again when restaged at the new target",
    REGISTRY,
    ("direction",),
)
resize_duration = Histogram(
    "tpujob_operator_resize_duration_seconds",
    "Wall time of one completed elastic resize: staging record created -> "
    "new world size published (drain barrier + pod churn included)",
    REGISTRY,
    buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300, 600),
)
resize_rollbacks = Counter(
    "tpujob_operator_resize_rollbacks_total",
    "In-flight resizes superseded by a spec change back to their origin "
    "(a flap: the staged target was abandoned and the job returned to the "
    "replica count it started from)",
    REGISTRY,
)

# API write-path series (the write-path overhaul): status persistence
# proportional to CHANGE, not to sync count.  A sync whose recomputed status
# is semantically identical to the informer-cached one skips the write
# (result="suppressed"); real writes ship a JSON-merge-patch of only the
# changed fields, and burst events per job coalesce into one sync.
status_writes = LabeledCounter(
    "tpujob_operator_status_writes_total",
    "Job status write decisions per sync: result=written (a status write "
    "was issued) or result=suppressed (the recomputed status matched the "
    "informer cache semantically and the write was skipped)",
    REGISTRY,
    ("result",),
)
syncs_coalesced = Counter(
    "tpujob_operator_syncs_coalesced_total",
    "Object events absorbed into an already-scheduled sync by the "
    "per-job-key settle window (each would have been its own sync without "
    "coalescing)",
    REGISTRY,
)
status_patch_bytes = Counter(
    "tpujob_operator_status_patch_bytes_total",
    "Serialized bytes of status merge patches actually shipped",
    REGISTRY,
)
status_full_bytes = Counter(
    "tpujob_operator_status_full_bytes_total",
    "Serialized bytes the same status writes would have shipped as "
    "full-object PUTs (the patch-vs-put payload baseline)",
    REGISTRY,
)

# API read-path series (the read-path overhaul): LIST/watch cost proportional
# to what changed, at six-figure object counts.  Informer cold starts and
# 410-forced relists page their LISTs (continue tokens pinned to a snapshot
# resourceVersion), quiet watches ride periodic BOOKMARK events so their
# resume points never fall behind compaction, and relists diff the listed
# pages against the cache instead of rebuilding the world.
list_pages_total = Counter(
    "tpujob_operator_list_pages_total",
    "LIST pages fetched by informers (paged initial syncs and relists; an "
    "unpaged LIST counts as one page)",
    REGISTRY,
)
watch_bookmarks = Counter(
    "tpujob_operator_watch_bookmarks_total",
    "Watch BOOKMARK events consumed by informers — each advances a stream's "
    "resume point without any data traffic",
    REGISTRY,
)
relist_objects_diffed = Counter(
    "tpujob_operator_relist_objects_diffed_total",
    "Objects fetched and diffed against the informer cache during LIST "
    "reconciliations (initial syncs and relists) — the read-side traffic "
    "a relist actually costs",
    REGISTRY,
)
history_compactions = Counter(
    "tpujob_operator_history_compactions_total",
    "Compaction pressure on the in-memory API server's bounded watch "
    "history: explicit compact() calls plus events evicted by the history "
    "bound — each advances the oldest servable resume/continue point",
    REGISTRY,
)

# Workload-telemetry series (the job telemetry plane): per-job training
# progress ingested from the workloads' tpujob.dev/progress pod-annotation
# heartbeats — zero extra API reads; everything arrives through the informer
# cache the reconciler already holds.  Label semantics: each controller
# instance exports ONLY the jobs whose shard it currently owns, with the
# owning shard as a label ('-' when unsharded), so N scraped controllers
# compose into one fleet view and the partition invariant stays checkable in
# promql (each (namespace, job) must appear on exactly one instance).  Series
# are removed when the job finishes, is deleted, or its shard is handed off.
_JOB_LABELS = ("namespace", "job", "shard")
job_steps = LabeledGauge(
    "tpujob_job_steps",
    "Latest global training step reported by the job's workload heartbeat "
    "(gauge: a crash restore may regress it to the last checkpoint)",
    REGISTRY,
    _JOB_LABELS,
)
job_samples_per_second = LabeledGauge(
    "tpujob_job_samples_per_second",
    "Smoothed training throughput reported by the job's workload heartbeat",
    REGISTRY,
    _JOB_LABELS,
)
job_checkpoint_age = LabeledGauge(
    "tpujob_job_checkpoint_age_seconds",
    "Seconds since the job's reported checkpoint step last advanced "
    "(controller monotonic clock; the workload's clock is never trusted)",
    REGISTRY,
    _JOB_LABELS,
)
job_heartbeat_age = LabeledGauge(
    "tpujob_job_heartbeat_age_seconds",
    "Seconds since the job's progress heartbeat last changed in the "
    "informer cache (controller monotonic clock)",
    REGISTRY,
    _JOB_LABELS,
)
job_stalled = LabeledGauge(
    "tpujob_job_stalled",
    "Whether the progress watchdog currently holds the job's Stalled "
    "condition True (1) or not (0)",
    REGISTRY,
    _JOB_LABELS,
)
# Gang-scheduler series (the native admission queue): how deep the queue
# is, how much admission throughput the fleet sustains, how long gangs wait
# for their all-or-nothing placement, and how often preemption fired.  Only
# the instance holding the scheduler duty (shard 0's owner in a sharded
# fleet) moves these.
sched_queue_depth = Gauge(
    "tpujob_scheduler_queue_depth",
    "Feasible gangs currently waiting in the admission queue (sampled once "
    "per scheduler tick; infeasible jobs are rejected, not queued)",
    REGISTRY,
)
sched_admissions = Counter(
    "tpujob_scheduler_admissions_total",
    "Gangs admitted all-or-nothing against the modeled slice capacity "
    "(each is one committed sched-assignment annotation)",
    REGISTRY,
)
sched_preemptions = Counter(
    "tpujob_scheduler_preemptions_total",
    "Preemptions staged by the scheduler (each publishes a preempt-target "
    "and runs the bounded checkpoint barrier before eviction)",
    REGISTRY,
)
sched_admission_wait = Histogram(
    "tpujob_scheduler_admission_wait_seconds",
    "Time a gang waited in the admission queue before its all-or-nothing "
    "placement committed",
    REGISTRY,
    # admission waits are queue-scale, not cache-hit scale: an oversubscribed
    # fleet holds gangs for minutes-to-hours behind aging + preemption
    buckets=(0.05, 0.25, 1.0, 5.0, 15.0, 60.0, 300.0, 900.0, 3600.0,
             14400.0),
)

# Node inventory & fleet repair series.  Naming note (see docs/monitoring):
# these follow Prometheus conventions — the `_total` suffix appears ONLY on
# counters (`tpujob_scheduler_migrations_total`,
# `tpujob_node_health_transitions_total`); gauges carry none
# (`tpujob_node_count`).  The convention now holds with no exceptions —
# the one legacy wart, a gauge named `tpujob_job_steps_total`, completed
# its one-release deprecation and is gone; TPL201 enforces the suffix
# rule mechanically from here on.
node_count = LabeledGauge(
    "tpujob_node_count",
    "Nodes in the fleet inventory by effective state (ready / not_ready / "
    "cordoned), sampled once per scheduler tick by the scheduler duty",
    REGISTRY,
    ("state",),
)
node_transitions = LabeledCounter(
    "tpujob_node_health_transitions_total",
    "Durable node health flips committed by the scheduler duty "
    "(to=not_ready when a heartbeat went stale past the bounded grace, "
    "to=ready when it resumed)",
    REGISTRY,
    ("to",),
)
sched_migrations = Counter(
    "tpujob_scheduler_migrations_total",
    "Checkpoint-aware gang migrations staged off dead/cordoned hosts (each "
    "publishes a preempt-target + migrated-from record and runs the bounded "
    "checkpoint barrier before eviction; zero failure strikes)",
    REGISTRY,
)

# Elastic capacity optimizer series: num_slices flex + torus defrag.  Moved
# only by the scheduler duty, like the rest of the tpujob_scheduler_*
# families.
sched_flex = LabeledCounter(
    "tpujob_scheduler_flex_total",
    "num_slices flex moves committed by the capacity planner "
    "(direction=shrink: a gang gave up slices through the staged drain "
    "barrier instead of being evicted; direction=grow: the background "
    "grower flexed a shrunk gang back into idle capacity)",
    REGISTRY,
    ("direction",),
)
sched_defrag_moves = Counter(
    "tpujob_scheduler_defrag_moves_total",
    "Torus defragmentation moves staged (each migrates one gang through "
    "the checkpoint-barrier eviction so its freed fragments merge into a "
    "larger contiguous host run)",
    REGISTRY,
)
sched_fragmentation = Gauge(
    "tpujob_scheduler_fragmentation_ratio",
    "How shredded the free capacity is: 1 - largest free contiguous host "
    "run / total free hosts (0 = all free capacity is one placeable run, "
    "sampled once per scheduler tick)",
    REGISTRY,
)

# Goodput accounting plane (the per-job phase ledger, tpujob/obs/goodput):
# every second of a job's life attributed to one phase, on the controller's
# monotonic clock.  Same one-exporter-per-job discipline as the other
# tpujob_job_* families: only the shard owner exports a job, series are
# removed on finish/delete/handoff, and scraping all members composes the
# fleet view.  The *_seconds_total families are counter-typed but ledger-
# driven (LabeledSettableCounter): cumulative precisely-observed seconds
# within one exporter; a restart/handoff resets them toward zero like a
# process restart (the coarse condition-timestamp re-seed feeds only the
# ratio gauge and the debug/scheduler surfaces).
job_goodput_ratio = LabeledGauge(
    "tpujob_job_goodput_ratio",
    "Productive fraction of the job's accounted wall clock: "
    "(training + checkpointing) seconds / total ledger seconds",
    REGISTRY,
    _JOB_LABELS,
)
job_goodput_seconds = LabeledSettableCounter(
    "tpujob_job_goodput_seconds_total",
    "Productive (training + checkpointing) seconds the job's phase ledger "
    "has attributed",
    REGISTRY,
    _JOB_LABELS,
)
job_badput_seconds = LabeledSettableCounter(
    "tpujob_job_badput_seconds_total",
    "Unproductive seconds the job's phase ledger has attributed, by phase "
    "(queued, scheduling, initializing, stalled, resizing, migrating, "
    "preempted, restarting)",
    REGISTRY,
    _JOB_LABELS + ("phase",),
)
fleet_goodput_ratio = Gauge(
    "tpujob_fleet_goodput_ratio",
    "This member's rollup: productive seconds / total ledger seconds over "
    "every job it currently accounts (fleet-wide truth is the scrape-merge "
    "of the per-job *_seconds_total families — see docs/monitoring)",
    REGISTRY,
)

# Fleet observatory series (tpujob/obs/observatory): the scrape-merge
# plane.  Moved ONLY by an observatory instance (never by a fleet member),
# so running the observatory in-process next to a member keeps every family
# single-writer.  The partition-violation counter is the first-class alarm
# for the invariant every tpujob_job_* family documents: each job has
# exactly one exporter, and shard_ownership sums to 1 per shard — a
# violation that outlives the declared handoff grace names its kind here
# (job-double-export / shard-double-owned / shard-orphaned).
observatory_scrapes = LabeledCounter(
    "tpujob_observatory_scrapes_total",
    "Member scrape attempts by outcome (result=ok / error; one per member "
    "per poll cycle)",
    REGISTRY,
    ("member", "result"),
)
observatory_partition_violations = LabeledCounter(
    "tpujob_observatory_partition_violations_total",
    "Partition-invariant violations that persisted past the handoff grace "
    "window (kind=job-double-export / shard-double-owned / shard-orphaned; "
    "one increment per violation episode, offending members named in "
    "/debug/observatory)",
    REGISTRY,
    ("kind",),
)
observatory_member_up = LabeledGauge(
    "tpujob_observatory_member_up",
    "Whether the member's last scrape succeeded within the staleness bound "
    "(1) or its view is stale/unreachable (0)",
    REGISTRY,
    ("member",),
)
observatory_scrape_age = LabeledGauge(
    "tpujob_observatory_scrape_age_seconds",
    "Seconds since the member's last successful scrape (observatory "
    "monotonic clock)",
    REGISTRY,
    ("member",),
)
observatory_merged_jobs = Gauge(
    "tpujob_observatory_merged_jobs",
    "Distinct jobs in the merged fleet view as of the last poll cycle "
    "(each counted once regardless of how many members exported it)",
    REGISTRY,
)

# SLO engine series: declarative objectives evaluated over the MERGED view
# with multi-window burn-rate alerting (short + long windows must both
# burn past the threshold to fire — one alerts_total increment per
# episode, hysteresis on clear, so scrape races cannot flap an alert).
slo_burn_rate = LabeledGauge(
    "tpujob_slo_burn_rate",
    "Error-budget burn rate of the objective over the named window "
    "(window=short / long; 1.0 = burning exactly the budget)",
    REGISTRY,
    ("slo", "window"),
)
slo_alert_active = LabeledGauge(
    "tpujob_slo_alert_active",
    "Whether the objective's burn-rate alert is currently firing (1) or "
    "not (0)",
    REGISTRY,
    ("slo",),
)
slo_alerts = LabeledCounter(
    "tpujob_slo_alerts_total",
    "Burn-rate alert episodes fired per objective (an episode increments "
    "once on fire; the clear is hysteresis-gated, not counted)",
    REGISTRY,
    ("slo",),
)

# Federation series: the meta-controller above the clusters.  Cluster
# ownership is single-writer by rendezvous (one federation replica
# processes each cluster), so each cluster-labeled series has exactly one
# exporter — the same one-exporter discipline the observatory families
# document, one level up.
federation_scrapes = LabeledCounter(
    "tpujob_federation_scrapes_total",
    "Cluster scrape attempts by outcome (result=ok / error; one per member "
    "target per federation tick, labeled by the cluster scraped)",
    REGISTRY,
    ("cluster", "result"),
)
federation_cluster_up = LabeledGauge(
    "tpujob_federation_cluster_up",
    "Whether the cluster answered its last scrape cycle (1) or every "
    "member scrape is stale (0; a durable NotReady verdict additionally "
    "requires the uncached member-lease re-read to confirm)",
    REGISTRY,
    ("cluster",),
)
federation_cluster_jobs = LabeledGauge(
    "tpujob_federation_cluster_jobs",
    "Jobs owned by the cluster per the federation job mirrors (the "
    "durable tpujob.dev/cluster annotation, mirrored to the meta store)",
    REGISTRY,
    ("cluster",),
)
federation_placements = LabeledCounter(
    "tpujob_federation_placements_total",
    "Initial cluster-placement decisions, labeled by the cluster chosen "
    "(the once-per-job durable annotation write)",
    REGISTRY,
    ("cluster",),
)
federation_spillovers = LabeledCounter(
    "tpujob_federation_spillovers_total",
    "Queue-starved jobs re-targeted through the two-phase transfer "
    "(source = the overloaded home, target = the cluster that took it)",
    REGISTRY,
    ("source", "target"),
)
federation_failovers = LabeledCounter(
    "tpujob_federation_failovers_total",
    "Jobs re-admitted on a survivor after a dark-cluster failover "
    "(source = the cluster marked NotReady, target = where the job "
    "landed with fresh status and checkpoint restore)",
    REGISTRY,
    ("source", "target"),
)
federation_dark_clusters = Gauge(
    "tpujob_federation_dark_clusters",
    "Member clusters currently confirmed dark by this replica (stale "
    "scrapes + no live member lease on the uncached re-read)",
    REGISTRY,
)
federation_tick_seconds = Gauge(
    "tpujob_federation_tick_seconds",
    "Duration of the last federation tick (scrape + mirror + place + "
    "rescue across every owned cluster)",
    REGISTRY,
)

jobs_stalled = Counter(
    "tpujob_operator_stalled_jobs_total",
    "Stalled-condition flips by the progress watchdog (each is one detected "
    "stall episode; recoveries clear the condition but are not counted here)",
    REGISTRY,
)
watchdog_restarts = Counter(
    "tpujob_operator_watchdog_restarts_total",
    "Stuck replicas deleted by the progress watchdog's restart policy "
    "(--stall-policy restart; the normal reconcile then recreates them)",
    REGISTRY,
)
