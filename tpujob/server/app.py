"""Operator application wiring.

Mirrors reference ``cmd/pytorch-operator.v1/app/server.go:66-174``: build
the transport, start monitoring, run leader election, hand leadership to the
controller run loop, wire signal handling.
"""
from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

from tpujob.controller.job_base import ControllerConfig
from tpujob.controller.reconciler import TPUJobController
from tpujob.kube.client import ClientSet
from tpujob.kube.fencing import FencedTransport, KillSwitchTransport
from tpujob.kube.httpclient import HTTPApiClient
from tpujob.kube.memserver import InMemoryAPIServer
from tpujob.obs.recorder import CONTROLLER_TIMELINE_KEY
from tpujob.server.leader_election import LeaderElector
from tpujob.server.monitoring import MonitoringServer
from tpujob.server.options import ServerOption

log = logging.getLogger("tpujob.server")


def build_transport(opt: ServerOption):
    if opt.apiserver == "memory":
        from tpujob.api.validation import install_tpujob_admission

        server = InMemoryAPIServer()
        # UPDATE admission: with elastic resize, Worker replicas is the one
        # mutable spec field of a running job — reject everything else
        # (templates, topology, Master count) server-side with a per-field
        # error list, the ValidatingAdmissionWebhook role
        install_tpujob_admission(server)
        return server
    if opt.apiserver == "kube":
        # real-cluster transport: the self-contained K8s REST client
        # (in-cluster serviceaccount config, kubeconfig fallback)
        from tpujob.kube.kubetransport import (  # noqa: PLC0415
            KubeApiTransport,
            KubeConfig,
            KubeConfigError,
        )

        try:
            config = KubeConfig.load()
        except KubeConfigError as e:
            raise SystemExit(f"--apiserver=kube: no cluster config found: {e}")
        return _maybe_rate_limit(
            KubeApiTransport(config, namespace=opt.namespace or None), opt
        )
    client = HTTPApiClient(opt.apiserver)
    if not client.healthy():
        raise SystemExit(f"cannot reach tpujob API server at {opt.apiserver}")
    return _maybe_rate_limit(client, opt)


def _maybe_rate_limit(transport, opt: ServerOption):
    """Apply --kube-api-qps/--kube-api-burst to real API transports
    (client-go rest.Config QPS/Burst semantics, options.go:54-84).  The
    in-process simulator has no API server to protect and stays unwrapped."""
    if opt.qps and opt.qps > 0:
        from tpujob.kube.ratelimit import RateLimitedTransport

        return RateLimitedTransport(transport, opt.qps, opt.burst)
    return transport


def setup_signal_handler(stop_event: threading.Event) -> None:
    """SIGTERM/SIGINT graceful stop; second signal exits hard
    (vendored signals package semantics)."""

    def handler(signum, frame):
        if stop_event.is_set():
            raise SystemExit(1)
        log.info("received signal %s; shutting down", signum)
        stop_event.set()

    try:
        signal.signal(signal.SIGTERM, handler)
        signal.signal(signal.SIGINT, handler)
    except ValueError:
        pass  # not the main thread (tests)


class OperatorApp:
    def __init__(self, opt: ServerOption, transport=None):
        self.opt = opt
        self.transport = transport if transport is not None else build_transport(opt)
        # the elector speaks the (unfenced) transport directly — lease
        # writes are how you BECOME leader; the controller's clients are
        # fenced on the elector's token so a deposed leader cannot keep
        # writing.  Both ride kill switches so hard_kill() can sever them
        # mid-sync, the way a SIGKILL severs a real process's sockets.
        self.elector: Optional[LeaderElector] = None
        self.coordinator = None  # ShardCoordinator in sharded mode
        self._controller_kill_switch = KillSwitchTransport(self.transport)
        self._elector_kill_switch = KillSwitchTransport(self.transport)
        controller_transport = self._controller_kill_switch
        if opt.shard_count > 0:
            # sharded control plane (--shards N): membership + per-shard
            # fencing leases replace the single-leader election; every
            # member runs its informers and syncs only the shards it owns,
            # with each sync's writes fenced on that shard's lease
            from tpujob.server.sharding import ShardCoordinator

            self.coordinator = ShardCoordinator(
                self._elector_kill_switch,
                num_shards=opt.shard_count,
                namespace=self.lease_namespace(),
                lease_duration=opt.lease_duration_s,
                retry_period=opt.retry_period_s,
                drain_timeout=opt.shard_drain_timeout_s,
            )
            if opt.enable_fencing:
                controller_transport = FencedTransport(
                    self._controller_kill_switch,
                    fence=self.coordinator.current_call_token)
        elif opt.enable_leader_election:
            self.elector = LeaderElector(
                self._elector_kill_switch,
                lock_name=opt.leader_election_id,
                namespace=self.lease_namespace(),
                lease_duration=opt.lease_duration_s,
                renew_deadline=opt.renew_deadline_s,
                retry_period=opt.retry_period_s,
            )
            if opt.enable_fencing:
                controller_transport = FencedTransport(
                    self._controller_kill_switch, fence=self.elector.current_token)
        self.clients = ClientSet(controller_transport)
        self.controller = TPUJobController(
            self.clients,
            config=ControllerConfig(
                threadiness=opt.threadiness,
                resync_period=opt.resync_period_s,
                enable_gang_scheduling=opt.enable_gang_scheduling,
                gang_scheduler_name=opt.gang_scheduler_name,
                init_container_image=opt.init_container_image,
                namespace=opt.namespace or None,
                restart_backoff_seconds=opt.restart_backoff_s,
                restart_backoff_max_seconds=opt.restart_backoff_max_s,
                resize_drain_grace_s=opt.resize_drain_grace_s,
                backoff_base_delay=opt.workqueue_base_backoff_s,
                backoff_max_delay=opt.workqueue_max_backoff_s,
                enable_tracing=opt.enable_tracing,
                slow_sync_threshold_s=opt.slow_sync_threshold_s,
                flight_recorder_size=opt.flight_recorder_size,
                suppress_noop_status=opt.suppress_noop_status,
                status_patch=opt.status_patch,
                settle_window_s=opt.settle_window_s,
                informer_page_size=opt.informer_page_size,
                watch_bookmarks=opt.watch_bookmarks,
                cache_sync_timeout_s=opt.cache_sync_timeout_s,
                enable_telemetry=opt.enable_telemetry,
                stall_timeout_s=opt.stall_timeout_s,
                stall_policy=opt.stall_policy,
                stall_check_interval_s=opt.stall_check_interval_s,
                enable_goodput=opt.enable_goodput,
                cluster_name=opt.cluster_name,
            ),
        )
        if self.coordinator is not None:
            # the coordinator's acquisition/handoff hooks are the
            # controller's: damper rebuild pre-activation, enqueue replay
            # post-activation, drain barrier pre-release
            self.controller.set_sharder(self.coordinator)
            self.coordinator.on_shard_prepare = self.controller.prepare_shard
            self.coordinator.on_shard_acquired = self.controller.on_shard_acquired
            self.coordinator.on_shard_drain = self.controller.drain_shard
        self.scheduler = None
        if opt.scheduler_capacity:
            # native gang scheduler (--sched-capacity): an admission queue
            # in front of the reconciler — jobs hold no pods until their
            # whole gang places all-or-nothing against the modeled slice
            # capacity.  The decision loop starts with the controller (it
            # needs synced informer caches) and, in a sharded fleet, only
            # runs ticks on the member owning the scheduler shard.
            from tpujob.server.scheduler import GangScheduler

            self.scheduler = GangScheduler(
                self.controller,
                capacity=opt.scheduler_capacity,
                tick_s=opt.scheduler_tick_s,
                aging_s=opt.scheduler_aging_s,
                enable_preemption=opt.scheduler_preemption,
                preempt_grace_s=opt.scheduler_preempt_grace_s,
                node_grace_s=opt.node_grace_s,
                node_damp_s=opt.node_migration_damp_s,
                enable_flex=opt.scheduler_flex,
                enable_defrag=opt.scheduler_defrag,
                defrag_threshold=opt.scheduler_defrag_threshold,
            )
            self.controller.set_scheduler(self.scheduler)
        self.monitoring: Optional[MonitoringServer] = None
        self.observatory = None  # Observatory when --observatory is on
        self.observatory_server = None  # its HTTP listener
        self.federation = None  # FederationController when --federation is on
        self.federation_server = None  # its /debug/federation listener
        self.stop_event = threading.Event()
        self.controller_threads: list = []
        self._elector_thread: Optional[threading.Thread] = None
        self._coordinator_thread: Optional[threading.Thread] = None
        self._hard_killed = False

    def run(self, block: bool = True) -> None:
        # fields-aware formatters: per-job tags from joblogger render in both
        # text and JSON output (reference logrus setup, main.go:42-58)
        from tpujob.controller.joblogger import configure_root_logging

        configure_root_logging(self.opt.json_log_format)
        setup_signal_handler(self.stop_event)
        if self.opt.monitoring_port:
            # negative port = ephemeral bind (port 0); the negative value
            # stays truthy so the gate above still opens
            self.monitoring = MonitoringServer(
                port=max(0, self.opt.monitoring_port),
                flight=self.controller.flight,
                fleet=self.controller.fleet_snapshot,
                debug_state=self.controller.debug_job_state,
                why=self.controller.explain_job,
            ).start()
            log.info("monitoring on :%d/metrics (+/debug/jobs, /debug/fleet)",
                     self.monitoring.port)
        if self.opt.enable_observatory:
            self._start_observatory()
        if self.opt.enable_federation:
            self._start_federation()

        def start_controller():
            log.info("starting controller (threadiness=%d%s)",
                     self.opt.threadiness,
                     f", shards={self.opt.shard_count}"
                     if self.coordinator is not None else "")
            self.controller_threads = self.controller.run(
                self.stop_event, threadiness=self.opt.threadiness)
            if self.scheduler is not None:
                # behind the cache-sync barrier like the workers: the first
                # tick must see the full durable assignment state, never a
                # half-filled cache that would double-book capacity
                self.controller_threads.append(
                    self.scheduler.start(self.stop_event))

        def started_leading():
            try:
                token = self.elector.current_token() if self.elector else None
                if token is not None:
                    self.controller.flight.record(
                        CONTROLLER_TIMELINE_KEY, "leadership",
                        f"{token.holder} acquired leadership "
                        f"(generation {token.generation})",
                        {"identity": token.holder,
                         "generation": token.generation})
                start_controller()
            except Exception:
                # a failed cold start (e.g. caches never synced) must be
                # fatal, not a zombie that holds the lease while doing
                # nothing: stop the app so the process exits and the
                # Deployment restarts it; the elector's clean stop then
                # releases the lease for a standby
                log.exception("controller failed to start after acquiring "
                              "leadership; exiting")
                self.stop_event.set()

        def lost_leadership():
            # loss of leadership is fatal; the Deployment restarts us.  The
            # fence has already slammed shut: is_leader flipped before this
            # callback, so every in-flight mutating call is being rejected.
            self.controller.flight.record(
                CONTROLLER_TIMELINE_KEY, "leadership",
                f"{self.elector.identity} lost leadership; exiting",
                {"identity": self.elector.identity})
            log.error("leader election lost; exiting")
            self.stop_event.set()

        if self.coordinator is not None:
            # sharded fleet: the controller (informers + workers) starts
            # unconditionally — the dequeue-time ownership check keeps
            # unowned shards untouched — and the coordinator thread starts
            # only AFTER the cache-sync barrier, so acquisition hooks
            # (damper rebuild, enqueue replay) always read a synced cache
            start_controller()
            self.controller.flight.record(
                CONTROLLER_TIMELINE_KEY, "shard",
                f"{self.coordinator.identity} joined the shard fleet "
                f"({self.coordinator.num_shards} shards)",
                {"identity": self.coordinator.identity,
                 "shards": self.coordinator.num_shards})
            # start before publish: a shutdown racing construction must
            # never join a created-but-unstarted Thread (TPL001)
            coordinator_thread = threading.Thread(
                target=self.coordinator.run, args=(self.stop_event,),
                daemon=True, name="shard-coordinator",
            )
            coordinator_thread.start()
            self._coordinator_thread = coordinator_thread
        elif self.elector is not None:
            self.elector.on_started_leading = started_leading
            self.elector.on_stopped_leading = lost_leadership
            # start before publish: a shutdown racing construction must
            # never join a created-but-unstarted Thread (TPL001)
            elector_thread = threading.Thread(
                target=self.elector.run, args=(self.stop_event,), daemon=True,
                name="leader-elector",
            )
            elector_thread.start()
            self._elector_thread = elector_thread
        else:
            start_controller()

        if block:
            try:
                while not self.stop_event.wait(0.5):
                    pass
            finally:
                self.shutdown()

    def _start_observatory(self) -> None:
        """In-process fleet observatory (--observatory): scrape the
        member list in --observatory-targets (default: just this member's
        own monitoring endpoint), merge, verify, alert.  The handoff
        grace defaults to one lease term plus one scrape interval — the
        window in which a double export is the protocol, not a bug."""
        from tpujob.obs.observatory import Observatory, ObservatoryServer

        targets = [t.strip()
                   for t in self.opt.observatory_targets.split(",")
                   if t.strip()]
        # an explicit target list is the whole membership catalog, so the
        # shard-orphan invariant is falsifiable; the self-scrape default
        # is knowingly partial and must not run it
        whole_fleet = bool(targets)
        if not targets:
            if self.monitoring is None:
                log.warning("--observatory without targets or a monitoring "
                            "port: nothing to scrape; skipping")
                return
            targets = [f"http://127.0.0.1:{self.monitoring.port}"]
        grace = self.opt.observatory_handoff_grace_s
        if grace <= 0:
            grace = self.opt.lease_duration_s + self.opt.observatory_interval_s
        self.observatory = Observatory(
            targets=targets,
            interval_s=self.opt.observatory_interval_s,
            handoff_grace_s=grace,
            check_orphans=whole_fleet,
        )
        self.observatory_server = ObservatoryServer(
            self.observatory, port=max(0, self.opt.observatory_port)).start()
        self.observatory.start(self.stop_event)
        log.info("observatory on :%d scraping %d member(s) "
                 "(handoff grace %.1fs)",
                 self.observatory_server.port, len(targets), grace)

    def _start_federation(self) -> None:
        """In-process federation replica (--federation): scrape the member
        clusters in --federation-clusters, own a rendezvous-assigned
        subset, place/spill/rescue their jobs.  The CLI can only express
        clusters as scrape targets; the cluster matching --cluster-name
        additionally gets this member's own API transport, so the
        federation can do fenced writes into its home cluster.  (Chaos
        harness and embedders construct ClusterHandles with real
        transports for EVERY cluster; the meta store rides this member's
        own API server — a real deployment points it at the federation
        host cluster.)"""
        import uuid

        from tpujob.server.federation import (
            ClusterHandle,
            FederationController,
            FederationServer,
        )

        clusters = []
        for spec in self.opt.federation_clusters.split(";"):
            spec = spec.strip()
            if not spec:
                continue
            name, sep, urls = spec.partition("=")
            if not sep or not name:
                log.warning("--federation-clusters: skipping malformed "
                            "spec %r (want name=url1|url2)", spec)
                continue
            targets = [u.strip() for u in urls.split("|") if u.strip()]
            server = (self.transport
                      if name.strip() == self.opt.cluster_name else None)
            clusters.append(ClusterHandle(
                name=name.strip(), server=server, targets=targets))
        if not clusters:
            log.warning("--federation without --federation-clusters: "
                        "nothing to federate; skipping")
            return
        identity = (self.coordinator.identity if self.coordinator is not None
                    else f"fed-{uuid.uuid4().hex[:8]}")
        grace = self.opt.federation_dark_grace_s
        if grace <= 0:
            grace = (self.opt.lease_duration_s
                     + 2 * self.opt.federation_interval_s)
        damp = self.opt.federation_damp_s
        if damp <= 0:
            damp = 2 * self.opt.lease_duration_s
        self.federation = FederationController(
            identity=identity,
            meta=self.transport,
            clusters=clusters,
            namespace=self.lease_namespace(),
            interval_s=self.opt.federation_interval_s,
            lease_duration_s=self.opt.lease_duration_s,
            spillover_wait_s=self.opt.federation_spillover_wait_s,
            dark_grace_s=grace,
            damp_base_s=damp,
        )
        if self.opt.federation_port:
            self.federation_server = FederationServer(
                self.federation,
                port=max(0, self.opt.federation_port)).start()
        self.federation.start(self.stop_event)
        log.info("federation replica %s over %d cluster(s) (dark grace "
                 "%.1fs, damp base %.1fs)", identity, len(clusters),
                 grace, damp)

    def lease_namespace(self) -> str:
        """The namespace holding the leader-election Lease: the operator's
        OWN namespace, like the reference derives from KUBEFLOW_NAMESPACE
        (server.go:72-76,146-152).  A hardcoded 'default' would make two
        operators in different namespaces fight over one lease — and a
        namespace-restricted deploy couldn't write it at all."""
        import os

        if self.opt.leader_election_namespace:
            return self.opt.leader_election_namespace
        env_ns = os.environ.get("OPERATOR_NAMESPACE", "")
        if env_ns:
            return env_ns
        # in-cluster: the serviceaccount-mounted namespace on the transport
        cfg = getattr(self.transport, "config", None)
        cfg_ns = getattr(cfg, "namespace", "") if cfg is not None else ""
        return cfg_ns or "default"

    def _stop_threads(self) -> bool:
        """Stop and JOIN every thread this app started — workers included,
        so no in-flight sync keeps writing after the stop returns (for a
        clean shutdown that would be exactly the deposed-leader window
        fencing exists to close; joining closes it at the source).
        Returns True iff every thread actually exited within its join
        timeout."""
        self.stop_event.set()
        self.controller.queue.shutdown()
        self.controller.factory.stop()
        # join order follows the spawn chain: elector (publishes
        # leading_thread) -> leading callback (assigns controller_threads
        # when start_controller returns) -> workers.  Joining out of order
        # could read leading_thread/controller_threads before the upstream
        # thread published them and skip threads that are still starting.
        threads = []
        if self._coordinator_thread is not None:
            threads.append(self._coordinator_thread)
            self._coordinator_thread.join(timeout=2)
        if self._elector_thread is not None:
            threads.append(self._elector_thread)
            self._elector_thread.join(timeout=2)
        if self.elector is not None and self.elector.leading_thread is not None:
            threads.append(self.elector.leading_thread)
            self.elector.leading_thread.join(timeout=2)
        for t in self.controller_threads:
            threads.append(t)
            t.join(timeout=2)
        if self.observatory is not None and self.observatory._thread is not None:
            threads.append(self.observatory._thread)
            self.observatory._thread.join(timeout=2)
        if self.observatory_server is not None:
            self.observatory_server.stop()
        if self.federation is not None and self.federation._thread is not None:
            threads.append(self.federation._thread)
            self.federation._thread.join(timeout=2)
        if self.federation_server is not None:
            self.federation_server.stop()
        if self.monitoring:
            self.monitoring.stop()
        return not any(t.is_alive() for t in threads)

    def shutdown(self) -> None:
        """Clean shutdown: stop + join everything, then release the lease
        (zeroed holderIdentity) so a restarted or failed-over standby
        acquires immediately instead of waiting out ``lease_duration``."""
        drained = self._stop_threads()
        if self._hard_killed:
            return
        if drained:
            # every thread is joined, so this cannot race an in-flight
            # write OR the elector's own clean-stop release; idempotent
            # once already released
            if self.elector is not None:
                self.elector.release()
            if self.coordinator is not None:
                self.coordinator.release_all()
        elif self.elector is not None or self.coordinator is not None:
            # a worker outlived its join timeout (e.g. wedged in a slow
            # API call): releasing now would invite a standby in while
            # our write may still land — let the lease(s) expire instead
            log.warning(
                "threads still alive at shutdown; skipping early lease "
                "release (standby must wait out lease_duration)")

    def hard_kill(self) -> None:
        """Crash simulation: stop every thread WITHOUT releasing the lease,
        flushing status, or draining the queue.  All in-memory state —
        expectations, restart ledgers, crash-loop dampers, flight recorder —
        dies with the instance, exactly as a SIGKILLed process; a standby
        must wait out the stale lease.  The chaos harness's controller-kill
        schedules use this seam."""
        self._hard_killed = True
        if self.elector is not None:
            self.elector.release_on_stop = False
        # sever BEFORE stopping: a worker mid-sync dies on its next API call
        # instead of finishing the sync — crashes land between the writes of
        # one sync (where recovery bugs live), not on tidy sync boundaries
        self._controller_kill_switch.sever()
        self._elector_kill_switch.sever()
        self._stop_threads()
