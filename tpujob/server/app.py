"""Operator application wiring.

Mirrors reference ``cmd/pytorch-operator.v1/app/server.go:66-174``: build
the transport, start monitoring, run leader election, hand leadership to the
controller run loop, wire signal handling.
"""
from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

from tpujob.controller.job_base import ControllerConfig
from tpujob.controller.reconciler import TPUJobController
from tpujob.kube.client import ClientSet
from tpujob.kube.httpclient import HTTPApiClient
from tpujob.kube.memserver import InMemoryAPIServer
from tpujob.server.leader_election import LeaderElector
from tpujob.server.monitoring import MonitoringServer
from tpujob.server.options import ServerOption

log = logging.getLogger("tpujob.server")


def build_transport(opt: ServerOption):
    if opt.apiserver == "memory":
        return InMemoryAPIServer()
    if opt.apiserver == "kube":
        # real-cluster transport: the self-contained K8s REST client
        # (in-cluster serviceaccount config, kubeconfig fallback)
        from tpujob.kube.kubetransport import (  # noqa: PLC0415
            KubeApiTransport,
            KubeConfig,
            KubeConfigError,
        )

        try:
            config = KubeConfig.load()
        except KubeConfigError as e:
            raise SystemExit(f"--apiserver=kube: no cluster config found: {e}")
        return _maybe_rate_limit(
            KubeApiTransport(config, namespace=opt.namespace or None), opt
        )
    client = HTTPApiClient(opt.apiserver)
    if not client.healthy():
        raise SystemExit(f"cannot reach tpujob API server at {opt.apiserver}")
    return _maybe_rate_limit(client, opt)


def _maybe_rate_limit(transport, opt: ServerOption):
    """Apply --kube-api-qps/--kube-api-burst to real API transports
    (client-go rest.Config QPS/Burst semantics, options.go:54-84).  The
    in-process simulator has no API server to protect and stays unwrapped."""
    if opt.qps and opt.qps > 0:
        from tpujob.kube.ratelimit import RateLimitedTransport

        return RateLimitedTransport(transport, opt.qps, opt.burst)
    return transport


def setup_signal_handler(stop_event: threading.Event) -> None:
    """SIGTERM/SIGINT graceful stop; second signal exits hard
    (vendored signals package semantics)."""

    def handler(signum, frame):
        if stop_event.is_set():
            raise SystemExit(1)
        log.info("received signal %s; shutting down", signum)
        stop_event.set()

    try:
        signal.signal(signal.SIGTERM, handler)
        signal.signal(signal.SIGINT, handler)
    except ValueError:
        pass  # not the main thread (tests)


class OperatorApp:
    def __init__(self, opt: ServerOption, transport=None):
        self.opt = opt
        self.transport = transport if transport is not None else build_transport(opt)
        self.clients = ClientSet(self.transport)
        self.controller = TPUJobController(
            self.clients,
            config=ControllerConfig(
                threadiness=opt.threadiness,
                resync_period=opt.resync_period_s,
                enable_gang_scheduling=opt.enable_gang_scheduling,
                gang_scheduler_name=opt.gang_scheduler_name,
                init_container_image=opt.init_container_image,
                namespace=opt.namespace or None,
                restart_backoff_seconds=opt.restart_backoff_s,
                restart_backoff_max_seconds=opt.restart_backoff_max_s,
                backoff_base_delay=opt.workqueue_base_backoff_s,
                backoff_max_delay=opt.workqueue_max_backoff_s,
                enable_tracing=opt.enable_tracing,
                slow_sync_threshold_s=opt.slow_sync_threshold_s,
                flight_recorder_size=opt.flight_recorder_size,
            ),
        )
        self.monitoring: Optional[MonitoringServer] = None
        self.stop_event = threading.Event()

    def run(self, block: bool = True) -> None:
        # fields-aware formatters: per-job tags from joblogger render in both
        # text and JSON output (reference logrus setup, main.go:42-58)
        from tpujob.controller.joblogger import configure_root_logging

        configure_root_logging(self.opt.json_log_format)
        setup_signal_handler(self.stop_event)
        if self.opt.monitoring_port:
            self.monitoring = MonitoringServer(
                port=self.opt.monitoring_port,
                flight=self.controller.flight,
            ).start()
            log.info("monitoring on :%d/metrics (+/debug/jobs)",
                     self.monitoring.port)

        def start_controller():
            log.info("leadership acquired; starting controller (threadiness=%d)",
                     self.opt.threadiness)
            self.controller.run(self.stop_event, threadiness=self.opt.threadiness)

        def lost_leadership():
            # loss of leadership is fatal; the Deployment restarts us
            log.error("leader election lost; exiting")
            self.stop_event.set()

        if self.opt.enable_leader_election:
            elector = LeaderElector(
                self.transport,
                lock_name=self.opt.leader_election_id,
                namespace=self.lease_namespace(),
                lease_duration=self.opt.lease_duration_s,
                renew_deadline=self.opt.renew_deadline_s,
                retry_period=self.opt.retry_period_s,
                on_started_leading=start_controller,
                on_stopped_leading=lost_leadership,
            )
            thread = threading.Thread(
                target=elector.run, args=(self.stop_event,), daemon=True,
                name="leader-elector",
            )
            thread.start()
        else:
            start_controller()

        if block:
            try:
                while not self.stop_event.wait(0.5):
                    pass
            finally:
                self.shutdown()

    def lease_namespace(self) -> str:
        """The namespace holding the leader-election Lease: the operator's
        OWN namespace, like the reference derives from KUBEFLOW_NAMESPACE
        (server.go:72-76,146-152).  A hardcoded 'default' would make two
        operators in different namespaces fight over one lease — and a
        namespace-restricted deploy couldn't write it at all."""
        import os

        if self.opt.leader_election_namespace:
            return self.opt.leader_election_namespace
        env_ns = os.environ.get("OPERATOR_NAMESPACE", "")
        if env_ns:
            return env_ns
        # in-cluster: the serviceaccount-mounted namespace on the transport
        cfg = getattr(self.transport, "config", None)
        cfg_ns = getattr(cfg, "namespace", "") if cfg is not None else ""
        return cfg_ns or "default"

    def shutdown(self) -> None:
        self.stop_event.set()
        self.controller.queue.shutdown()
        self.controller.factory.stop()
        if self.monitoring:
            self.monitoring.stop()
