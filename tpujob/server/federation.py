"""Multi-cluster federation: cluster-sharded job ownership, queue
spillover, and dark-cluster failover.

Everything below the federation runs inside ONE cluster; this module is
the meta-controller above them, and it is deliberately a REUSE of the
sharding abstractions rather than a new consensus design — each member
cluster's API server is, in effect, one more shard of the control plane:

- **Job → cluster**: ownership is cluster-granular and durable ON the job
  object (``tpujob.dev/cluster``, written once at placement).  The meta
  store only *mirrors* it — annotations survive every controller restart,
  and a mirror that disagrees with a live cluster is corrected FROM the
  cluster, never the other way around.
- **Cluster → federation replica**: rendezvous hashing over the live
  federation membership (``sharding.rendezvous_owner`` with cluster NAMES
  as the shard keys — the same ≈1/N stability argument holds: adding a
  replica moves only the clusters the newcomer wins).  Membership is the
  same fail-closed heartbeat-lease protocol as the shard plane
  (``sharding.live_lease_holders`` on the ``tpujob-fedmember-*`` prefix in
  the meta store).
- **Per-cluster fencing**: one federation duty lease per member cluster
  (``tpujob-fed-<cluster>``), held IN that cluster's own API server.
  Every federation write into a cluster carries a
  :class:`~tpujob.kube.fencing.FencingToken` naming that lease at the
  generation the duty was acquired; a deposed replica's stale token is
  rejected server-side by the same fence validation that protects the
  shard plane.
- **Placement** scores candidate clusters by topology feasibility (the
  gang must be placeable on SOME pool — ``quota.feasibility_errors``
  against the cluster's declared or scraped capacity), live queue depth
  and capacity (each cluster's members' ``/debug/fleet``, scraped through
  the shared :mod:`tpujob.obs.scrape` client), and per-cluster fleet
  goodput ratio; ties break by rendezvous weight so every replica computes
  the same answer from the same view.
- **Spillover**: a job whose home cluster's queue holds it beyond a
  bounded wait is re-targeted through a two-phase transfer (stamp the new
  owner + ``cluster-transfer`` marker on the source copy → create on the
  target → delete the source copy) so BOTH copies agree on the one owner
  at every committed instant and an interrupted transfer resumes instead
  of forking.
- **Dark-cluster failover**: a cluster whose scrapes have ALL gone stale
  is confirmed by an uncached member-lease re-read against its API server
  (the NodeHealth stance: no verdict from a cache); once dark past the
  grace it is durably marked ``NotReady`` in the meta store and its jobs
  are re-placed onto surviving feasible clusters — re-created with fresh
  status (ZERO counted restarts; the workload restores from its last
  checkpoint barrier) and ``failed-over-from`` provenance.  Failover is
  damped per-cluster with exponential backoff so a flapping WAN link can
  never storm the fleet.

A revived cluster is swept before it is trusted: local copies of jobs the
mirror re-homed elsewhere are deleted (fenced, at the NEW duty
generation) before the durable state flips back to ``Ready``.  Until that
sweep lands — bounded by one federation tick — the revived cluster's own
members may briefly recreate pods for a job that failed over; the job
object deletion (not failure) and the workload's checkpoint restore make
that window harmless.

All placement/failover logic is clock- and transport-injectable
(``tick(now=...)``, ``fetch=``) for the unit matrix; ``e2e/federation.py``
drives whole in-process clusters through it.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpujob.analysis import lockgraph
from tpujob.api import constants as c
from tpujob.api.quota import (
    capacity_chips,
    feasibility_errors,
    gang_request,
    parse_capacity,
)
from tpujob.api.types import TPUJob
from tpujob.kube.client import RESOURCE_TPUJOBS
from tpujob.kube.errors import (
    AlreadyExistsError,
    ConflictError,
    FencedError,
    NotFoundError,
)
from tpujob.kube.fencing import FencingToken, call_token
from tpujob.obs.scrape import ScrapeClient, http_fetch
from tpujob.server import metrics
from tpujob.server.leader_election import (
    acquire_or_renew_lease,
    release_lease,
    rfc3339micro,
)
from tpujob.server.sharding import (
    MEMBER_LEASE_PREFIX,
    heartbeat_member_lease,
    live_lease_holders,
    rendezvous_owner,
    stable_hash,
)

log = logging.getLogger("tpujob.federation")

# meta-store resources (the memserver auto-creates stores per resource;
# a real deployment backs these with CRDs in the federation host cluster)
RESOURCE_JOB_MIRRORS = "jobmirrors"
RESOURCE_CLUSTER_STATES = "clusterstates"

# federation membership heartbeats live in the META store on their own
# prefix so they can never collide with a cluster's shard-plane members
FED_MEMBER_LEASE_PREFIX = "tpujob-fedmember"
# the per-cluster federation duty lease lives IN that cluster's own API
# server: the fence that validates our writes must die with the cluster
FED_DUTY_LEASE_PREFIX = "tpujob-fed"

# scheduler-protocol annotations that must NOT survive a cross-cluster
# move: the target cluster's scheduler admits the gang from scratch
_SCHED_ANNOTATIONS = (
    c.ANNOTATION_SCHED_ASSIGNMENT,
    c.ANNOTATION_SCHED_EVICTED,
    c.ANNOTATION_PREEMPT_TARGET,
    c.ANNOTATION_PREEMPT_ACK,
    c.ANNOTATION_FLEX_SLICES,
    c.ANNOTATION_MIGRATED_FROM,
)


def fed_duty_lease_name(cluster: str) -> str:
    return f"{FED_DUTY_LEASE_PREFIX}-{cluster}"


def preferred_cluster(job_key: str, clusters: List[str]) -> Optional[str]:
    """The rendezvous-preferred home for a job among cluster names — the
    deterministic tiebreak every replica computes identically, and the
    function the cluster-granularity stability test pins (adding a cluster
    moves ≈1/N preferences, all TO the newcomer)."""
    return rendezvous_owner(f"job:{job_key}", clusters)


@dataclass
class ClusterHandle:
    """One member cluster as the federation sees it.

    ``server`` is the cluster's API-server transport (an
    ``InMemoryAPIServer`` in the chaos tier, a ``KubeApiTransport`` in a
    real deployment); ``targets`` are its members' debug/metrics base URLs
    for the scrape plane.  ``capacity`` optionally declares the cluster's
    slice pools (``"v4-16x2"``-style) as the feasibility bootstrap — when
    empty, capacity is reconstructed from the scraped scheduler
    inventory."""

    name: str
    server: Any = None
    targets: List[str] = field(default_factory=list)
    capacity: str = ""


class FederationController:
    """Scrape every cluster, own a rendezvous-assigned subset of them, and
    for each owned cluster: mirror its jobs into the meta store, place the
    unplaced, spill over the starved, rescue the dark."""

    def __init__(
        self,
        identity: str,
        meta: Any,
        clusters: List[ClusterHandle],
        namespace: str = "default",
        interval_s: float = 1.0,
        lease_duration_s: float = 5.0,
        spillover_wait_s: float = 30.0,
        dark_grace_s: Optional[float] = None,
        damp_base_s: Optional[float] = None,
        stale_after_s: Optional[float] = None,
        fetch: Optional[Callable[[str, str], Any]] = None,
    ):
        self.identity = identity
        self.meta = meta
        self.clusters = list(clusters)
        self.namespace = namespace
        self.interval_s = interval_s
        self.lease_duration_s = lease_duration_s
        self.spillover_wait_s = spillover_wait_s
        # a cluster must be CONFIRMED dark (stale scrapes + no live member
        # lease on an uncached re-read) for a full grace before failover:
        # default one lease term + two scrape intervals — the window in
        # which a healthy cluster could still prove itself
        self.dark_grace_s = (dark_grace_s if dark_grace_s is not None
                             else lease_duration_s + 2 * interval_s)
        # failover damper base: episode N waits base * 2^(N-1) before the
        # next failover of the SAME cluster may fire
        self.damp_base_s = (damp_base_s if damp_base_s is not None
                            else 2 * lease_duration_s)
        self.stale_after_s = (stale_after_s if stale_after_s is not None
                              else interval_s * 1.5)
        self._scraper = ScrapeClient(
            fetch=fetch if fetch is not None else http_fetch(
                timeout_s=max(0.5, interval_s)),
            stale_after_s=self.stale_after_s,
            lock_name="federation-scrape")
        self._lock = lockgraph.new_lock("federation")
        # all guarded by self._lock:
        self._duties: Dict[str, int] = {}  # cluster -> held duty generation
        self._members: List[str] = []  # last live federation membership
        self._dark_since: Dict[str, float] = {}  # first confirmed-dark time
        self._damp_until: Dict[str, float] = {}  # no failover before (mono)
        self._damp_factor: Dict[str, int] = {}  # episode count per cluster
        self._cluster_up: Dict[str, bool] = {}
        self.ticks = 0
        self.placements = 0
        self.spillovers = 0
        self.failovers = 0
        self._thread: Optional[threading.Thread] = None

    # -- small lookups -------------------------------------------------------

    def _cluster(self, name: str) -> Optional[ClusterHandle]:
        for cl in self.clusters:
            if cl.name == name:
                return cl
        return None

    def owned_clusters(self) -> List[str]:
        with self._lock:
            return sorted(self._duties)

    def _token(self, cluster: str) -> Optional[FencingToken]:
        with self._lock:
            gen = self._duties.get(cluster)
        if gen is None:
            return None
        return FencingToken(self.identity, gen,
                            lease=fed_duty_lease_name(cluster))

    def _deposed(self, cluster: str) -> None:
        """A fence rejection means another replica holds the duty now:
        drop it locally and let the next tick re-rendezvous."""
        with self._lock:
            self._duties.pop(cluster, None)
        log.warning("federation duty for cluster %s fenced away from %s",
                    cluster, self.identity)

    # -- meta-store records --------------------------------------------------

    def _mirrors(self) -> Dict[str, Dict[str, Any]]:
        out = {}
        for m in self.meta.list(RESOURCE_JOB_MIRRORS, self.namespace):
            md = m.get("metadata") or {}
            ns = md.get("namespace") or self.namespace
            out[f"{ns}/{md.get('name')}"] = m
        return out

    def _upsert(self, resource: str, name: str,
                mutate: Callable[[Dict[str, Any]], None]) -> bool:
        """Create-or-update one meta record; a lost optimistic-concurrency
        race is retried next tick (the meta store is single-logical-writer
        per cluster by rendezvous, so races are membership-churn noise)."""
        try:
            current = self.meta.get(resource, self.namespace, name)
        except NotFoundError:
            obj = {"metadata": {"name": name, "namespace": self.namespace}}
            mutate(obj)
            try:
                self.meta.create(resource, obj)
                return True
            except AlreadyExistsError:
                current = self.meta.get(resource, self.namespace, name)
        mutate(current)
        try:
            self.meta.update(resource, current)
            return True
        except (ConflictError, NotFoundError):
            return False

    def _cluster_state(self, name: str) -> Dict[str, Any]:
        try:
            return self.meta.get(RESOURCE_CLUSTER_STATES, self.namespace,
                                 name)
        except NotFoundError:
            return {}

    # -- capacity / load views (from the shared scrape plane) ----------------

    def _fresh_payloads(self, cl: ClusterHandle,
                        now: float) -> Dict[str, Dict[str, Any]]:
        return self._scraper.fresh(now, cl.targets)

    def _sched_block(self, cl: ClusterHandle,
                     now: float) -> Optional[Dict[str, Any]]:
        """The cluster's scheduler-duty owner's block: the one actually
        narrating (queue/rings populated); non-owners export empty
        shells — the observatory's selection rule, applied per cluster."""
        best, best_score = None, -1
        for payload in self._fresh_payloads(cl, now).values():
            block = payload.get("scheduler")
            if not block:
                continue
            score = (len(block.get("queue") or [])
                     + len(block.get("rings") or {})
                     + len(block.get("verdicts") or {}))
            if score > best_score:
                best, best_score = block, score
        return best

    def _cluster_pools(self, cl: ClusterHandle, now: float):
        """Feasibility pools: the declared bootstrap capacity when given,
        else reconstructed from the scraped scheduler inventory; None when
        the cluster's capacity is unknowable this tick."""
        spec = cl.capacity
        if not spec:
            block = self._sched_block(cl, now) or {}
            rows = block.get("capacity") or []
            spec = ",".join(
                f"{r['accelerator']}x{r['slices']}" for r in rows
                if r.get("accelerator") and r.get("slices"))
        if not spec:
            return None
        try:
            return parse_capacity(spec)
        except Exception:  # noqa: TPL005 - unmodelable capacity = not a candidate
            return None

    def _cluster_load(self, cl: ClusterHandle,
                      now: float) -> Tuple[int, float]:
        """(queue depth, fleet goodput ratio) from the live scrape."""
        block = self._sched_block(cl, now) or {}
        depth = len(block.get("queue") or [])
        ratios = []
        for payload in self._fresh_payloads(cl, now).values():
            g = payload.get("goodput") or {}
            if g.get("goodput_ratio") is not None:
                ratios.append(float(g["goodput_ratio"]))
        ratio = sum(ratios) / len(ratios) if ratios else 1.0
        return depth, ratio

    def _queue_wait_s(self, cl: ClusterHandle, now: float,
                      job_key: str) -> Optional[float]:
        block = self._sched_block(cl, now) or {}
        for row in block.get("queue") or []:
            if row.get("job") == job_key and row.get("wait_s") is not None:
                return float(row["wait_s"])
        return None

    # -- placement -----------------------------------------------------------

    def _gang_req(self, job_dict: Dict[str, Any]):
        try:
            return gang_request(TPUJob.from_dict(job_dict))
        except Exception:  # noqa: TPL005 - an unmodelable spec places by load alone
            return None

    def _place(self, job_dict: Dict[str, Any], candidates: List[str],
               now: float) -> Optional[str]:
        """Best feasible cluster for the job among ``candidates``: most
        free-looking first (shallowest queue, most chips, best goodput),
        rendezvous weight as the deterministic tiebreak.  None when no
        candidate is feasible."""
        md = job_dict.get("metadata") or {}
        key = f"{md.get('namespace') or self.namespace}/{md.get('name')}"
        req = self._gang_req(job_dict)
        scored = []
        for name in candidates:
            cl = self._cluster(name)
            if cl is None:
                continue
            state = self._cluster_state(name)
            if state.get("phase") == c.CLUSTER_NOT_READY:
                continue
            pools = self._cluster_pools(cl, now)
            if pools is None:
                continue
            if req is not None and feasibility_errors(req, pools):
                continue
            depth, ratio = self._cluster_load(cl, now)
            scored.append((
                -depth, capacity_chips(pools), ratio,
                stable_hash(f"shard:job:{key}:member:{name}"), name))
        if not scored:
            return None
        return max(scored)[-1]

    # -- mirror/object shaping -----------------------------------------------

    @staticmethod
    def _sanitized(job_dict: Dict[str, Any], target: str,
                   failed_over_from: Optional[str] = None) -> Dict[str, Any]:
        """The job object as it lands on a NEW cluster: same spec, fresh
        status (zero counted restarts — failover is not failure), owner
        annotation for the target, every scheduler-protocol marker and
        server-assigned field cleared so the target admits from scratch."""
        obj = json.loads(json.dumps(job_dict))
        md = obj.setdefault("metadata", {})
        for k in ("resourceVersion", "uid", "creationTimestamp",
                  "generation"):
            md.pop(k, None)
        ann = dict(md.get("annotations") or {})
        for k in _SCHED_ANNOTATIONS:
            ann.pop(k, None)
        ann.pop(c.ANNOTATION_CLUSTER_TRANSFER, None)
        ann[c.ANNOTATION_CLUSTER] = target
        if failed_over_from:
            ann[c.ANNOTATION_FAILED_OVER_FROM] = failed_over_from
        md["annotations"] = ann
        obj.pop("status", None)
        return obj

    def _record_mirror(self, key: str, job_dict: Dict[str, Any],
                       cluster: str, transfer_from: Optional[str] = None,
                       rescue_from: Optional[str] = None) -> None:
        ns, _, name = key.partition("/")

        def mutate(m: Dict[str, Any]) -> None:
            m["metadata"]["namespace"] = ns
            m["cluster"] = cluster
            if transfer_from is not None:
                m["transfer_from"] = transfer_from
            if rescue_from is not None:
                m["rescue_from"] = rescue_from
            if job_dict is not None:
                m["object"] = self._sanitized(job_dict, cluster)
            m["observed_at"] = rfc3339micro(time.time())

        self._upsert(RESOURCE_JOB_MIRRORS, name, mutate)

    # -- the tick ------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> None:
        """One federation cycle: heartbeat, rendezvous, renew duties,
        scrape everyone, then process each OWNED cluster (mirror, place,
        spill, rescue).  Mirrors the shard coordinator's tick shape —
        membership truth first, then per-duty work."""
        now = time.monotonic() if now is None else now
        t0 = time.monotonic()
        heartbeat_member_lease(self.meta, self.namespace, self.identity,
                               self.lease_duration_s,
                               prefix=FED_MEMBER_LEASE_PREFIX)
        members = live_lease_holders(self.meta, self.namespace,
                                     FED_MEMBER_LEASE_PREFIX,
                                     self.lease_duration_s)
        with self._lock:
            self._members = members
        if self.identity not in members:
            # our own heartbeat is not visible: own nothing this tick (the
            # shard coordinator's self-eviction stance)
            desired: List[str] = []
        else:
            desired = [cl.name for cl in self.clusters
                       if rendezvous_owner(f"cluster:{cl.name}", members)
                       == self.identity]

        # release duties for clusters rendezvous moved away (best effort —
        # an unreachable cluster's lease simply expires)
        with self._lock:
            held = list(self._duties)
        for name in held:
            if name in desired:
                continue
            cl = self._cluster(name)
            if cl is not None and cl.server is not None:
                try:
                    release_lease(cl.server, self.namespace,
                                  fed_duty_lease_name(name), self.identity)
                except Exception:  # noqa: TPL005 - dark cluster: lease expires instead
                    pass
            with self._lock:
                self._duties.pop(name, None)

        # scrape EVERY cluster (placement scoring needs candidates we do
        # not own); write-duties are acquired only for the owned subset
        for cl in self.clusters:
            for target in cl.targets:
                payload = self._scraper.scrape(target, "/debug/fleet",
                                               now=now)
                metrics.federation_scrapes.labels(
                    cluster=cl.name,
                    result="ok" if payload is not None else "error").inc()

        for name in desired:
            cl = self._cluster(name)
            if cl is None or cl.server is None:
                continue
            try:
                self._process_cluster(cl, now)
            except FencedError:
                self._deposed(cl.name)
            except Exception:  # noqa: TPL005 - one cluster's fault never kills the loop
                log.exception("federation tick failed for cluster %s",
                              cl.name)

        with self._lock:
            self.ticks += 1
            dark = sum(1 for up in self._cluster_up.values() if not up)
        metrics.federation_dark_clusters.set(dark)
        metrics.federation_tick_seconds.set(
            round(time.monotonic() - t0, 6))

    # -- per-cluster duty work -----------------------------------------------

    def _process_cluster(self, cl: ClusterHandle, now: float) -> None:
        up = bool(self._fresh_payloads(cl, now))
        if not up:
            self._handle_dark_candidate(cl, now)
            return
        with self._lock:
            self._dark_since.pop(cl.name, None)
            self._cluster_up[cl.name] = True
        metrics.federation_cluster_up.labels(cluster=cl.name).set(1)

        # the cluster answers: hold (or take) the federation duty lease in
        # ITS OWN store — the fence every write below is validated against
        with self._lock:
            renewing = cl.name in self._duties
        gen = acquire_or_renew_lease(
            cl.server, self.namespace, fed_duty_lease_name(cl.name),
            self.identity, self.lease_duration_s, renewing=renewing)
        if gen is None:
            # another replica's unexpired duty lease stands; rendezvous
            # says it is ours, so it will expire into our hands shortly
            return
        with self._lock:
            self._duties[cl.name] = gen

        was_not_ready = (self._cluster_state(cl.name).get("phase")
                         == c.CLUSTER_NOT_READY)
        jobs = cl.server.list(RESOURCE_TPUJOBS, self.namespace)
        mirrors = self._mirrors()
        local_keys = set()
        token = self._token(cl.name)
        for job in jobs:
            md = job.get("metadata") or {}
            key = f"{md.get('namespace') or self.namespace}/{md.get('name')}"
            local_keys.add(key)
            self._process_job(cl, job, key, mirrors.get(key), token, now,
                              reviving=was_not_ready)

        # rescue/create pass: mirrors homed HERE whose object is absent —
        # phase 2 of a transfer, or a dark-cluster rescue landing
        for key, m in mirrors.items():
            if m.get("cluster") != cl.name or key in local_keys:
                continue
            self._materialize(cl, key, m, token)

        metrics.federation_cluster_jobs.labels(cluster=cl.name).set(
            sum(1 for m in self._mirrors().values()
                if m.get("cluster") == cl.name))

        if was_not_ready:
            # revival: the sweep above already deleted every local copy
            # the mirror re-homed; only now is the cluster trusted again
            self._upsert(RESOURCE_CLUSTER_STATES, cl.name,
                         lambda s: s.update(
                             phase=c.CLUSTER_READY,
                             since=rfc3339micro(time.time()),
                             reason="scrapes and member leases live again"))
            log.info("cluster %s revived: swept and marked Ready", cl.name)

    def _process_job(self, cl: ClusterHandle, job: Dict[str, Any], key: str,
                     mirror: Optional[Dict[str, Any]],
                     token: FencingToken, now: float,
                     reviving: bool = False) -> None:
        md = job.get("metadata") or {}
        ann = dict(md.get("annotations") or {})
        owner = ann.get(c.ANNOTATION_CLUSTER)
        ns, _, name = key.partition("/")

        if (reviving and owner == cl.name and mirror is not None
                and mirror.get("cluster") not in (None, cl.name)):
            # zombie copy: the job failed over while this cluster was
            # dark — the mirror's re-homing IS the committed ownership.
            # Align our copy's annotation first (both copies agree on the
            # one owner at every committed instant), then delete it; the
            # cluster only flips back to Ready after this sweep lands.
            new_home = mirror["cluster"]
            with call_token(token):
                cl.server.patch(RESOURCE_TPUJOBS, ns, name, {
                    "metadata": {"annotations": {
                        c.ANNOTATION_CLUSTER: new_home}}})
                try:
                    cl.server.delete(RESOURCE_TPUJOBS, ns, name)
                except NotFoundError:
                    pass
            log.info("revival sweep: zombie copy of %s on %s deleted "
                     "(owner is %s since the failover)", key, cl.name,
                     new_home)
            return

        if owner is None:
            # unplaced: assign once, durably, on the object itself.  The
            # home cluster wins when feasible (optimistic-local-start keeps
            # placement latency off the happy path; spillover corrects
            # overload later)
            candidates = [cl.name] + [x.name for x in self.clusters
                                      if x.name != cl.name]
            home_pools = self._cluster_pools(cl, now)
            req = self._gang_req(job)
            if home_pools is not None and (
                    req is None or not feasibility_errors(req, home_pools)):
                target = cl.name
            else:
                target = self._place(job, candidates, now)
            if target is None:
                return  # nowhere feasible; leave unplaced and visible
            patch = {"metadata": {"annotations": {
                c.ANNOTATION_CLUSTER: target}}}
            if target != cl.name:
                patch["metadata"]["annotations"][
                    c.ANNOTATION_CLUSTER_TRANSFER] = target
            with call_token(token):
                cl.server.patch(RESOURCE_TPUJOBS, ns, name, patch)
            self._record_mirror(
                key, job, target,
                transfer_from=cl.name if target != cl.name else None)
            with self._lock:
                self.placements += 1
            metrics.federation_placements.labels(cluster=target).inc()
            log.info("placed %s on cluster %s", key, target)
            return

        if owner == cl.name:
            # home-owned: keep the mirror true, then judge spillover
            if (mirror is None or mirror.get("cluster") != cl.name
                    or mirror.get("transfer_from")
                    or mirror.get("rescue_from")):
                self._record_mirror(key, job, cl.name,
                                    transfer_from="", rescue_from="")
            wait = self._queue_wait_s(cl, now, key)
            if wait is not None and wait > self.spillover_wait_s:
                self._spill(cl, job, key, token, now)
            return

        # owner is another cluster: this is a transfer source copy.  Once
        # the mirror shows the target holds it (transfer marker cleared),
        # delete ours — phase 3, the commit of the move
        if (mirror is not None and mirror.get("cluster") == owner
                and not mirror.get("transfer_from")):
            with call_token(token):
                try:
                    cl.server.delete(RESOURCE_TPUJOBS, ns, name)
                except NotFoundError:
                    pass
            log.info("transfer of %s to %s committed: source copy on %s "
                     "deleted", key, owner, cl.name)

    def _spill(self, cl: ClusterHandle, job: Dict[str, Any], key: str,
               token: FencingToken, now: float) -> None:
        """Phase 1 of the two-phase transfer for a queue-starved job: pick
        a strictly-better feasible cluster, stamp the new owner + transfer
        marker on the source copy (fenced), re-home the mirror."""
        home_depth, _ = self._cluster_load(cl, now)
        candidates = [x.name for x in self.clusters if x.name != cl.name]
        target = self._place(job, candidates, now)
        if target is None:
            return
        depth, _ = self._cluster_load(self._cluster(target), now)
        if depth >= home_depth:
            return  # no better home; spilling would just trade queues
        ns, _, name = key.partition("/")
        with call_token(token):
            cl.server.patch(RESOURCE_TPUJOBS, ns, name, {
                "metadata": {"annotations": {
                    c.ANNOTATION_CLUSTER: target,
                    c.ANNOTATION_CLUSTER_TRANSFER: target}}})
        self._record_mirror(key, job, target, transfer_from=cl.name)
        with self._lock:
            self.spillovers += 1
        metrics.federation_spillovers.labels(
            source=cl.name, target=target).inc()
        log.info("spillover: %s re-targeted %s -> %s (queue wait past "
                 "%.1fs)", key, cl.name, target, self.spillover_wait_s)

    def _materialize(self, cl: ClusterHandle, key: str,
                     m: Dict[str, Any], token: FencingToken) -> None:
        """Create the mirror's object on its (this) home cluster: phase 2
        of a transfer, or a rescue landing after a failover."""
        obj = m.get("object")
        if not obj:
            return
        rescue_from = m.get("rescue_from") or None
        obj = self._sanitized(obj, cl.name, failed_over_from=rescue_from)
        with call_token(token):
            try:
                cl.server.create(RESOURCE_TPUJOBS, obj)
            except AlreadyExistsError:
                pass  # already landed (a prior tick's write raced the read)
        if m.get("transfer_from") or rescue_from:
            def clear(mm: Dict[str, Any]) -> None:
                mm["transfer_from"] = ""
                mm["rescue_from"] = ""
                mm["observed_at"] = rfc3339micro(time.time())
            ns, _, name = key.partition("/")
            self._upsert(RESOURCE_JOB_MIRRORS, name, clear)
        if rescue_from:
            with self._lock:
                self.failovers += 1
            metrics.federation_failovers.labels(
                source=rescue_from, target=cl.name).inc()
            log.info("failover: %s re-admitted on %s (from dark %s, fresh "
                     "status, checkpoint restore)", key, cl.name,
                     rescue_from)

    # -- dark-cluster detection + failover -----------------------------------

    def _handle_dark_candidate(self, cl: ClusterHandle, now: float) -> None:
        """Every scrape of the cluster is stale.  Confirm with an UNCACHED
        member-lease read against its API server (fail closed: any live —
        or unparseable — member lease vetoes darkness), then wait out the
        grace and the damper before the failover fires."""
        alive: Optional[List[str]] = None
        try:
            alive = live_lease_holders(cl.server, self.namespace,
                                       MEMBER_LEASE_PREFIX,
                                       self.lease_duration_s)
        except Exception:  # noqa: TPL005 - API unreachable IS the confirmation
            alive = None
        if alive:
            # scrape plane dark but the control plane answers with live
            # members: a monitoring failure, not a dead cluster
            with self._lock:
                self._dark_since.pop(cl.name, None)
            return
        with self._lock:
            first = self._dark_since.setdefault(cl.name, now)
            damp_until = self._damp_until.get(cl.name, float("-inf"))
            self._cluster_up[cl.name] = False
        metrics.federation_cluster_up.labels(cluster=cl.name).set(0)
        if now - first < self.dark_grace_s or now < damp_until:
            return
        self._fail_over(cl, now)

    def _fail_over(self, cl: ClusterHandle, now: float) -> None:
        """The cluster is confirmed dark past grace and damper: durably
        mark it NotReady and re-home every job it owned onto the best
        surviving feasible cluster.  The actual re-creation is each
        survivor's duty owner's next pass — single-writer per cluster all
        the way down."""
        with self._lock:
            episode = self._damp_factor.get(cl.name, 0) + 1
            self._damp_factor[cl.name] = episode
            self._damp_until[cl.name] = (
                now + self.damp_base_s * (2 ** (episode - 1)))
        self._upsert(RESOURCE_CLUSTER_STATES, cl.name,
                     lambda s: s.update(
                         phase=c.CLUSTER_NOT_READY,
                         since=rfc3339micro(time.time()),
                         reason="all scrapes stale and no live member "
                                "lease on uncached re-read",
                         episodes=episode))
        survivors = [x.name for x in self.clusters if x.name != cl.name]
        moved = stranded = 0
        for key, m in self._mirrors().items():
            if m.get("cluster") != cl.name:
                continue
            obj = m.get("object")
            if not obj:
                stranded += 1
                continue
            target = self._place(obj, survivors, now)
            ns, _, name = key.partition("/")
            if target is None:
                stranded += 1
                self._upsert(RESOURCE_JOB_MIRRORS, name,
                             lambda mm: mm.update(stranded=True))
                continue

            def rehome(mm: Dict[str, Any], target=target) -> None:
                mm["cluster"] = target
                mm["rescue_from"] = cl.name
                mm["transfer_from"] = ""
                mm["stranded"] = False
                mm["observed_at"] = rfc3339micro(time.time())

            if self._upsert(RESOURCE_JOB_MIRRORS, name, rehome):
                moved += 1
        log.warning(
            "cluster %s marked NotReady (episode %d): %d job(s) re-homed "
            "to survivors, %d stranded; next failover damped %.1fs",
            cl.name, episode, moved, stranded,
            self.damp_base_s * (2 ** (episode - 1)))

    # -- snapshot / debug surface --------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``/debug/federation`` payload: the fleet-of-fleets merge —
        durable meta state (mirrors, cluster phases) plus this replica's
        live scrape view and duty map."""
        now = time.monotonic()
        with self._lock:
            duties = dict(self._duties)
            members = list(self._members)
            dark_since = dict(self._dark_since)
            damp_until = dict(self._damp_until)
            damp_factor = dict(self._damp_factor)
            ticks = self.ticks
            placements = self.placements
            spillovers = self.spillovers
            failovers = self.failovers
        mirrors = self._mirrors()
        states = self._scraper.states()
        rows = []
        for cl in self.clusters:
            fresh = self._fresh_payloads(cl, now)
            pools = self._cluster_pools(cl, now)
            depth, ratio = self._cluster_load(cl, now)
            state = self._cluster_state(cl.name)
            target_rows = []
            for t in cl.targets:
                st = states.get(t) or {}
                age = (None if st.get("last_ok") is None
                       else round(now - st["last_ok"], 3))
                target_rows.append({
                    "target": t, "up": t in fresh, "scrape_age_s": age,
                    "failures": st.get("failures", 0),
                    "error": None if t in fresh else st.get("error"),
                })
            rows.append({
                "name": cl.name,
                "phase": state.get("phase") or c.CLUSTER_READY,
                "up": bool(fresh),
                "owner": duties.get(cl.name) is not None and self.identity
                or rendezvous_owner(f"cluster:{cl.name}", members),
                "owned_here": cl.name in duties,
                "duty_generation": duties.get(cl.name),
                "targets": target_rows,
                "jobs": sum(1 for m in mirrors.values()
                            if m.get("cluster") == cl.name),
                "queue_depth": depth,
                "goodput_ratio": ratio,
                "capacity_chips": capacity_chips(pools) if pools else None,
                "dark_since_s": (round(now - dark_since[cl.name], 3)
                                 if cl.name in dark_since else None),
                "damped_for_s": (round(damp_until[cl.name] - now, 3)
                                 if damp_until.get(cl.name, -1) > now
                                 else None),
                "failover_episodes": damp_factor.get(cl.name, 0),
            })
        return {
            "identity": self.identity,
            "ticks": ticks,
            "members": members,
            "clusters": rows,
            "jobs": {
                key: {"cluster": m.get("cluster"),
                      "transfer_from": m.get("transfer_from") or None,
                      "rescue_from": m.get("rescue_from") or None,
                      "stranded": bool(m.get("stranded"))}
                for key, m in sorted(mirrors.items())},
            "totals": {"placements": placements, "spillovers": spillovers,
                       "failovers": failovers},
            "spillover_wait_s": self.spillover_wait_s,
            "dark_grace_s": self.dark_grace_s,
            "damp_base_s": self.damp_base_s,
        }

    # -- run loop ------------------------------------------------------------

    def start(self, stop_event: threading.Event) -> threading.Thread:
        # start before publish: a shutdown racing construction must never
        # join a created-but-unstarted Thread (TPL001)
        thread = threading.Thread(target=self.run, args=(stop_event,),
                                  daemon=True, name="tpujob-federation")
        thread.start()
        self._thread = thread
        return thread

    def run(self, stop_event: threading.Event) -> None:
        while not stop_event.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: TPL005 - the tick loop is the one retry policy
                log.exception("federation tick failed; retrying next "
                              "interval")
        self.release_all()

    def release_all(self) -> None:
        """Graceful shutdown: release every held duty lease so a standby
        replica acquires immediately instead of waiting out the term."""
        with self._lock:
            held = list(self._duties)
            self._duties.clear()
        for name in held:
            cl = self._cluster(name)
            if cl is None or cl.server is None:
                continue
            try:
                release_lease(cl.server, self.namespace,
                              fed_duty_lease_name(name), self.identity)
            except Exception:  # noqa: TPL005 - best effort; the lease expires
                pass


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


class _FedHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        path = self.path.partition("?")[0]
        fed: FederationController = self.server.federation
        if path.startswith("/debug/federation"):
            body = json.dumps(fed.snapshot(), indent=2,
                              default=str).encode()
            ctype, code = "application/json", 200
        elif path.startswith("/healthz"):
            body, ctype, code = b"ok", "text/plain", 200
        else:
            body, ctype, code = b"not found", "text/plain", 404
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class FederationServer:
    """The federation's own listener: /debug/federation, /healthz."""

    def __init__(self, federation: FederationController,
                 host: str = "0.0.0.0", port: int = 0):
        self.httpd = ThreadingHTTPServer((host, port), _FedHandler)
        self.httpd.daemon_threads = True
        self.httpd.federation = federation
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "FederationServer":
        # start before publish (TPL001)
        thread = threading.Thread(target=self.httpd.serve_forever,
                                  daemon=True, name="tpujob-federation-http")
        thread.start()
        self._thread = thread
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2)
