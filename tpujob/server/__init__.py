"""Operator server: entrypoint, options, leader election, metrics."""
