"""/metrics + /healthz HTTP listener (reference main.go:31-40)."""
from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tpujob.server.metrics import REGISTRY


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        if self.path.startswith("/metrics"):
            body = REGISTRY.expose().encode()
            ctype = "text/plain; version=0.0.4"
            code = 200
        elif self.path.startswith("/healthz"):
            body, ctype, code = b"ok", "text/plain", 200
        else:
            body, ctype, code = b"not found", "text/plain", 404
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MonitoringServer:
    def __init__(self, host: str = "0.0.0.0", port: int = 8443):
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "MonitoringServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True, name="tpujob-monitoring"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2)
