"""/metrics + /healthz + /debug/* HTTP listener (reference main.go:31-40).

Beyond the reference's metrics/health surface, the listener serves the
flight recorder's introspection payloads — the ``kubectl describe`` analog
for the operator's own decision history:

- ``/debug/jobs``                 index of tracked jobs
- ``/debug/jobs/<ns>/<name>``     ordered per-job lifecycle timeline, plus
                                  the controller-owned ``status`` block
                                  (resize staging record, observed
                                  generation, live progress row)
- ``/debug/traces/<corr-id>``     one sync's nested span tree
- ``/debug/fleet``                this instance's workload-telemetry view:
                                  identity, owned shards, one progress row
                                  per job it currently syncs.  Merging the
                                  payloads of every fleet member yields the
                                  fleet-wide view (each job appears under
                                  exactly one member — the shard partition
                                  invariant).

All JSON, all read-only, all bounded (the recorder rotates history).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tpujob.server.metrics import REGISTRY


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _debug_payload(self, path: str):
        """Resolve one /debug/* path to its JSON payload (None = 404)."""
        parts = [p for p in path.split("/") if p]  # ["debug", ...]
        if parts == ["debug", "fleet"]:
            fleet = getattr(self.server, "fleet", None)
            return fleet() if callable(fleet) else None
        if len(parts) == 4 and parts[:2] == ["debug", "why"]:
            # "why is my job not running": the scheduler's verdict +
            # decision ring for one job (see docs/failure-handling)
            why = getattr(self.server, "why", None)
            return why(parts[2], parts[3]) if callable(why) else None
        flight = getattr(self.server, "flight", None)
        if flight is None:
            return None
        if parts == ["debug", "jobs"]:
            return flight.jobs_index()
        if len(parts) == 4 and parts[:2] == ["debug", "jobs"]:
            payload = flight.timeline(parts[2], parts[3])
            state_fn = getattr(self.server, "debug_state", None)
            if callable(state_fn):
                # controller-owned state the timeline cannot carry: the
                # durable resize record, observedGeneration, live progress
                state = state_fn(parts[2], parts[3])
                if payload is None and state is not None:
                    payload = {"job": f"{parts[2]}/{parts[3]}", "entries": []}
                if payload is not None:
                    payload["status"] = state
            return payload
        if len(parts) == 3 and parts[:2] == ["debug", "traces"]:
            return flight.trace(parts[2])
        return None

    def do_GET(self):
        path = self.path.partition("?")[0]
        if path.startswith("/metrics"):
            body = REGISTRY.expose().encode()
            ctype = "text/plain; version=0.0.4"
            code = 200
        elif path.startswith("/healthz"):
            body, ctype, code = b"ok", "text/plain", 200
        elif path.startswith("/debug/"):
            payload = self._debug_payload(path)
            if payload is None:
                body, ctype, code = b'{"error": "not found"}', "application/json", 404
            else:
                body = json.dumps(payload, indent=2).encode()
                ctype, code = "application/json", 200
        else:
            body, ctype, code = b"not found", "text/plain", 404
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MonitoringServer:
    def __init__(self, host: str = "0.0.0.0", port: int = 8443, flight=None,
                 fleet=None, debug_state=None, why=None):
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.daemon_threads = True
        # the flight recorder backing /debug/* (None = endpoints 404)
        self.httpd.flight = flight
        # callable returning the /debug/fleet payload (None = 404)
        self.httpd.fleet = fleet
        # callable(ns, name) merged into /debug/jobs/<ns>/<name> as "status"
        self.httpd.debug_state = debug_state
        # callable(ns, name) behind /debug/why/<ns>/<name> (None = 404)
        self.httpd.why = why
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "MonitoringServer":
        # start before publish: a concurrent stop() must never see (and
        # join) a created-but-unstarted Thread (TPL001)
        server = threading.Thread(
            target=self.httpd.serve_forever, daemon=True, name="tpujob-monitoring"
        )
        server.start()
        self._thread = server
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2)
