"""Defaulting for TPUJob resources.

Mirrors reference ``pkg/apis/pytorch/v1/defaults.go:36-106``:
- cleanPodPolicy -> None
- replica-type names normalized to CamelCase (master -> Master)
- replicas -> 1, restartPolicy -> OnFailure
- the default coordinator port appended to the Master's managed container
TPU-first addition: default the chip topology / chipsPerHost from the
accelerator generation, and default Worker replicas to the slice host count
minus the Master host.
"""
from __future__ import annotations

from tpujob.api import constants as c
from tpujob.api.topology import TopologyError
from tpujob.api.types import ReplicaSpec, TPUJob
from tpujob.kube.objects import ContainerPort


def _normalize_replica_type(rtype: str) -> str:
    low = rtype.lower()
    if low == c.REPLICA_TYPE_MASTER.lower():
        return c.REPLICA_TYPE_MASTER
    if low == c.REPLICA_TYPE_WORKER.lower():
        return c.REPLICA_TYPE_WORKER
    return rtype


def set_default_port(spec: ReplicaSpec) -> None:
    """Append the default coordinator port to the managed container if absent
    (defaults.go:36-58)."""
    for container in spec.template.spec.containers:
        if container.name != c.DEFAULT_CONTAINER_NAME:
            continue
        for port in container.ports:
            if port.name == c.DEFAULT_PORT_NAME:
                return
        container.ports.append(
            ContainerPort(name=c.DEFAULT_PORT_NAME, container_port=c.DEFAULT_PORT)
        )


def set_defaults_tpujob(job: TPUJob) -> None:
    """Apply all defaults in place (defaults.go:88-106 equivalent)."""
    spec = job.spec
    if spec.run_policy.clean_pod_policy is None:
        spec.run_policy.clean_pod_policy = c.DEFAULT_CLEAN_POD_POLICY

    # normalize replica-type keys
    for rtype in list(spec.tpu_replica_specs):
        norm = _normalize_replica_type(rtype)
        if norm != rtype:
            spec.tpu_replica_specs[norm] = spec.tpu_replica_specs.pop(rtype)

    master = spec.tpu_replica_specs.get(c.REPLICA_TYPE_MASTER)
    worker = spec.tpu_replica_specs.get(c.REPLICA_TYPE_WORKER)

    # resolve topology defaults before defaulting replica counts
    slice_topo = None
    for rspec in spec.tpu_replica_specs.values():
        if rspec.tpu and rspec.tpu.accelerator:
            try:
                topo = rspec.tpu.resolve()
            except TopologyError:
                continue  # validation reports it with a proper error
            rspec.tpu.topology = topo.topology
            rspec.tpu.chips_per_host = topo.chips_per_host
            slice_topo = slice_topo or topo

    for rtype, rspec in spec.tpu_replica_specs.items():
        if rspec.replicas is None:
            if rtype == c.REPLICA_TYPE_WORKER and slice_topo is not None:
                # default Worker count to the slice's host pods (minus the
                # Master's host when one exists)
                rspec.replicas = max(
                    0, slice_topo.num_processes - (1 if master is not None else 0)
                )
            else:
                rspec.replicas = 1
        if rspec.restart_policy is None:
            rspec.restart_policy = c.DEFAULT_RESTART_POLICY

    if master is not None:
        set_default_port(master)
    elif worker is not None:
        # master-less single-replica-set jobs still need the coordinator port
        set_default_port(worker)
