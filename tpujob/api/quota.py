"""Gang-scheduler quota math: tiers, fair share, aging, feasibility.

The pure-function half of the native gang scheduler
(``tpujob/server/scheduler.py`` owns the capacity bookkeeping and the
decision loop; everything here is side-effect-free and unit-testable in
isolation):

- **priority tiers** parsed from ``runPolicy.schedulingPolicy.priorityClass``
  (named classes or explicit ``tier-N``), with **aging** promotion — a
  queued job's *effective* tier rises one level per ``aging_s`` waited, so
  nothing starves below the tier cap forever (the anti-starvation bound:
  a feasible gang waits at most ``TIER_MAX * aging_s`` before it outranks
  everything admitted below the cap and may preempt);
- **per-namespace fair share** by dominant-resource (chip) accounting:
  among equals, the namespace using the smallest fraction of the modeled
  fleet goes first;
- **gang requests** derived from the job spec (``api/topology.py`` is the
  single source of host/chip arithmetic) and the **feasibility check**
  that rejects never-placeable shapes at admission — an infeasible gang
  must get a durable verdict, not wedge the queue head forever;
- the **snake (boustrophedon) host order** that makes "a contiguous host
  index range" mean "a torus-adjacent host path" on both 2D (v2/v3/v5e)
  and 3D (v4/v5p) ICI meshes.
"""
from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from typing import List, Optional, Tuple

from tpujob.api import constants as c
from tpujob.api.topology import (
    SliceTopology,
    TopologyError,
    default_topology,
    parse_accelerator,
)
from tpujob.api.types import TPUJob

# Priority tiers: 0 (preempt-me-first) .. TIER_MAX (never preempted).
TIER_MAX = 3
TIER_NAMES = {
    "": 1,
    "low": 0,
    "normal": 1,
    "default": 1,
    "high": 2,
    "critical": TIER_MAX,
}


def parse_tier(priority_class: Optional[str]) -> int:
    """Tier of a ``schedulingPolicy.priorityClass`` value.

    Named classes (low/normal/high/critical) or an explicit ``tier-N``;
    anything unrecognized falls back to normal — a typo'd class must not
    silently make a job preempt everything (or be preempted by everything).
    """
    name = (priority_class or "").strip().lower()
    if name in TIER_NAMES:
        return TIER_NAMES[name]
    if name.startswith("tier-"):
        try:
            return max(0, min(TIER_MAX, int(name[len("tier-"):])))
        except ValueError:
            return TIER_NAMES["normal"]
    return TIER_NAMES["normal"]


def effective_tier(tier: int, waited_s: float, aging_s: float) -> int:
    """Aging promotion: one tier per ``aging_s`` in the queue, capped at
    TIER_MAX.  ``aging_s <= 0`` disables aging (tier stays as declared)."""
    if aging_s <= 0 or waited_s <= 0:
        return min(TIER_MAX, max(0, tier))
    return min(TIER_MAX, max(0, tier) + int(waited_s / aging_s))


# ---------------------------------------------------------------------------
# fleet capacity description
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SlicePoolSpec:
    """One homogeneous pool of TPU slices, e.g. 4x v4-32."""

    accelerator: str  # e.g. "v4-32"
    count: int  # number of identical slices in the pool
    shape: SliceTopology  # resolved single-slice topology

    @property
    def generation(self) -> str:
        return parse_accelerator(self.accelerator)[0].name

    @property
    def total_chips(self) -> int:
        return self.shape.chips * self.count

    @property
    def chips_per_host(self) -> int:
        return self.shape.chips_per_host


def parse_capacity(spec: str) -> List[SlicePoolSpec]:
    """Parse a fleet capacity string like ``v4-32x4`` or ``v4-16x2,v5e-16x1``
    into slice pools.  Raises :class:`TopologyError` on garbage — a fleet
    that cannot be modeled must fail at startup, not at the first admission.
    """
    pools: List[SlicePoolSpec] = []
    for part in (p.strip() for p in (spec or "").split(",")):
        if not part:
            continue
        accel, sep, count_s = part.rpartition("x")
        if not sep or not accel:
            raise TopologyError(
                f"invalid capacity pool {part!r}; want e.g. 'v4-32x4'")
        try:
            count = int(count_s)
        except ValueError:
            raise TopologyError(
                f"invalid slice count {count_s!r} in capacity pool {part!r}")
        if count <= 0:
            raise TopologyError(
                f"capacity pool {part!r} must have a positive slice count")
        pools.append(SlicePoolSpec(
            accelerator=accel, count=count,
            shape=SliceTopology.resolve(accel)))
    if not pools:
        raise TopologyError(f"empty capacity spec {spec!r}")
    return pools


def capacity_chips(pools: List[SlicePoolSpec]) -> int:
    return sum(p.total_chips for p in pools)


# ---------------------------------------------------------------------------
# gang requests + feasibility
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class GangRequest:
    """What one job needs, all-or-nothing: ``num_slices`` slices (of the
    named generation when pinned, any pool otherwise), each hosting
    ``hosts_per_slice`` torus-adjacent host pods."""

    namespace: str
    name: str
    generation: Optional[str]  # TPU generation pinned by spec.tpu (or None)
    accelerator: Optional[str]  # the pinned accelerator string (or None)
    num_slices: int
    hosts_per_slice: int
    tier: int
    # elastic-capacity floor: the scheduler may flex the gang down to this
    # many slices (but never below) instead of evicting it; None = no
    # declared floor (flexible to one slice)
    min_slices: Optional[int] = None

    @property
    def total_hosts(self) -> int:
        return self.num_slices * self.hosts_per_slice

    def chips_on(self, pool: SlicePoolSpec) -> int:
        """Modeled chip cost when placed on ``pool`` (the dominant-share
        accounting unit)."""
        return self.total_hosts * pool.chips_per_host


def flex_request(req: GangRequest, flex: Optional[int]) -> GangRequest:
    """The flex-effective request: the spec shape narrowed to the slice
    count the scheduler currently holds the gang at.  The full-spec request
    still judges feasibility (never-placeable is about the SPEC), but
    capacity decisions — outgrow detection, re-admission while flexed —
    follow the flexed shape."""
    if flex is None or flex >= req.num_slices or flex < 1:
        return req
    return dataclasses.replace(req, num_slices=flex)


def gang_request(job: TPUJob) -> GangRequest:
    """Derive the job's gang request from its spec.

    A topology-pinned job (any replica carries ``spec.tpu``) requests its
    resolved slice count and per-slice host count; an unpinned job requests
    its total replica count as torus-adjacent hosts on any single slice.
    Raises :class:`TopologyError` on an unresolvable tpu spec (CREATE-time
    admission rejects those before they ever reach a queue).
    """
    sp = job.spec.run_policy.scheduling_policy
    tier = parse_tier(sp.priority_class if sp is not None else None)
    min_slices = sp.min_slices if sp is not None else None
    ns = job.metadata.namespace or "default"
    tpu = None
    for rspec in job.spec.tpu_replica_specs.values():
        if rspec.tpu is not None and rspec.tpu.accelerator:
            tpu = rspec.tpu
            break
    total = sum(
        (r.replicas if r.replicas is not None else 1)
        for t, r in job.spec.tpu_replica_specs.items()
        if t in (c.REPLICA_TYPE_MASTER, c.REPLICA_TYPE_WORKER)
    )
    if tpu is None:
        return GangRequest(
            namespace=ns, name=job.metadata.name or "",
            generation=None, accelerator=None,
            num_slices=1, hosts_per_slice=max(1, total), tier=tier,
            min_slices=min_slices)
    topo = tpu.resolve()
    gen, _ = parse_accelerator(topo.accelerator)
    return GangRequest(
        namespace=ns, name=job.metadata.name or "",
        generation=gen.name, accelerator=topo.accelerator,
        num_slices=topo.num_slices, hosts_per_slice=topo.hosts, tier=tier,
        min_slices=min_slices)


def pool_fits(req: GangRequest, pool: SlicePoolSpec) -> bool:
    """Whether ``pool``'s slices can host this gang's per-slice shape."""
    if req.generation is not None and pool.generation != req.generation:
        return False
    return req.hosts_per_slice <= pool.shape.hosts


def feasibility_errors(req: GangRequest,
                       pools: List[SlicePoolSpec]) -> List[str]:
    """Why this gang can NEVER be placed on an EMPTY fleet (empty list =
    feasible).  Checked at admission so an impossible shape gets a durable
    verdict instead of wedging the queue."""
    errs: List[str] = []
    if req.num_slices < 1 or req.hosts_per_slice < 1:
        errs.append(
            f"gang shape is degenerate: {req.num_slices} slice(s) x "
            f"{req.hosts_per_slice} host(s)")
        return errs
    candidates = [p for p in pools if pool_fits(req, p)]
    if not candidates:
        if req.generation is not None and not any(
                p.generation == req.generation for p in pools):
            errs.append(
                f"no {req.generation} capacity in the fleet (pools: "
                f"{sorted({p.accelerator for p in pools})})")
        else:
            errs.append(
                f"no slice in the fleet has {req.hosts_per_slice} hosts "
                f"(largest: "
                f"{max((p.shape.hosts for p in pools), default=0)})")
        return errs
    if max(p.count for p in candidates) < req.num_slices:
        errs.append(
            f"gang needs {req.num_slices} slices but the largest matching "
            f"pool has {max(p.count for p in candidates)}")
    return errs


# ---------------------------------------------------------------------------
# fair share (dominant-resource accounting per namespace)
# ---------------------------------------------------------------------------


def namespace_share(used_chips: float, fleet_chips: int) -> float:
    """One namespace's dominant share: the fraction of the modeled fleet's
    chips its admitted gangs currently hold."""
    if fleet_chips <= 0:
        return 0.0
    return used_chips / float(fleet_chips)


def queue_sort_key(req: GangRequest, eff_tier: int, ns_share: float,
                   queued_since: float) -> Tuple:
    """Total order over the admission queue: effective tier first (higher
    wins), then the namespace furthest under its fair share, then FIFO, then
    name (a deterministic tiebreak so two members — or two ticks — always
    agree on the order)."""
    return (-eff_tier, ns_share, queued_since, req.namespace, req.name)


# ---------------------------------------------------------------------------
# torus-adjacent host ordering
# ---------------------------------------------------------------------------


def host_grid(shape: SliceTopology) -> Tuple[int, ...]:
    """The host grid of one slice: hosts factored near-balanced into the
    generation's ICI dimensionality (2D for v2/v3/v5e-style meshes, 3D for
    v4/v5p tori), mirroring how real slices group chips into host VMs."""
    gen, _ = parse_accelerator(shape.accelerator)
    dims = tuple(int(d) for d in
                 default_topology(shape.hosts, gen.topology_dims).split("x"))
    assert math.prod(dims) == shape.hosts
    return dims


def snake_order(dims: Tuple[int, ...]) -> List[Tuple[int, ...]]:
    """Boustrophedon walk of a grid: consecutive entries differ by exactly
    one step along exactly one axis, so ANY contiguous index range of the
    walk is a connected (torus-adjacent) host path.  This is what lets the
    capacity model allocate "torus-adjacent hosts" as plain contiguous
    intervals."""
    if not dims:
        return [()]
    out: List[Tuple[int, ...]] = []
    inner = snake_order(dims[1:])
    for i in range(dims[0]):
        walk = inner if i % 2 == 0 else list(reversed(inner))
        out.extend((i,) + rest for rest in walk)
    return out
