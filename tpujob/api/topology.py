"""TPU slice topology math.

The reference operator assumes 1 process = 1 pod = 1 rank and computes
``WORLD_SIZE = Σ replicas`` (``pkg/controller.v1/pytorch/pod.go:252,267-274``).
On TPU that arithmetic changes: a job runs on a *slice*; each host pod runs
one XLA process that owns ``devices_per_host`` chips, so

    num_processes      = hosts × num_slices          (JAX process world)
    global_devices     = devices × num_slices        (XLA device world)

This module owns that mapping: accelerator-type parsing ("v4-32"),
chip-grid topology strings ("2x2x4"), host counts, device counts, and the
(slice, host) → process-id function used by the controller's environment
injection (the TPU-native replacement for ``setClusterSpec``).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


class TopologyError(ValueError):
    pass


@dataclass(frozen=True)
class Generation:
    name: str
    cores_per_chip: int  # TensorCores counted by the accelerator-type suffix
    chips_per_host: int  # chips attached to one host VM
    devices_per_chip: int  # PJRT devices exposed per chip (megacore => 1)
    topology_dims: int  # 2 for v5e-style 2D ICI mesh, 3 for v4/v5p torus


# Known TPU generations.  The accelerator-type suffix counts TensorCores for
# v2-v4/v5p (so v4-8 is 4 chips / 1 host) and chips for the "lite" parts.
GENERATIONS: Dict[str, Generation] = {
    "v2": Generation("v2", cores_per_chip=2, chips_per_host=4, devices_per_chip=2, topology_dims=2),
    "v3": Generation("v3", cores_per_chip=2, chips_per_host=4, devices_per_chip=2, topology_dims=2),
    "v4": Generation("v4", cores_per_chip=2, chips_per_host=4, devices_per_chip=1, topology_dims=3),
    "v5p": Generation("v5p", cores_per_chip=2, chips_per_host=4, devices_per_chip=1, topology_dims=3),
    "v5litepod": Generation(
        "v5litepod", cores_per_chip=1, chips_per_host=8, devices_per_chip=1, topology_dims=2
    ),
    "v5e": Generation("v5e", cores_per_chip=1, chips_per_host=8, devices_per_chip=1, topology_dims=2),
    "v6e": Generation("v6e", cores_per_chip=1, chips_per_host=8, devices_per_chip=1, topology_dims=2),
}


def parse_accelerator(accelerator: str) -> Tuple[Generation, int]:
    """Parse an accelerator type like ``v4-32`` into (generation, suffix)."""
    if not accelerator or "-" not in accelerator:
        raise TopologyError(f"invalid accelerator type {accelerator!r}; want e.g. 'v4-32'")
    name, _, suffix_s = accelerator.rpartition("-")
    gen = GENERATIONS.get(name)
    if gen is None:
        raise TopologyError(
            f"unknown TPU generation {name!r} in {accelerator!r}; known: {sorted(GENERATIONS)}"
        )
    try:
        suffix = int(suffix_s)
    except ValueError:
        raise TopologyError(f"invalid accelerator size {suffix_s!r} in {accelerator!r}")
    if suffix <= 0 or suffix % gen.cores_per_chip != 0:
        raise TopologyError(
            f"accelerator size {suffix} not a positive multiple of "
            f"{gen.cores_per_chip} for generation {gen.name}"
        )
    return gen, suffix


def parse_topology(topology: str) -> Tuple[int, ...]:
    """Parse a chip-grid string like ``2x2x4`` into dims."""
    try:
        dims = tuple(int(d) for d in topology.lower().split("x"))
    except ValueError:
        raise TopologyError(f"invalid topology {topology!r}; want e.g. '2x2x4'")
    if not dims or any(d <= 0 for d in dims):
        raise TopologyError(f"invalid topology {topology!r}; dims must be positive")
    return dims


def default_topology(chips: int, ndims: int) -> str:
    """A near-balanced ndims-factorization of `chips`, e.g. 16,3 -> '2x2x4'."""
    dims = [1] * ndims
    remaining = chips
    # peel off prime factors largest-first onto the currently-smallest dim
    factors: List[int] = []
    n = remaining
    d = 2
    while d * d <= n:
        while n % d == 0:
            factors.append(d)
            n //= d
        d += 1
    if n > 1:
        factors.append(n)
    for f in sorted(factors, reverse=True):
        dims[dims.index(min(dims))] *= f
    return "x".join(str(d) for d in sorted(dims))


@dataclass(frozen=True)
class SliceTopology:
    """Resolved topology for one job: the source of all rank arithmetic."""

    accelerator: str  # e.g. "v4-32"
    topology: str  # chip grid, e.g. "2x2x4"
    chips: int  # chips per slice
    hosts: int  # host VMs (= worker pods) per slice
    chips_per_host: int
    devices_per_chip: int
    num_slices: int = 1  # >1 => multislice over DCN

    # -- derived ------------------------------------------------------------
    @property
    def devices_per_host(self) -> int:
        return self.chips_per_host * self.devices_per_chip

    @property
    def devices_per_slice(self) -> int:
        return self.chips * self.devices_per_chip

    @property
    def global_devices(self) -> int:
        return self.devices_per_slice * self.num_slices

    @property
    def num_processes(self) -> int:
        """JAX/PJRT process world size (one process per host per slice)."""
        return self.hosts * self.num_slices

    def process_id(self, slice_id: int, host_index: int) -> int:
        """Global process id for host `host_index` of slice `slice_id`."""
        if not (0 <= slice_id < self.num_slices):
            raise TopologyError(f"slice_id {slice_id} out of range [0,{self.num_slices})")
        if not (0 <= host_index < self.hosts):
            raise TopologyError(f"host_index {host_index} out of range [0,{self.hosts})")
        return slice_id * self.hosts + host_index

    def host_of_process(self, process_id: int) -> Tuple[int, int]:
        if not (0 <= process_id < self.num_processes):
            raise TopologyError(f"process_id {process_id} out of range [0,{self.num_processes})")
        return divmod(process_id, self.hosts)

    @classmethod
    def resolve(
        cls,
        accelerator: str,
        topology: Optional[str] = None,
        chips_per_host: Optional[int] = None,
        num_slices: int = 1,
    ) -> "SliceTopology":
        """Resolve a full SliceTopology from (partially-specified) spec fields."""
        gen, suffix = parse_accelerator(accelerator)
        chips = suffix // gen.cores_per_chip
        cph = chips_per_host or min(gen.chips_per_host, chips)
        if chips % cph != 0:
            raise TopologyError(
                f"{accelerator}: {chips} chips not divisible by chipsPerHost={cph}"
            )
        if topology:
            dims = parse_topology(topology)
            if math.prod(dims) != chips:
                raise TopologyError(
                    f"topology {topology} has {math.prod(dims)} chips but "
                    f"{accelerator} is a {chips}-chip slice"
                )
        else:
            topology = default_topology(chips, gen.topology_dims)
        if num_slices < 1:
            raise TopologyError(f"numSlices must be >= 1, got {num_slices}")
        return cls(
            accelerator=accelerator,
            topology=topology,
            chips=chips,
            hosts=max(1, chips // cph),
            chips_per_host=cph,
            devices_per_chip=gen.devices_per_chip,
            num_slices=num_slices,
        )
