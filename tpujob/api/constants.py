"""API group constants and well-known names for the TPUJob CRD.

Mirrors the capability of reference ``pkg/apis/pytorch/v1/constants.go:26-33``
and ``register.go:31-44``, re-targeted at TPU workloads.
"""

# --- group/version/kind (register.go equivalents) --------------------------
GROUP_NAME = "tpujob.dev"
VERSION = "v1"
KIND = "TPUJob"
PLURAL = "tpujobs"
SINGULAR = "tpujob"
API_VERSION = f"{GROUP_NAME}/{VERSION}"

# --- defaults (constants.go equivalents) ------------------------------------
# Name of the port exposed by the coordinator (master) container.  The
# reference used "pytorchjob-port"/23456 for torch TCP rendezvous
# (constants.go:26-33); on TPU the rendezvous is the JAX/PJRT distributed
# coordinator service, conventionally port 8476.
DEFAULT_PORT_NAME = "tpujob-port"
DEFAULT_PORT = 8476
# The container the operator manages (reference: "pytorch").
DEFAULT_CONTAINER_NAME = "tpu"
DEFAULT_RESTART_POLICY = "OnFailure"
DEFAULT_CLEAN_POD_POLICY = "None"

# --- replica types ----------------------------------------------------------
REPLICA_TYPE_MASTER = "Master"
REPLICA_TYPE_WORKER = "Worker"

# --- labels stamped on pods/services (controller.go:55-59 equivalents) ------
LABEL_GROUP_NAME = "group-name"
LABEL_JOB_NAME = "tpu-job-name"
LABEL_REPLICA_TYPE = "tpu-replica-type"
LABEL_REPLICA_INDEX = "tpu-replica-index"
# legacy-style selector label also set by the reference ("job-name")
LABEL_JOB_NAME_SHORT = "job-name"

# --- TPU resource names -----------------------------------------------------
TPU_RESOURCE = "google.com/tpu"
TPU_ACCELERATOR_NODE_SELECTOR = "cloud.google.com/gke-tpu-accelerator"
TPU_TOPOLOGY_NODE_SELECTOR = "cloud.google.com/gke-tpu-topology"

# --- condition types (kubeflow/common types.go:101-127 equivalents) ---------
JOB_CREATED = "Created"
JOB_QUEUED = "Queued"  # gang scheduler: waiting for all-or-nothing admission
JOB_RUNNING = "Running"
JOB_RESTARTING = "Restarting"
JOB_RESIZING = "Resizing"  # elastic resize (staged drain/join) in flight
JOB_STALLED = "Stalled"  # progress watchdog: workload heartbeats stopped
JOB_SUCCEEDED = "Succeeded"
JOB_FAILED = "Failed"

# --- restart policies (types.go:145-156) ------------------------------------
RESTART_POLICY_ALWAYS = "Always"
RESTART_POLICY_ON_FAILURE = "OnFailure"
RESTART_POLICY_NEVER = "Never"
RESTART_POLICY_EXIT_CODE = "ExitCode"

# --- clean pod policies (types.go:130-137) ----------------------------------
CLEAN_POD_POLICY_NONE = "None"
CLEAN_POD_POLICY_RUNNING = "Running"
CLEAN_POD_POLICY_ALL = "All"

# --- gang scheduling ---------------------------------------------------------
DEFAULT_GANG_SCHEDULER_NAME = "volcano"
POD_GROUP_ANNOTATION = "scheduling.k8s.io/group-name"

# --- elastic resize: the world-size publication channel ----------------------
# Pod env (TPUJOB_NUM_PROCESSES) is bootstrap-only — it names the world the
# pod was BORN into and cannot change without a restart, which is exactly
# what an elastic resize must avoid.  The live world is published on job
# annotations instead (a real pod reads them through a downward-API mount;
# the in-process harness reads the job object):
#
# - WORLD_SIZE: the world size currently in effect — every live replica has
#   rendezvoused (or must re-rendezvous) at this size.  Written only by the
#   controller, only after the join/drain staging completed.
# - TARGET_WORLD_SIZE: a pending resize's destination, published BEFORE any
#   drain deletion so the workload can hit a checkpoint barrier first.
# - RESIZE_GENERATION: bumped on every completed resize — the workload's
#   cheap change detector.
# - CHECKPOINT_ACK: written by the WORKLOAD (coordinator process): the
#   target world size it has checkpointed for.  The controller's drain
#   barrier waits for this ack (bounded by the drain grace period).
ANNOTATION_WORLD_SIZE = f"{GROUP_NAME}/world-size"
ANNOTATION_TARGET_WORLD_SIZE = f"{GROUP_NAME}/target-world-size"
ANNOTATION_RESIZE_GENERATION = f"{GROUP_NAME}/resize-generation"
ANNOTATION_CHECKPOINT_ACK = f"{GROUP_NAME}/checkpoint-ack"

# --- workload telemetry: the progress-heartbeat channel ----------------------
# Written by the WORKLOAD (coordinator process) on its OWN pod, rate-limited
# and merge-patched so it composes with every other annotation writer: a
# compact `step=N sps=F ckpt=N gen=N t=T` record of training progress (see
# tpujob.api.progress for the exact grammar).  The controller ingests it from
# its informer cache — the reverse direction of the world-size channel above,
# and the signal the Stalled-job watchdog and the tpujob_job_* metric
# families are built on.
ANNOTATION_PROGRESS = f"{GROUP_NAME}/progress"

# --- native gang scheduler: the admission/preemption channel -----------------
# The scheduler's durable state lives on job annotations, exactly like the
# elastic-resize staging record lives in status: every decision is resumable
# across controller crash and shard handoff because the NEXT tick re-derives
# the capacity model from what is already committed.
#
# - SCHED_ASSIGNMENT: JSON placement record written at admission (which
#   slices, which torus-adjacent host ranges).  Present = the gang HOLDS its
#   modeled capacity.  All-or-nothing by construction: the record always
#   covers the whole gang or is absent.
# - SCHED_EVICTED: eviction marker (ISO timestamp).  assignment+evicted =
#   the gang is being vacated — the reconciler's admission gate deletes its
#   pods (not failure strikes) while the scheduler keeps the capacity
#   reserved until the last pod is gone, so a re-admission can never be
#   placed onto hosts the victim still occupies.
# - PREEMPT_TARGET: preemption staged (ISO timestamp of the publish) — the
#   workload should checkpoint NOW; the scheduler waits for the ack (or the
#   telemetry checkpoint catching up to the step, or the bounded grace)
#   before writing the eviction marker.  The PR-9 drain protocol, re-aimed:
#   publish target, wait the checkpoint barrier, then evict.
# - PREEMPT_ACK: written by the WORKLOAD (coordinator): the preemption
#   checkpoint barrier is hit; evict away.  Separate from CHECKPOINT_ACK so
#   the resize machinery's ack-consumption can never race a preemption.
ANNOTATION_SCHED_ASSIGNMENT = f"{GROUP_NAME}/sched-assignment"
ANNOTATION_SCHED_EVICTED = f"{GROUP_NAME}/sched-evicted"
ANNOTATION_PREEMPT_TARGET = f"{GROUP_NAME}/preempt-target"
ANNOTATION_PREEMPT_ACK = f"{GROUP_NAME}/preempt-ack"

# --- elastic capacity: num_slices flex ---------------------------------------
# Under pressure the scheduler SHRINKS a running low-tier multislice gang by
# whole slices (through the staged-resize drain barrier — zero failure
# strikes) instead of evicting it, and a background grower flexes it back
# into idle capacity.  Both decisions are durable-by-annotation like every
# other scheduler protocol:
#
# - FLEX_SLICES: written by the SCHEDULER — the slice count the gang is
#   currently flexed to (strictly less than spec num_slices while shrunk;
#   cleared when the grower restores the full spec shape).  The reconciler's
#   flex staging gate clamps the Worker replica count to this value, which
#   drives the ordinary staged drain/join machinery.
# - MIN_SLICES: optional per-job override of spec.runPolicy.
#   schedulingPolicy.minSlices — the floor below which the scheduler must
#   preempt rather than flex (a job that cannot make progress under N
#   slices declares it here).
ANNOTATION_FLEX_SLICES = f"{GROUP_NAME}/flex-slices"
ANNOTATION_MIN_SLICES = f"{GROUP_NAME}/min-slices"

# --- node inventory & fleet repair -------------------------------------------
# Nodes are a first-class resource: each Node object names one TPU host VM
# (its slice pool, slice index and torus host coordinate) and carries a
# heartbeat lease.  The scheduler's CapacityModel is rebuilt from the live
# Node informer cache each tick; `--sched-capacity` becomes a bootstrap
# fallback that SYNTHESIZES Node objects so modeled fleets keep working.
#
# - NODE_HEARTBEAT: the node agent's liveness lease, bumped on the node's own
#   object.  Staleness is judged on the CONTROLLER's monotonic clock (the
#   PR-10 watchdog stance); a node that has never heartbeated is judged by
#   its durable status alone (synthesized fleets never die by silence).
# - NODE_CORDONED ("tpujob.dev/unschedulable"): operator cordon marker — the
#   host is excluded from placement and its gangs are migrated, exactly like
#   a dead host but human-initiated and instantly reversible.
# - NODE_TAINT: durable record of WHY the node is NotReady/cordoned, written
#   by the scheduler duty when it flips the node's phase.
# - MIGRATED_FROM (on TPUJobs): the host(s) a scheduled migration vacated a
#   gang from — set when the migration's preempt-target publishes, cleared
#   with the assignment on release.
ANNOTATION_NODE_HEARTBEAT = f"{GROUP_NAME}/heartbeat"
ANNOTATION_NODE_CORDONED = f"{GROUP_NAME}/unschedulable"
ANNOTATION_NODE_TAINT = f"{GROUP_NAME}/taint"
ANNOTATION_MIGRATED_FROM = f"{GROUP_NAME}/migrated-from"
# marks Node objects synthesized from the --sched-capacity bootstrap string
LABEL_NODE_SYNTHESIZED = f"{GROUP_NAME}/synthesized"
NODE_READY = "Ready"
NODE_NOT_READY = "NotReady"

# --- multi-cluster federation ------------------------------------------------
# The federation meta-controller treats each member cluster's API server as
# one more shard of the control plane: job ownership is CLUSTER-granular,
# assigned once by the federation duty owner for that cluster and durable on
# the job object itself (annotations survive every controller restart; the
# meta store only mirrors them).  All federation writes into a cluster are
# fenced on that cluster's own federation duty lease — a deposed duty
# owner's stale token is rejected server-side, never merged.
#
# - CLUSTER: THE ownership record — the name of the exactly-one cluster
#   that owns this job.  Written once at placement by the federation duty
#   owner; rewritten only through the two-phase transfer (spillover) or a
#   dark-cluster failover.  A member whose --cluster-name does not match
#   holds the job dark: no pods, no failure strikes.
# - CLUSTER_TRANSFER: the in-flight transfer marker (value = target
#   cluster) — phase 1 of the two-phase spillover stamps it on the source
#   copy so BOTH copies agree on the owner mid-transfer and an interrupted
#   transfer resumes instead of forking.
# - FAILED_OVER_FROM: durable provenance on a job re-placed off a dark
#   cluster (value = the cluster that went dark) — the re-created object
#   starts with fresh status (zero counted restarts; the workload restores
#   from its last checkpoint barrier).
ANNOTATION_CLUSTER = f"{GROUP_NAME}/cluster"
ANNOTATION_CLUSTER_TRANSFER = f"{GROUP_NAME}/cluster-transfer"
ANNOTATION_FAILED_OVER_FROM = f"{GROUP_NAME}/failed-over-from"
# durable cluster phases recorded in the federation meta store (the
# NodeHealth stance at cluster granularity: NotReady is a written verdict,
# never an inference replayed from a stale cache)
CLUSTER_READY = "Ready"
CLUSTER_NOT_READY = "NotReady"
